"""DistributedOptimizer: gradient averaging injected into an optimizer.

Rebuild of the reference's framework optimizer wrappers:
``horovod/torch/__init__.py:65-198`` (``_DistributedOptimizer`` with
per-parameter hooks and ``backward_passes_per_step`` accumulation) and
``horovod/tensorflow/__init__.py:151-249`` (``compute_gradients`` override).
The JAX-native form is an ``optax.GradientTransformation`` wrapper: gradient
averaging happens at ``update()`` time, before the inner optimizer sees the
gradients.

Two modes, matching ``ops``:

* **SPMD** (``axis_name=...``): for train steps compiled with
  ``pjit``/``shard_map`` over a mesh — the averaging is a ``lax.pmean`` that
  XLA schedules and fuses on ICI. This is the TPU hot path; there is no
  engine, no host hop, and XLA's all-reduce combiner plays the role of the
  reference's fusion buffer (``HOROVOD_FUSION_THRESHOLD``).
* **Eager** (default): concrete per-process gradients are submitted to the
  background engine as named tensors — one async allreduce per leaf,
  synchronized together, which exercises the same fusion path the reference
  drives from its gradient hooks (``torch/__init__.py:95-130``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from . import basics, ops
from .core.logging import LOG
from .ops.compression import Compression

# Build-time knob resolutions made BEFORE hvd.init() (env reads).
# init() audits these against the pinned config: a step traced before init
# keeps its build-time routing/codec forever, so a divergence would
# otherwise be silent (see check_build_time_resolutions).
_prebuild_hierarchical_resolutions: list = []
_prebuild_compression_resolutions: list = []


def _use_hierarchical(axis_name, hierarchical) -> bool:
    if hierarchical is not None:
        return hierarchical
    if isinstance(axis_name, str) or axis_name is None or \
            len(tuple(axis_name)) != 2:
        return False
    # HOROVOD_HIERARCHICAL_ALLREDUCE knob, as in the reference
    # (operations.cc:1880-1890). Resolution must not depend on init order:
    # make_dp_train_step consults this at BUILD time to pick check_vma, and
    # a step built before hvd.init() would otherwise silently lose the
    # factored route (vma tracking pre-psums the cotangents). Initialized
    # worlds use the pinned config; otherwise read the env directly.
    if basics.is_initialized():
        return basics.config().hierarchical_allreduce
    from .core.config import Config

    resolved = Config.from_env().hierarchical_allreduce
    _prebuild_hierarchical_resolutions.append(resolved)
    return resolved


def _resolve_compression(compression, record: bool = False):
    """``compression=None`` means "follow the HOROVOD_COMPRESSION knob"
    (``core.config``): initialized worlds use the pinned config; before
    ``hvd.init()`` the env is read directly — same build-time semantics
    as the hierarchical knob (a step traced before init keeps its
    build-time codec). An explicit ``Compression.*`` argument always
    wins. ``record=True`` registers a pre-init resolution for the
    ``check_build_time_resolutions`` audit — set only by the reduction
    sites that actually bake the codec into a traced step, so ad-hoc
    resolutions (tests, introspection) cannot trigger spurious
    stale-codec warnings at the next init."""
    if compression is not None:
        return compression
    if basics.is_initialized():
        name = basics.config().compression
    else:
        from .core.config import Config

        name = Config.from_env().compression
        if record:
            _prebuild_compression_resolutions.append(name)
    return Compression.lookup(name)


def check_build_time_resolutions(cfg) -> None:
    """Called by ``hvd.init()``: warn when a step traced before init
    resolved the hierarchical knob differently from the now-pinned config
    (env changed between build and init, or ``init(config=...)`` overrode
    it). The traced step silently keeps its build-time behavior — XLA has
    already baked the collective routing in — so the only honest remedy is
    to rebuild the step or align the config."""
    stale = {v for v in _prebuild_hierarchical_resolutions
             if v != cfg.hierarchical_allreduce}
    stale_codecs = {v for v in _prebuild_compression_resolutions
                    if v != cfg.compression}
    # Consume the audited entries: a later shutdown/re-init must only audit
    # steps built since THIS init, not re-warn about ones already reported
    # (which may have been rebuilt by then).
    _prebuild_hierarchical_resolutions.clear()
    _prebuild_compression_resolutions.clear()
    if stale:
        built = "ON" if True in stale else "off"
        pinned = "ON" if cfg.hierarchical_allreduce else "off"
        LOG.warning(
            "a train step was built before hvd.init() with hierarchical "
            "allreduce %s, but the initialized world pins it %s. Steps "
            "traced before init keep their build-time collective routing; "
            "rebuild them after init (or align "
            "HOROVOD_HIERARCHICAL_ALLREDUCE / init(config=...)) so the "
            "routing matches the pinned config.", built, pinned)
    if stale_codecs:
        LOG.warning(
            "a train step was built before hvd.init() with compression "
            "codec %s (HOROVOD_COMPRESSION), but the initialized world "
            "pins %r. Steps traced before init keep their build-time "
            "wire codec; rebuild them after init (or align the env / "
            "init(config=...)) so the wire matches the pinned config.",
            "/".join(sorted(stale_codecs)), cfg.compression)


def allreduce_gradients(grads: Any, axis_name=None, average: bool = True,
                        compression=None,
                        hierarchical: Optional[bool] = None) -> Any:
    """Average a gradient pytree across the world.

    The DistributedGradientTape analog
    (``tensorflow/__init__.py:252-326``): apply to any grads pytree before
    feeding an optimizer. With a two-axis ``axis_name`` (dcn, ici) and
    ``hierarchical`` (or ``HOROVOD_HIERARCHICAL_ALLREDUCE``), varying
    gradients take the factored reduce_scatter/allreduce/all_gather route
    of ``parallel.hierarchical``. ``compression=None`` follows the
    ``HOROVOD_COMPRESSION`` knob; a quantized codec (``Compression.int8``
    / ``.fp8``) moves the collective bytes as block-quantized wire — on
    the hierarchical route only the DCN hop is quantized (the EQuARX
    design point)."""
    compression = _resolve_compression(compression, record=True)
    quantized = bool(getattr(compression, "quantized", False))
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if axis_name is not None:
        if _use_hierarchical(axis_name, hierarchical):
            from .ops.spmd import _varies_over, _vma_tracking_active
            from .parallel.hierarchical import hierarchical_grad_allreduce

            dcn_axis, ici_axis = tuple(axis_name)
            # The factored route applies when the gradient still needs
            # cross-device summing: a varying cotangent under vma tracking,
            # or ANY cotangent under legacy tracing (check_vma=False, where
            # shard_map does not auto-psum transposes — the mode a
            # hierarchical step should be built in, because vma tracking
            # pre-sums replicated-param grads with a flat whole-mesh psum
            # before this transform ever sees them, silencing the knob).
            legacy = not _vma_tracking_active(axis_name)
            reduced = []
            factored_leaves = 0
            for g in leaves:
                comp, ctx = compression.compress(g)
                if legacy or _varies_over(comp, axis_name):
                    factored_leaves += 1
                    red = hierarchical_grad_allreduce(
                        comp, dcn_axis, ici_axis, average=average,
                        codec=compression if quantized else None)
                else:
                    # pre-summed cotangent (see ops.spmd.allreduce)
                    red = ops.spmd.allreduce(comp, axis_name, average=average)
                reduced.append(compression.decompress(red, ctx))
            if leaves and not factored_leaves:
                # The knob is ON but every cotangent arrived pre-summed by
                # vma tracking's flat whole-mesh psum — the factored
                # reduce_scatter/psum/all_gather route never fires. Runs at
                # trace time, so this warns once per trace, not per step.
                source = ("hierarchical=True" if hierarchical
                          else "HOROVOD_HIERARCHICAL_ALLREDUCE")
                LOG.warning(
                    "hierarchical allreduce is enabled (via %s) but every "
                    "gradient leaf arrived pre-summed (vma tracking inserts "
                    "a flat whole-mesh psum in the shard_map transpose), so "
                    "the factored hierarchical route is inert for this "
                    "step. Build the step with shard_map(..., "
                    "check_vma=False) so cotangents reach the optimizer "
                    "unsummed (see benchmarks/_dp_step.py).", source)
            return jax.tree_util.tree_unflatten(treedef, reduced)
        reduced = [
            ops.allreduce(g, average=average, compression=compression,
                          axis_name=axis_name)
            for g in leaves
        ]
        return jax.tree_util.tree_unflatten(treedef, reduced)
    # Eager: submit all leaves asynchronously first so the engine can fuse
    # them into buckets (the reference's gradient hooks achieve the same
    # arrival pattern), then synchronize in order.
    handles = [
        ops.allreduce_async(g, average=average,
                            name=f"DistributedOptimizer.grad.{i}",
                            compression=compression)
        for i, g in enumerate(leaves)
    ]
    reduced = [ops.synchronize(h) for h in handles]
    return jax.tree_util.tree_unflatten(treedef, reduced)


class DistributedOptState(NamedTuple):
    inner: Any
    accum: Any  # gradient accumulator (backward_passes_per_step > 1) or None
    counter: jnp.ndarray  # passes since last allreduce+apply


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *,
                         axis_name=None,
                         compression=None,
                         average: bool = True,
                         backward_passes_per_step: int = 1,
                         hierarchical: Optional[bool] = None,
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates are computed from world-averaged
    gradients. ``backward_passes_per_step`` accumulates N passes locally
    before one allreduce + one inner update, exactly the delay-counter
    semantics of ``torch/__init__.py:71-73,114-130``.
    ``compression=None`` follows ``HOROVOD_COMPRESSION`` (resolved per
    reduction, so a step traced after ``hvd.init()`` sees the pinned
    config); pass ``hvd.Compression.int8`` (or fp16/bf16/fp8) to pin a
    codec explicitly."""
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    n_acc = backward_passes_per_step

    def init_fn(params):
        accum = None
        if n_acc > 1:
            accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return DistributedOptState(
            inner=optimizer.init(params),
            accum=accum,
            counter=jnp.zeros((), jnp.int32),
        )

    def _reduce(grads):
        return allreduce_gradients(grads, axis_name=axis_name,
                                   average=average, compression=compression,
                                   hierarchical=hierarchical)

    def update_fn(grads, state, params=None):
        if n_acc == 1:
            reduced = _reduce(grads)
            updates, inner = optimizer.update(reduced, state.inner, params)
            return updates, DistributedOptState(inner, None, state.counter)

        accum = jax.tree_util.tree_map(jnp.add, state.accum, grads)
        counter = state.counter + 1
        if axis_name is None:
            # Eager path: concrete values, python control flow.
            if int(counter) >= n_acc:
                reduced = _reduce(accum)
                updates, inner = optimizer.update(reduced, state.inner, params)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
                return updates, DistributedOptState(
                    inner, zeros, jnp.zeros((), jnp.int32))
            updates = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return updates, DistributedOptState(state.inner, accum, counter)

        # SPMD path: compiled control flow.
        def sync_branch(operand):
            accum_, inner_, params_ = operand
            reduced = _reduce(accum_)
            updates, new_inner = optimizer.update(reduced, inner_, params_)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum_)
            return updates, new_inner, zeros, jnp.zeros((), jnp.int32)

        def accum_branch(operand):
            accum_, inner_, _params_ = operand
            updates = jax.tree_util.tree_map(jnp.zeros_like, accum_)
            return updates, inner_, accum_, counter

        updates, inner, accum, counter = lax.cond(
            counter >= n_acc, sync_branch, accum_branch,
            (accum, state.inner, params))
        return updates, DistributedOptState(inner, accum, counter)

    # Tag for is_distributed(): GradientTransformation is a plain NamedTuple
    # (no instance attributes), so the marker rides on the update function.
    update_fn._horovod_distributed = True
    # Fused reduce+apply threading (docs/tensor-fusion.md §fused apply):
    # when the inner optimizer is one of the fusable rules
    # (hvd.fused_sgd/fused_momentum/fused_adam), carry the rule and the
    # wrap's routing knobs so apply_step can hand the whole
    # reduce→unscale→update chain to the engine as ONE program under
    # HOROVOD_FUSED_APPLY=1.
    from .ops.fused_apply import rule_of as _rule_of

    update_fn._horovod_apply_rule = _rule_of(optimizer)
    update_fn._horovod_apply_meta = {
        "axis_name": axis_name, "average": average,
        "compression": compression, "n_acc": n_acc,
    }
    return optax.GradientTransformation(init_fn, update_fn)


def is_distributed(tx: optax.GradientTransformation) -> bool:
    """True if ``tx`` was produced by :func:`DistributedOptimizer` (used by
    the front-ends to refuse double wrapping)."""
    return bool(getattr(tx.update, "_horovod_distributed", False))


def _fused_apply_armed() -> bool:
    """The ``HOROVOD_FUSED_APPLY`` opt-in, resolved like the other
    build-time knobs: pinned config once initialized, env before."""
    if basics.is_initialized():
        return basics.config().fused_apply
    from .core.config import Config

    return Config.from_env().fused_apply


def _zero1_armed() -> bool:
    """The ``HOROVOD_ZERO`` opt-in (docs/sharding.md), resolved exactly
    like :func:`_fused_apply_armed`; capability (XLA plane, world > 1)
    is the engine's call via ``ops.zero1_active``."""
    from .sharding.zero1 import armed

    return armed()


def apply_step(tx: optax.GradientTransformation, grads: Any, state: Any,
               params: Any):
    """One distributed optimizer step that LANDS applied parameters:
    ``(new_params, new_state) = apply_step(tx, grads, state, params)``.

    ``tx`` must be a :func:`DistributedOptimizer`. Two routes, bit-exact
    to each other by the shared :mod:`ops.fused_apply` rule math
    (certified by ``dryrun_fused_apply``):

    * **two-dispatch** (default): the classic pair — allreduce the
      gradients through ``tx.update``, then ``optax.apply_updates`` —
      one reduce dispatch plus per-leaf apply dispatches.
    * **apply-fused** (``HOROVOD_FUSED_APPLY=1``, eager path, inner
      optimizer from :func:`~horovod_tpu.fused_sgd` /
      :func:`~horovod_tpu.fused_momentum` / :func:`~horovod_tpu.fused_adam`):
      each leaf rides an apply-capable allreduce and the engine's flush
      returns the applied parameter and fresh optimizer slots from one
      fused reduce+apply program per batch (docs/tensor-fusion.md
      §fused apply) — the reduce→apply device round trip is gone, and
      the PR 9 sub-buffer overlap window covers the update math too.

    The SPMD path (``axis_name=``) always takes the two-dispatch form
    here — inside jit XLA already fuses the chain; see
    :func:`ops.spmd.reduce_apply` for the explicit in-program fusion."""
    if not is_distributed(tx):
        raise ValueError(
            "apply_step needs a DistributedOptimizer-wrapped transform")
    meta = getattr(tx.update, "_horovod_apply_meta", None) or {}
    rule = getattr(tx.update, "_horovod_apply_rule", None)
    comp = _resolve_compression(meta.get("compression"))
    # cast codecs (fp16/bf16) change the wire dtype pre-submit — the
    # f32 apply bucket cannot carry them, so they keep the two-dispatch
    # path; quantized codecs decode INSIDE the fused program (EQuARX)
    quantized_ok = comp is Compression.none or \
        getattr(comp, "quantized", False)
    fusable = rule is not None and meta.get("axis_name") is None and \
        meta.get("n_acc", 1) == 1 and quantized_ok
    if fusable and _fused_apply_armed():
        from .ops import apply_synchronize, fused_apply_async, \
            zero1_active
        from .ops.fused_apply import FusedApplyState

        inner = state.inner
        count_next = int(inner.count) + 1
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_flatten(params)[0]
        # ZeRO-1 (docs/sharding.md): when the engine armed the sharded
        # plane, optimizer slots live as this rank's ShardLeaf shards —
        # localized lazily on the first armed step (init_fn still builds
        # full zeros; elastic restore re-cuts whatever world committed),
        # and the engine runs reduce-scatter → shard apply → all-gather
        # instead of the replicated reduce+apply. Parameters land fully
        # replicated and bit-exact either way.
        z1 = _zero1_armed() and zero1_active()
        slot_trees = inner.slots
        if z1:
            from .sharding import zero1 as _z1

            if slot_trees and not _z1.has_shards(slot_trees):
                slot_trees = tuple(
                    _z1.localize_tree(s, basics.size(), basics.rank())
                    for s in slot_trees)
            _z1.note_slot_residency(slot_trees)
            shard_cols = [jax.tree_util.tree_flatten(
                s, is_leaf=_z1.is_shard)[0] for s in slot_trees]
            slot_leaves = [[sl.data for sl in col]
                           for col in shard_cols]
        else:
            slot_leaves = [jax.tree_util.tree_flatten(s)[0]
                           for s in slot_trees]
        handles = [
            fused_apply_async(
                g, p_leaves[i], tuple(s[i] for s in slot_leaves), rule,
                count_next, name=f"DistributedOptimizer.apply.{i}",
                average=meta.get("average", True), compression=comp,
                zero1=z1)
            for i, g in enumerate(leaves)]
        outs = [apply_synchronize(h) for h in handles]
        unflatten = jax.tree_util.tree_unflatten
        new_params = unflatten(treedef, [o[0] for o in outs])
        if z1:
            import numpy as _np

            new_slots = tuple(
                unflatten(treedef, [
                    _z1.ShardLeaf(_np.asarray(o[1][k]),
                                  shard_cols[k][i].spec)
                    for i, o in enumerate(outs)])
                for k in range(rule.nslots))
        else:
            new_slots = tuple(
                unflatten(treedef, [o[1][k] for o in outs])
                for k in range(rule.nslots))
        new_inner = FusedApplyState(count=inner.count + 1,
                                    slots=new_slots)
        return new_params, DistributedOptState(
            inner=new_inner, accum=state.accum, counter=state.counter)
    if rule is not None:
        # replicated paths below cannot consume ZeRO-1 shard slots
        # (their shapes are 1/N of each leaf) — reaching them with a
        # sharded state means the knobs or codec changed mid-run
        from .sharding import zero1 as _z1guard

        if _z1guard.has_shards(getattr(state.inner, "slots", ())):
            raise RuntimeError(
                "ZeRO-1 sharded optimizer state cannot take the "
                "replicated two-dispatch path; keep HOROVOD_ZERO=1 "
                "runs on a fusable configuration (dense or quantized "
                "codec, HOROVOD_FUSED_APPLY=1)")
    if fusable:
        # the two-dispatch REFERENCE path: one reduce dispatch (summed
        # wire, the fused plane's exact input), then one jitted apply
        # program per leaf from the SAME bucket_apply_fn family the
        # engine compiles — average divide in-program — so fused vs
        # two-dispatch is bit-exact by construction (the
        # dryrun_fused_apply certification). The optax-compatible
        # tx.update surface below remains for generic inner optimizers;
        # its eager apply_updates add lands within 1 ulp of these
        # in-program chains (XLA fuses mul+add differently there).
        from .ops.engine import _APPLY_DISPATCHES
        from .ops.fused_apply import FusedApplyState, bucket_apply_fn

        reduced = allreduce_gradients(
            grads, axis_name=None, average=False, compression=comp)
        inner = state.inner
        count_next = int(inner.count) + 1
        denom = basics.size() if meta.get("average", True) else 1
        fn = bucket_apply_fn(rule, False, denom)
        leaves, treedef = jax.tree_util.tree_flatten(reduced)
        p_leaves = jax.tree_util.tree_flatten(params)[0]
        slot_leaves = [jax.tree_util.tree_flatten(s)[0]
                       for s in inner.slots]
        new_p, new_slot_cols = [], [[] for _ in range(rule.nslots)]
        import numpy as _np

        for i, g in enumerate(leaves):
            out = fn(g, p_leaves[i], _np.int32(count_next),
                     *(s[i] for s in slot_leaves))
            # one standalone apply dispatch per leaf — the cost the
            # fused plane folds into the reduce (the dispatches-per-step
            # story, docs/tensor-fusion.md §fused apply)
            _APPLY_DISPATCHES.inc()
            new_p.append(out[0])
            for k in range(rule.nslots):
                new_slot_cols[k].append(out[3 + k])
        unflatten = jax.tree_util.tree_unflatten
        new_params = unflatten(treedef, new_p)
        new_slots = tuple(unflatten(treedef, c) for c in new_slot_cols)
        new_inner = FusedApplyState(count=inner.count + 1,
                                    slots=new_slots)
        return new_params, DistributedOptState(
            inner=new_inner, accum=state.accum, counter=state.counter)
    updates, new_state = tx.update(grads, state, params)
    return optax.apply_updates(params, updates), new_state
