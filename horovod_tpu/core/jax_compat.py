"""Compatibility aliases for JAX API drift.

The codebase targets the current public JAX surface — ``jax.shard_map``
(with ``check_vma=``), ``lax.axis_size`` — while deployment images pin
older releases where ``shard_map`` still lives in ``jax.experimental``
(spelled ``check_rep=``) and ``lax.axis_size`` does not exist yet.
``install()`` bridges the gap by installing the missing names on the jax
modules when (and only when) they are absent, with semantics-preserving
adapters:

* ``jax.shard_map``: the experimental ``shard_map`` with replication
  checking FORCED OFF (``check_rep=False``), whatever the caller passed
  for ``check_vma``. Old rep-tracking pre-sums replicated-param
  cotangents in the transpose *without* exposing the vma value types the
  library keys off (``jax.typeof(x).vma``), so
  ``ops.spmd._vma_tracking_active`` would report legacy semantics while
  the pre-sum still happened — every ``hvd.allreduce`` of a cotangent
  would then double-reduce (the classic 8x-gradient bug
  ``ops.spmd.allreduce`` exists to prevent). With checking off, old
  shard_map neither pre-sums nor type-checks — exactly the "legacy
  tracing" mode the whole library detects and handles correctly.
* ``lax.axis_size``: the static bound-axis size, read from the axis-env
  frame (older JAX returns the frame as the bare int).

Installed at ``import horovod_tpu`` time, before any test/bench module
does ``from jax import shard_map`` — library code, tests, benchmarks and
the driver entry all run unchanged on both JAX generations. Aliases are
only ever ADDED; on a current JAX this module is a no-op.
"""

from __future__ import annotations


def install() -> None:
    import jax
    from jax import lax

    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _shard_map

            def shard_map(f, *args, **kwargs):
                kwargs.pop("check_vma", None)
                kwargs["check_rep"] = False  # see module docstring
                return _shard_map(f, *args, **kwargs)

            shard_map.__doc__ = _shard_map.__doc__
            jax.shard_map = shard_map
        except ImportError:  # pragma: no cover - no shard_map at all
            pass

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            frame = jax.core.axis_frame(axis_name)
            return frame if isinstance(frame, int) else frame.size

        lax.axis_size = axis_size

    if not hasattr(lax, "pcast"):
        # vma re-typing only exists where vma tracking does; this shim
        # only installs on releases WITHOUT it (and jax.shard_map above
        # forces check_rep=False there), where every value is untyped
        # and "cast to varying" is the identity by construction. On a
        # current JAX the real pcast is present and this never installs.
        def pcast(x, axis_name, *, to):
            del axis_name, to
            return x

        lax.pcast = pcast
