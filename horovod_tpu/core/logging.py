"""Stream-style leveled logging, env-controlled.

Rebuild of the reference logger (``horovod/common/logging.{h,cc}``): levels
TRACE..FATAL selected by ``HOROVOD_LOG_LEVEL``, optional timestamp suppression
via ``HOROVOD_LOG_HIDE_TIME`` (``logging.h:35-56``). We implement it on the
stdlib ``logging`` module (one logger per process, stderr handler) rather than
C++ stream macros; the native core (horovod_tpu/cc) logs through the same
format so interleaved output is uniform.
"""

from __future__ import annotations

import logging as _pylogging
import os
import sys

TRACE = 5
_pylogging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": _pylogging.DEBUG,
    "info": _pylogging.INFO,
    "warning": _pylogging.WARNING,
    "error": _pylogging.ERROR,
    "fatal": _pylogging.CRITICAL,
}


def min_log_level_from_env() -> int:
    """Reference: ``MinLogLevelFromEnv`` (``logging.cc``); default WARNING."""
    from .config import HOROVOD_LOG_LEVEL

    raw = os.environ.get(HOROVOD_LOG_LEVEL, "warning").strip().lower()
    return _LEVELS.get(raw, _pylogging.WARNING)


def _build_logger() -> _pylogging.Logger:
    from .config import _env_bool

    logger = _pylogging.getLogger("horovod_tpu")
    logger.setLevel(min_log_level_from_env())
    if not logger.handlers:
        handler = _pylogging.StreamHandler(sys.stderr)
        if _env_bool("HOROVOD_LOG_HIDE_TIME"):
            fmt = "[%(levelname)s] %(message)s"
        else:
            fmt = "%(asctime)s [%(levelname)s] %(message)s"
        handler.setFormatter(_pylogging.Formatter(fmt))
        logger.addHandler(handler)
    logger.propagate = False
    return logger


LOG = _build_logger()


def log_rank(level: int, rank: int, msg: str) -> None:
    """``LOG(severity, rank)`` form from the reference macros."""
    LOG.log(level, "[%d]: %s", rank, msg)
