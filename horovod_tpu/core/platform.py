"""Force JAX onto virtual CPU devices — the "no cluster needed" fixture.

The reference's test fixture is single-process MPI (a self-initialized world
of size 1, SURVEY §4); ours is N virtual XLA CPU devices in one process.
Pinning matters beyond tests: in this environment the experimental TPU
plugin can hang for minutes inside a bare ``jax.devices()`` call, so any
code path that must never touch the real chip (tests, the driver's
multi-chip dryrun) pins the platform first.

The TPU plugin prepends itself to ``JAX_PLATFORMS``, so scrubbing the env
var alone is not enough — the config must also be overridden after import.
Both the env mutation and ``jax.config.update`` take effect as long as no
backend has spun up yet; XLA_FLAGS is read lazily at backend creation.
"""

from __future__ import annotations

import os


def pin_cpu_platform(n_devices: int = 8) -> None:
    """Pin JAX to ``n_devices`` virtual CPU devices, verifying the result.

    Must be called before any JAX backend query (``jax.devices()``,
    ``jax.process_index()``, array creation, ...). Safe to call after
    ``import jax`` itself. If another backend already spun up, the config
    update is a silent no-op in JAX — so this function queries the devices
    it just pinned and raises rather than letting the caller proceed on the
    wrong platform with the wrong device count.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    os.environ.pop("JAX_PLATFORMS", None)
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = " ".join(
            flag if f.startswith("--xla_force_host_platform_device_count")
            else f for f in flags.split())
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu" or len(devices) < n_devices:
        raise RuntimeError(
            f"pin_cpu_platform({n_devices}) failed: JAX reports "
            f"{len(devices)} {devices[0].platform!r} device(s). A backend "
            f"was already initialized before the pin ran — call "
            f"pin_cpu_platform before any jax.devices()/array operation.")


def init_cache_path(config_key, extra_sources=()):
    """Resolve the on-disk host-init cache entry for ``config_key``.

    One shared policy for every bench entry point: the filename carries an
    md5 of the model-zoo sources (``horovod_tpu/models/**/*.py``,
    recursive so a future models/ subpackage invalidates too), the
    caller's own source file(s) (``extra_sources`` — the synthesize/init
    code that actually generates the arrays), and the jax AND flax
    versions (flax initializers generate the cached param values), so
    editing/upgrading any of them invalidates stale entries instead of
    silently measuring them.

    Knob semantics: ``HOROVOD_BENCH_INIT_CACHE=0`` disables (returns "");
    unset/empty/``1`` enable with the default repo-local directory — a
    bare ``1`` is an on/off answer, NOT a relative directory named ``1``;
    any other value overrides the cache directory."""
    import glob
    import hashlib

    from .config import HOROVOD_BENCH_INIT_CACHE

    knob = os.environ.get(HOROVOD_BENCH_INIT_CACHE, "").strip()
    if knob.lower() in ("0", "false", "off"):
        return ""
    import jax

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if knob.lower() in ("", "1", "true", "on"):
        cache_dir = os.path.join(root, ".bench_init_cache")
    else:
        cache_dir = knob
    h = hashlib.md5(jax.__version__.encode())
    try:
        import flax

        h.update(getattr(flax, "__version__", "?").encode())
    except Exception:  # noqa: BLE001 - flax-less callers still get a key
        h.update(b"no-flax")
    sources = sorted(glob.glob(
        os.path.join(root, "horovod_tpu", "models", "**", "*.py"),
        recursive=True))
    sources += [os.path.abspath(s) for s in extra_sources]
    for src in sources:
        with open(src, "rb") as f:
            h.update(f.read())
    return os.path.join(cache_dir, f"{config_key}_{h.hexdigest()[:10]}.pkl")


def host_init_cached(cache_path, make, log=None):
    """Run ``make()`` (host-side model/data init) with an on-disk cache.

    Why: on the shared-tunnel accelerator, healthy windows can be shorter
    than the ~60-90 s a ResNet-class host init takes, so an attempt's
    first device touch lands after the window has already closed (round
    5: probe OK at +0 s, first device op at +90 s, wedged). The init
    arrays are deterministic per config (fixed PRNG keys), so cache the
    numpy pytree; a warm attempt reaches its first accelerator op in
    seconds. The pickle is a repo-local artifact written and read only by
    the bench harness on this box — not an interchange format. Callers
    key the path by config AND model-source hash so editing a model
    invalidates its entries (see bench.py ``_init_cache_path``).

    ``cache_path`` None/empty disables caching entirely."""
    import pickle

    log = log or (lambda *_: None)
    if cache_path:
        try:
            with open(cache_path, "rb") as f:
                out = pickle.load(f)
            log(f"host-init cache hit ({cache_path})")
            return out
        except FileNotFoundError:
            pass
        except Exception as exc:  # noqa: BLE001 - stale/corrupt: rebuild
            log(f"host-init cache unreadable ({exc!r}); rebuilding")
    out = make()
    if cache_path:
        try:
            import jax
            import numpy as np

            host = jax.tree_util.tree_map(np.asarray, out)
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, cache_path)  # atomic: never a torn cache file
            log(f"host-init cache written ({cache_path})")
            return host
        except Exception as exc:  # noqa: BLE001 - cache is best-effort
            log(f"host-init cache write failed ({exc!r}); continuing")
    return out


def init_on_host_cpu(make, placement, log=None):
    """Run ``make()`` on the host CPU backend and ship the result to
    ``placement`` (a device, a sharding, or a pytree-prefix of either
    matching ``make``'s return).

    Why: on a remote accelerator the dominant failure mode of this
    environment is a hung compile RPC (rounds 2-3: probe OK, then the
    first big compile hangs for >18 min). Model/data initialization is a
    full extra device compile that contributes nothing to the caller's
    real work, so running it on the separate CPU backend and paying plain
    transfers instead halves the hang surface per attempt. PRNG key
    creation must happen INSIDE ``make`` — a key built outside dispatches
    a jitted seed computation on the accelerator, re-opening the exact
    window this helper closes.

    Returns the placed pytree, or None when there is no separate host
    backend or anything fails — callers fall back to on-device init.
    The transfer is blocked on inside the failure boundary so async
    transfer errors select the fallback instead of escaping to first use.
    """
    import jax

    if jax.devices()[0].platform == "cpu":
        return None
    try:
        cpu0 = jax.local_devices(backend="cpu")[0]
    except Exception:  # noqa: BLE001 - no separate host backend
        return None
    log = log or (lambda *_: None)
    try:
        with jax.default_device(cpu0):
            out = make()
        # The transfer is the first accelerator touch of the attempt and
        # the tunnel's observed wedge point (round 5, attempt 1: probe OK,
        # then 18 min of silence before any post-init line) — bracket it
        # so a killed attempt's last log line says which side of it died.
        log("host init done; placing onto accelerator...")
        out = jax.device_put(out, placement)
        jax.block_until_ready(out)
        log("accelerator placement done")
        return out
    except Exception as exc:  # noqa: BLE001 - caller falls back
        from .logging import LOG

        LOG.warning("host-CPU init failed (%r); falling back to "
                    "on-device init", exc)
        return None
