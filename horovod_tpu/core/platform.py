"""Force JAX onto virtual CPU devices — the "no cluster needed" fixture.

The reference's test fixture is single-process MPI (a self-initialized world
of size 1, SURVEY §4); ours is N virtual XLA CPU devices in one process.
Pinning matters beyond tests: in this environment the experimental TPU
plugin can hang for minutes inside a bare ``jax.devices()`` call, so any
code path that must never touch the real chip (tests, the driver's
multi-chip dryrun) pins the platform first.

The TPU plugin prepends itself to ``JAX_PLATFORMS``, so scrubbing the env
var alone is not enough — the config must also be overridden after import.
Both the env mutation and ``jax.config.update`` take effect as long as no
backend has spun up yet; XLA_FLAGS is read lazily at backend creation.
"""

from __future__ import annotations

import os


def pin_cpu_platform(n_devices: int = 8) -> None:
    """Pin JAX to ``n_devices`` virtual CPU devices, verifying the result.

    Must be called before any JAX backend query (``jax.devices()``,
    ``jax.process_index()``, array creation, ...). Safe to call after
    ``import jax`` itself. If another backend already spun up, the config
    update is a silent no-op in JAX — so this function queries the devices
    it just pinned and raises rather than letting the caller proceed on the
    wrong platform with the wrong device count.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    os.environ.pop("JAX_PLATFORMS", None)
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = " ".join(
            flag if f.startswith("--xla_force_host_platform_device_count")
            else f for f in flags.split())
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu" or len(devices) < n_devices:
        raise RuntimeError(
            f"pin_cpu_platform({n_devices}) failed: JAX reports "
            f"{len(devices)} {devices[0].platform!r} device(s). A backend "
            f"was already initialized before the pin ran — call "
            f"pin_cpu_platform before any jax.devices()/array operation.")


def init_on_host_cpu(make, placement):
    """Run ``make()`` on the host CPU backend and ship the result to
    ``placement`` (a device, a sharding, or a pytree-prefix of either
    matching ``make``'s return).

    Why: on a remote accelerator the dominant failure mode of this
    environment is a hung compile RPC (rounds 2-3: probe OK, then the
    first big compile hangs for >18 min). Model/data initialization is a
    full extra device compile that contributes nothing to the caller's
    real work, so running it on the separate CPU backend and paying plain
    transfers instead halves the hang surface per attempt. PRNG key
    creation must happen INSIDE ``make`` — a key built outside dispatches
    a jitted seed computation on the accelerator, re-opening the exact
    window this helper closes.

    Returns the placed pytree, or None when there is no separate host
    backend or anything fails — callers fall back to on-device init.
    The transfer is blocked on inside the failure boundary so async
    transfer errors select the fallback instead of escaping to first use.
    """
    import jax

    if jax.devices()[0].platform == "cpu":
        return None
    try:
        cpu0 = jax.local_devices(backend="cpu")[0]
    except Exception:  # noqa: BLE001 - no separate host backend
        return None
    try:
        with jax.default_device(cpu0):
            out = make()
        out = jax.device_put(out, placement)
        jax.block_until_ready(out)
        return out
    except Exception as exc:  # noqa: BLE001 - caller falls back
        from .logging import LOG

        LOG.warning("host-CPU init failed (%r); falling back to "
                    "on-device init", exc)
        return None
