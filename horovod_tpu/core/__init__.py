"""Framework-agnostic core: topology, status, config, logging.

TPU-native rebuild of ``horovod/common/`` (SURVEY §2.1). The reference's
core is a C++ background thread coordinating MPI ranks; here the core state
is Python + a native controller (``horovod_tpu/cc``) for the eager/async
path, while the synchronous data plane is jit-compiled XLA collectives.
"""

from .config import Config
from .logging import LOG
from .status import (
    HorovodInternalError,
    NotInitializedError,
    RanksAbortedError,
    SHUT_DOWN_ERROR,
    Status,
    StatusType,
)
from .topology import Topology, discover

__all__ = [
    "Config",
    "LOG",
    "HorovodInternalError",
    "NotInitializedError",
    "RanksAbortedError",
    "SHUT_DOWN_ERROR",
    "Status",
    "StatusType",
    "Topology",
    "discover",
]
