"""Capture-provenance helpers shared by ``bench.py`` and the example
benchmarks: every self-describing measurement line stamps the revision it
was measured on, so the wedge-fallback path can tell (and report) when a
capture predates perf-relevant commits — a time bound alone cannot.
"""

from __future__ import annotations

import json
import subprocess
from typing import Optional, Tuple


def git_head_sha(path: str) -> Optional[str]:
    """Short HEAD sha of the git repo containing ``path``, best-effort
    (None outside a repo, without git, or on any subprocess failure)."""
    try:
        out = subprocess.run(
            ["git", "-C", path, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except (OSError, subprocess.SubprocessError):
        return None


def last_json_line(text: Optional[str],
                   want: type = dict) -> Tuple[Optional[str], object]:
    """Scan child stdout bottom-up for the last line parsing as JSON of
    type ``want``; returns ``(raw_line, parsed)`` or ``(None, None)``.

    The shared tolerant parse for every supervisor that relays a child's
    one-line result: library banners or interpreter-shutdown warnings
    printed after the ``json.dumps`` — and lines truncated mid-write by a
    SIGKILL — must fall through to the caller's retry path, not surface as
    corrupt JSON."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, want):
            return line, parsed
    return None, None
