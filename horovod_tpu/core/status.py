"""Status codes and error types.

TPU-native rebuild of the reference's ``Status`` machinery
(``horovod/common/common.h:28-75``): the reference threads a ``Status`` object
from the C++ core back through per-framework callbacks; we keep the same
status taxonomy so the async API (poll/synchronize) and the controller's
error-response construction can report identical failure classes.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from typing import List, Optional


class StatusType(enum.IntEnum):
    """Mirrors the reference StatusType enum (``common.h:33-39``)."""

    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass(frozen=True)
class Status:
    """Result of a collective operation (``common.h:41-75``)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    @staticmethod
    def ok() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def unknown_error(reason: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, reason)

    @staticmethod
    def precondition_error(reason: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, reason)

    @staticmethod
    def aborted(reason: str) -> "Status":
        return Status(StatusType.ABORTED, reason)

    @staticmethod
    def invalid_argument(reason: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, reason)

    @staticmethod
    def in_progress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def __bool__(self) -> bool:
        return self.type == StatusType.OK

    def raise_if_error(self) -> None:
        if self.type in (StatusType.OK, StatusType.IN_PROGRESS):
            return
        reason = self.reason or self.type.name
        # Integrity-plane verdicts first (docs/integrity.md): their tags
        # are more specific than the aborted-ranks tag a consensus reason
        # may also carry for the elastic driver's benefit.
        consensus = parse_consensus(reason)
        nonfinite = None if consensus is not None else \
            parse_nonfinite(reason)
        ranks = None if (consensus is not None or nonfinite is not None) \
            else parse_aborted_ranks(reason)
        if consensus is not None or nonfinite is not None or \
                ranks is not None:
            # Flight recorder (docs/blackbox.md): a STRUCTURED world
            # escalation is about to raise — ship this rank's black-box
            # tail before the exception unwinds (idempotent; a no-op
            # unless an engine armed the dump context, so synthetic
            # errors in tests trigger nothing).
            _flightrec_hook(reason)
        if consensus is not None:
            raise ConsensusError(consensus[0], consensus[1], reason)
        if nonfinite is not None:
            raise NonFiniteGradError(nonfinite[0], nonfinite[1], reason)
        if ranks is not None:
            raise RanksAbortedError(ranks, reason)
        raise HorovodInternalError(reason)


def _flightrec_hook(reason: str) -> None:
    """Lazy, failure-proof bridge to ``obs.flightrec.on_structured_error``
    (imported here, not at module level: core.status must stay the
    dependency floor of the package)."""
    try:
        from ..obs.flightrec import on_structured_error

        on_structured_error(reason)
    except Exception:  # noqa: BLE001 - never worsen the failure path
        pass


# The message every outstanding callback receives when the background
# controller shuts down mid-flight (reference: ``operations.cc:263-268``).
SHUT_DOWN_ERROR = (
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to allreduce, allgather or broadcast a tensor "
    "after one of the ranks finished execution. If the shutdown was caused "
    "by an exception, you should see the exception in the log before the "
    "first shutdown message."
)


# Refusal a controller service answers NEW registrations (hello) and
# fresh watch parks with once its world has negotiated shutdown: on
# shutdown(); init() re-use of the same port, a next-world client can
# reach the dying previous service — served hello + first-cycle EOF
# looked like a world abort (found by a randomized re-init soak). Both
# controller implementations emit this EXACT text and both clients
# treat it as retry-the-connect, not a final error.
CONTROLLER_RESTARTING = (
    "controller world has shut down; a next-world client should retry "
    "its connect against the successor service"
)

# Refusal for a hello/watch whose world identity does not match the
# service's: subset schedules let a non-member of world N race ahead
# into world N+1 while N's service is still LIVE on the shared port —
# without the identity check its remapped-rank hello superseded a live
# member's registration and aborted world N with a spurious rank death
# (found by the subset churn soak). Retryable: the caller's own world's
# service has not bound the port yet. Both controller implementations
# emit this exact prefix.
WORLD_MISMATCH = "controller serves a different world"


class HorovodInternalError(RuntimeError):
    """Raised when a collective completes with a non-OK status.

    The reference surfaces these as framework-specific exceptions from the
    synchronize/wait path (e.g. ``torch/mpi_ops_v2.cc:228-234``).
    """


class RanksAbortedError(HorovodInternalError):
    """A collective was aborted because specific peer ranks are gone.

    The structured form of the reference's blanket SHUT_DOWN_ERROR: when
    the coordinator can attribute the failure — a rank's connection
    dropped mid-job, or a stall outlived the
    ``HOROVOD_STALL_SHUTDOWN_TIME_S`` deadline — the abort names the
    missing ranks so an elastic driver (``horovod_tpu.elastic``) can
    blacklist the right slots on relaunch. Subclasses
    ``HorovodInternalError`` so existing handlers keep working.
    """

    def __init__(self, ranks: List[int], message: str) -> None:
        super().__init__(message)
        self.ranks = sorted(set(ranks))


class NonFiniteGradError(HorovodInternalError):
    """A reduced gradient carried NaN/Inf and the numerical-health sentry
    runs with ``HOROVOD_GRAD_SENTRY=abort`` (docs/integrity.md).

    The verdict behind it is collective (a finite-bit exchange over the
    controller wire), so every rank raises this on the SAME step ordinal
    — the structured alternative to letting a poisoned step reach the
    optimizer state of every rank. ``step`` is the sentry's batch ordinal
    (1-based, identical across ranks); ``tensor_names`` the non-finite
    tensors of that batch. Subclasses ``HorovodInternalError`` so the
    elastic driver's world-fault classification relaunches through the
    PR-2 path."""

    def __init__(self, step: int, tensor_names: List[str],
                 message: str) -> None:
        super().__init__(message)
        self.step = step
        self.tensor_names = list(tensor_names)


class ConsensusError(HorovodInternalError):
    """Cross-rank consensus verification failed: after an allreduce that
    must leave every rank bit-identical, the ranks' post-allreduce
    digests disagreed (docs/integrity.md) — the silent-data-corruption
    class (host bit flips, rank desync) that otherwise trains forever on
    diverged state. ``ranks`` names the outlier ranks (judged against the
    coordinator's authoritative combine digest on the host data plane,
    majority vote elsewhere); ``tensor_names`` the tensors whose digests
    diverged. Subclasses ``HorovodInternalError`` so existing handlers —
    and the elastic relaunch-and-restore path — keep working."""

    def __init__(self, ranks: List[int], tensor_names: List[str],
                 message: str) -> None:
        super().__init__(message)
        self.ranks = sorted(set(ranks))
        self.tensor_names = list(tensor_names)


# Machine-parseable tag embedded in abort reasons so every layer the
# message travels through (status flush, watch-channel push, engine-loop
# rewrap) preserves attribution. format/parse are the single source of
# truth for the wire text.
_ABORTED_TAG_RE = re.compile(r"\[aborted ranks: ([0-9][0-9,\s]*)\]")
# Fallbacks: abort reasons composed before this tag existed (the native
# C++ service's disconnect message, the stall warning's rank list).
_EXITED_RE = re.compile(r"rank (\d+) (?:exited mid-job|disconnected)")
_MISSING_RE = re.compile(r"missing ranks: ([0-9][0-9,\s]*)")


def format_aborted_ranks(ranks) -> str:
    """Render the structured tag appended to abort reasons."""
    return "[aborted ranks: " + ", ".join(
        str(r) for r in sorted(set(ranks))) + "]"


def parse_aborted_ranks(message: str,
                        strict: bool = False) -> Optional[List[int]]:
    """Extract the missing-rank list from an abort reason, if one is
    attributable; None for unattributed shutdowns.

    ``strict=True`` accepts only the explicit ``[aborted ranks: …]`` tag —
    required when scanning LOG output (e.g. a dead rank's stderr tail),
    where the fallback patterns would match the coordinator's routine
    stall warnings. The default full parse is for exception messages,
    which only ever contain genuine abort reasons."""
    m = _ABORTED_TAG_RE.search(message)
    if m is None and not strict:
        m = _MISSING_RE.search(message)
    if m is not None:
        ranks = [int(tok) for tok in m.group(1).replace(",", " ").split()]
        return sorted(set(ranks)) if ranks else None
    if strict:
        return None
    m = _EXITED_RE.search(message)
    if m is not None:
        return [int(m.group(1))]
    return None


# Integrity-plane tags (docs/integrity.md), same contract as the
# aborted-ranks tag: format/parse are the single source of truth for the
# wire text, so the verdict survives every rewrap between the controller
# and the waiter that finally raises.
_CONSENSUS_TAG_RE = re.compile(
    r"\[consensus mismatch: ranks ([0-9][0-9,\s]*)\]"
    r"(?: \[tensors: ([^\]]*)\])?")
_NONFINITE_TAG_RE = re.compile(
    r"\[non-finite grad: step (\d+)\](?: \[tensors: ([^\]]*)\])?")


def format_consensus(ranks, tensor_names) -> str:
    """Render the structured consensus-mismatch tag."""
    tag = "[consensus mismatch: ranks " + ", ".join(
        str(r) for r in sorted(set(ranks))) + "]"
    if tensor_names:
        tag += " [tensors: " + ", ".join(tensor_names) + "]"
    return tag


def parse_consensus(message: str):
    """``(ranks, tensor_names)`` from a consensus-mismatch reason, or
    None when the message carries no consensus tag."""
    m = _CONSENSUS_TAG_RE.search(message)
    if m is None:
        return None
    ranks = [int(tok) for tok in m.group(1).replace(",", " ").split()]
    names = [n.strip() for n in (m.group(2) or "").split(",") if n.strip()]
    return sorted(set(ranks)), names


def format_nonfinite(step: int, tensor_names) -> str:
    """Render the structured non-finite-gradient tag."""
    tag = f"[non-finite grad: step {step}]"
    if tensor_names:
        tag += " [tensors: " + ", ".join(tensor_names) + "]"
    return tag


def parse_nonfinite(message: str):
    """``(step, tensor_names)`` from a sentry-abort reason, or None."""
    m = _NONFINITE_TAG_RE.search(message)
    if m is None:
        return None
    names = [n.strip() for n in (m.group(2) or "").split(",") if n.strip()]
    return int(m.group(1)), names


def failure_record(exc: BaseException, traceback_str: str) -> dict:
    """Structured failure payload a worker ships to the driver (the wire
    form of a worker exception). Replaces text-parsing abort reasons out
    of tracebacks: the attribution ships as DATA — ``aborted_ranks`` from
    the exception object itself (``RanksAbortedError.ranks``), falling
    back to the tagged text for exceptions that only carry the reason as
    a message. ``format`` versions the record so old-format peers (a
    plain traceback string) keep decoding via the text fallback."""
    ranks = getattr(exc, "ranks", None)
    if ranks is None:
        ranks = parse_aborted_ranks(str(exc))
    if ranks is None:
        # chained/wrapped aborts (`raise UserError(...) from
        # RanksAbortedError`): the attribution may only survive in the
        # traceback text — the record must not be WEAKER than the text
        # fallback it replaces, since its presence disables that fallback
        # in the elastic driver
        ranks = parse_aborted_ranks(traceback_str)
    return {
        "format": 1,
        "error_type": type(exc).__name__,
        "traceback": traceback_str,
        "aborted_ranks": sorted(int(r) for r in ranks) if ranks else None,
        # world fault = the WORLD failed under this rank (aborted/shut-down
        # collectives), not the user's code — the elastic driver only
        # relaunches for these
        "world_fault": isinstance(exc, HorovodInternalError)
        or ranks is not None
        or "shut down" in str(exc)
        or "shut down" in traceback_str,
    }


class NotInitializedError(ValueError):
    """Raised when the API is used before ``init()``.

    Mirrors the CheckInitialized precondition (``operations.cc:2472``) and the
    ``ValueError`` the reference Python wrapper raises on a -1 rank
    (``horovod/common/__init__.py:90-154``).
    """

    def __init__(self) -> None:
        super().__init__(
            "Horovod has not been initialized; use hvd.init().")
