"""Status codes and error types.

TPU-native rebuild of the reference's ``Status`` machinery
(``horovod/common/common.h:28-75``): the reference threads a ``Status`` object
from the C++ core back through per-framework callbacks; we keep the same
status taxonomy so the async API (poll/synchronize) and the controller's
error-response construction can report identical failure classes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class StatusType(enum.IntEnum):
    """Mirrors the reference StatusType enum (``common.h:33-39``)."""

    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass(frozen=True)
class Status:
    """Result of a collective operation (``common.h:41-75``)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    @staticmethod
    def ok() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def unknown_error(reason: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, reason)

    @staticmethod
    def precondition_error(reason: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, reason)

    @staticmethod
    def aborted(reason: str) -> "Status":
        return Status(StatusType.ABORTED, reason)

    @staticmethod
    def invalid_argument(reason: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, reason)

    @staticmethod
    def in_progress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def __bool__(self) -> bool:
        return self.type == StatusType.OK

    def raise_if_error(self) -> None:
        if self.type in (StatusType.OK, StatusType.IN_PROGRESS):
            return
        raise HorovodInternalError(self.reason or self.type.name)


# The message every outstanding callback receives when the background
# controller shuts down mid-flight (reference: ``operations.cc:263-268``).
SHUT_DOWN_ERROR = (
    "Horovod has been shut down. This was caused by an exception on one of "
    "the ranks or an attempt to allreduce, allgather or broadcast a tensor "
    "after one of the ranks finished execution. If the shutdown was caused "
    "by an exception, you should see the exception in the log before the "
    "first shutdown message."
)


# Refusal a controller service answers NEW registrations (hello) and
# fresh watch parks with once its world has negotiated shutdown: on
# shutdown(); init() re-use of the same port, a next-world client can
# reach the dying previous service — served hello + first-cycle EOF
# looked like a world abort (found by a randomized re-init soak). Both
# controller implementations emit this EXACT text and both clients
# treat it as retry-the-connect, not a final error.
CONTROLLER_RESTARTING = (
    "controller world has shut down; a next-world client should retry "
    "its connect against the successor service"
)

# Refusal for a hello/watch whose world identity does not match the
# service's: subset schedules let a non-member of world N race ahead
# into world N+1 while N's service is still LIVE on the shared port —
# without the identity check its remapped-rank hello superseded a live
# member's registration and aborted world N with a spurious rank death
# (found by the subset churn soak). Retryable: the caller's own world's
# service has not bound the port yet. Both controller implementations
# emit this exact prefix.
WORLD_MISMATCH = "controller serves a different world"


class HorovodInternalError(RuntimeError):
    """Raised when a collective completes with a non-OK status.

    The reference surfaces these as framework-specific exceptions from the
    synchronize/wait path (e.g. ``torch/mpi_ops_v2.cc:228-234``).
    """


class NotInitializedError(ValueError):
    """Raised when the API is used before ``init()``.

    Mirrors the CheckInitialized precondition (``operations.cc:2472``) and the
    ``ValueError`` the reference Python wrapper raises on a -1 rank
    (``horovod/common/__init__.py:90-154``).
    """

    def __init__(self) -> None:
        super().__init__(
            "Horovod has not been initialized; use hvd.init().")
