"""World topology: rank / size / local_rank / local_size / cross ranks.

The reference derives these from MPI communicators: ``MPI_COMM_WORLD`` rank
and size, a shared-memory split for the node-local communicator, and a
local-rank split for the cross-node communicator
(``horovod/common/operations.cc:1728-1797``). There is no MPI in this build;
the world is discovered from, in priority order:

1. Launcher environment (``HOROVOD_RANK``/``HOROVOD_SIZE``/...), set by
   ``horovodrun``/``horovod_tpu.runner`` — the analog of
   ``OMPI_COMM_WORLD_RANK`` et al. that mpirun exports.
2. The JAX multi-process runtime (``jax.process_index()``/``process_count()``)
   on a real TPU pod, where one process per host is the natural deployment.
3. Single-process default: rank 0 of a world of size 1 (the reference's
   "single-process MPI self-world" test fixture, SURVEY §4).

A rank is a *process*, exactly as in the reference (one process per
accelerator there; one process per TPU host here, owning
``jax.local_device_count()`` chips). ``num_devices()`` reports the total
data-parallel device count across the world, which is what examples use for
learning-rate scaling.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional

from . import config as _config


@dataclass(frozen=True)
class Topology:
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    # Number of accelerator devices owned by this process / by the world.
    local_device_count: int
    global_device_count: int
    hostname: str
    # Launcher-world coordinates. For a full world these equal rank/size;
    # for a subset world (``hvd.init(ranks=[...])``, reference
    # ``operations.cc:1728-1742`` MPI_Group_incl) rank/size describe the
    # subset communicator while world_rank/world_size keep the launcher
    # coordinates — world_rank 0 always hosts the controller service, since
    # that is the address the launcher advertised to every process.
    world_rank: int = -1
    world_size: int = -1
    # False for a process outside the subset: it gets a self-world of size
    # 1 (collectives work locally, nothing deadlocks) instead of the
    # reference's ill-defined MPI_COMM_WORLD fallback.
    is_member: bool = True
    # The subset composition (launcher ranks, in communicator order) for
    # ``init(ranks=[...])`` worlds; None for the full world. Defines the
    # world identity the controller protocol uses to keep co-scheduled
    # worlds on one port from cross-registering (core.status.WORLD_MISMATCH).
    members: Optional[tuple] = None

    def __post_init__(self):
        if self.world_rank < 0:
            object.__setattr__(self, "world_rank", self.rank)
            object.__setattr__(self, "world_size", self.size)

    @property
    def in_subset_world(self) -> bool:
        # A permuted full-size list (ranks=[1,0]) is also a subset world:
        # subset ranks no longer align with JAX process indices, so the
        # device plane (which assumes that alignment) must not be used.
        return (self.world_size != self.size or not self.is_member
                or self.rank != self.world_rank)

    @property
    def is_homogeneous(self) -> bool:
        """Reference: allgather of local sizes → is_homogeneous
        (``operations.cc:1760-1780``). Our worlds are homogeneous by
        construction (launcher enforces a uniform per-host process count);
        heterogeneous TPU slices are not a supported deployment."""
        return True


def _jax_counts():
    # Deferred import: topology must be resolvable before JAX spins up
    # (the launcher computes ranks without touching devices).
    import jax

    return (
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def discover(use_jax: bool = True, subset=None) -> Topology:
    """Resolve the world, preferring launcher env over the JAX runtime.

    ``subset`` is the rank list of ``hvd.init(ranks=[...])``: the subset
    forms the active communicator in list order (the reference's
    MPI_Group_incl semantics, ``operations.cc:1728-1742``); every launcher
    process must call init with the same list. Processes outside the list
    become self-worlds of size 1. Host-local splits (local_rank/size) keep
    their launcher values — the subset does not move processes between
    hosts (documented delta: the reference re-splits the subset comm by
    shared memory)."""
    full = _discover_full(use_jax=use_jax)
    if subset is None:
        return full
    subset = list(subset)
    if sorted(set(subset)) != sorted(subset) or not subset or \
            not all(isinstance(r, int) and 0 <= r < full.world_size
                    for r in subset):
        raise ValueError(
            f"init(ranks=...) must be a list of distinct ranks within "
            f"[0, {full.world_size}), got {subset!r}")
    if full.rank not in subset:
        return Topology(
            rank=0, size=1, local_rank=0, local_size=1, cross_rank=0,
            cross_size=1, local_device_count=full.local_device_count,
            global_device_count=full.local_device_count,
            hostname=full.hostname, world_rank=full.rank,
            world_size=full.size, is_member=False,
            members=tuple(subset))
    index = subset.index(full.rank)
    return Topology(
        rank=index, size=len(subset), local_rank=full.local_rank,
        local_size=full.local_size, cross_rank=full.cross_rank,
        cross_size=full.cross_size,
        local_device_count=full.local_device_count,
        global_device_count=full.local_device_count * len(subset),
        hostname=full.hostname, world_rank=full.rank,
        world_size=full.size, is_member=True, members=tuple(subset))


def _discover_full(use_jax: bool = True) -> Topology:
    env = os.environ
    hostname = socket.gethostname()
    if _config.HOROVOD_RANK in env and _config.HOROVOD_SIZE in env:
        rank = int(env[_config.HOROVOD_RANK])
        size = int(env[_config.HOROVOD_SIZE])
        local_rank = int(env.get(_config.HOROVOD_LOCAL_RANK, 0))
        local_size = int(env.get(_config.HOROVOD_LOCAL_SIZE, 1))
        cross_rank = int(env.get(_config.HOROVOD_CROSS_RANK, rank // max(local_size, 1)))
        cross_size = int(env.get(_config.HOROVOD_CROSS_SIZE, size // max(local_size, 1)))
        if use_jax and env.get(_config.HOROVOD_DATA_PLANE) != "host":
            local_devices = _local_devices_safe()
        else:
            # Host-plane worlds (numpy-over-TCP; the torch/TF front-ends'
            # CPU deployment) never touch accelerators: one rank == one
            # device, and querying JAX here would needlessly initialize —
            # and on a machine with a wedged/slow TPU plugin, hang — a
            # backend the job will not use.
            local_devices = 1
        return Topology(
            rank=rank,
            size=size,
            local_rank=local_rank,
            local_size=local_size,
            cross_rank=cross_rank,
            cross_size=cross_size,
            local_device_count=local_devices,
            global_device_count=local_devices * size,
            hostname=hostname,
        )
    if use_jax:
        pidx, pcount, local_devices, global_devices = _jax_counts()
        return Topology(
            rank=pidx,
            size=pcount,
            local_rank=0,
            local_size=1,
            cross_rank=pidx,
            cross_size=pcount,
            local_device_count=local_devices,
            global_device_count=global_devices,
            hostname=hostname,
        )
    return Topology(
        rank=0, size=1, local_rank=0, local_size=1, cross_rank=0,
        cross_size=1, local_device_count=1, global_device_count=1,
        hostname=hostname,
    )


def _local_devices_safe() -> int:
    try:
        import jax

        return jax.local_device_count()
    except Exception:  # pragma: no cover - jax missing/broken
        return 1
