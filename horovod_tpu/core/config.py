"""Runtime configuration knobs, all environment variables.

The reference has no config files and no CLI parser in the library: every
runtime knob is an env var read in ``BackgroundThreadLoop``
(``horovod/common/operations.cc:1707,1825-1909``; names declared at
``operations.h:57-66``). We keep the exact same names (HOROVOD_*) so that
operational muscle memory and docs transfer, and add a small number of
TPU-specific knobs (controller address, virtual world description) needed
because our control plane is TCP rather than MPI.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

# --- reference knob names (operations.h:57-66) -------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
# Distributed tracing (docs/tracing.md; ours): plain HOROVOD_TIMELINE
# stays rank-0-only for back-compat with the reference artifact; setting
# this to 1 makes EVERY member rank record spans into a rank-suffixed
# file (<path>.rankN.json) that tools/trace_merge.py folds into one
# clock-corrected Chrome trace with a process lane per rank.
HOROVOD_TIMELINE_ALL_RANKS = "HOROVOD_TIMELINE_ALL_RANKS"
# Seconds between clock-alignment handshakes against the coordinator
# (min-RTT-filtered ping battery; obs/tracing.py). <= 0 disables the
# periodic re-sync (the init-time sync still runs where the plane is
# active at all).
HOROVOD_CLOCK_SYNC_INTERVAL = "HOROVOD_CLOCK_SYNC_INTERVAL_S"
# TPU-side twin of the timeline (SURVEY §5.1 mapping): the host timeline
# records enqueue/negotiate/execute; on-device time lives in the XLA
# profiler. This knob brackets init→shutdown with a jax.profiler trace on
# rank 0, so both artifacts land side by side.
HOROVOD_JAX_PROFILE = "HOROVOD_JAX_PROFILE"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
# Extension: the reference hardcodes 60s (STALL_WARNING_TIME,
# operations.cc:258); configurable here, same default.
HOROVOD_STALL_WARNING_TIME = "HOROVOD_STALL_WARNING_TIME"
# Fault-tolerance escalation (horovod_tpu.elastic): a stall that outlives
# this many seconds is converted from a warning into a structured world
# abort — every healthy rank raises RanksAbortedError naming the missing
# ranks instead of blocking forever. 0 (default) keeps the reference's
# warn-and-wait behavior; upstream Horovod later grew the same knob as
# HOROVOD_STALL_SHUTDOWN_TIME_SECONDS.
HOROVOD_STALL_SHUTDOWN_TIME = "HOROVOD_STALL_SHUTDOWN_TIME_S"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
# Default gradient-compression codec for DistributedOptimizer /
# allreduce_gradients when the caller does not pass compression=
# explicitly: none (default) / fp16 / bf16 / int8 / fp8. Extension beyond
# the reference (which only has the per-call Compression argument): the
# quantized wire (EQuARX int8/fp8) is an operational knob one wants to
# flip fleet-wide without touching training code. docs/compression.md.
HOROVOD_COMPRESSION = "HOROVOD_COMPRESSION"
# Steady-state negotiation bypass (docs/response-cache.md): max cached
# fused responses per rank/coordinator; 0 disables the cache-bit fast
# path. Upstream Horovod later grew the same knob as HOROVOD_CACHE_CAPACITY.
# Must resolve identically on every rank (the launcher's env export does
# this): cache coherence is deterministic replay of identical transitions,
# and capacity participates in eviction choices.
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
# --- closed-loop tuning plane (horovod_tpu.tune; ours, docs/autotune.md) -----
# Optimizer backend behind HOROVOD_AUTOTUNE=1: "policy" (default) is the
# pure-Python coordinate-descent/hill-climb loop — no native core needed;
# "native" opts back into the C++ GP/Bayesian parameter manager
# (cc/autotune.cc), which tunes only the classic (fusion, cycle) pair.
HOROVOD_AUTOTUNE_BACKEND = "HOROVOD_AUTOTUNE_BACKEND"
# Scored cycles folded (median) into one measurement window (default 5,
# the reference's median-of-5), and cycles discarded after each knob move
# before measurement resumes (default 5) — a just-applied knob reaches
# every rank one response later, so the first post-move cycles mix
# configurations and must not score.
HOROVOD_AUTOTUNE_WINDOW = "HOROVOD_AUTOTUNE_WINDOW"
HOROVOD_AUTOTUNE_COOLDOWN = "HOROVOD_AUTOTUNE_COOLDOWN"
# Relative score-regression tolerance of the revert guard (default 0.05):
# a measured window worse than best_known * (1 - tolerance) rolls the
# move back to the best-known config.
HOROVOD_AUTOTUNE_TOLERANCE = "HOROVOD_AUTOTUNE_TOLERANCE"
# JSONL decision audit log (one line per retune/revert; rendered by
# tools/tune_report.py). Distinct from HOROVOD_AUTOTUNE_LOG, the per-cycle
# CSV sample log.
HOROVOD_AUTOTUNE_DECISIONS = "HOROVOD_AUTOTUNE_DECISIONS"
# Opt-in codec ladder for the codec knob, e.g. "int8,fp8". EMPTY (the
# default) pins the codec: quantized wires are lossy, so the tuner may
# only explore them when the operator explicitly consents. Only
# codec=="none" allreduce batches at least CODEC_MIN_BYTES big (the
# "large gradient" tensor class, default 4096) are rewritten; explicitly
# quantized traffic is never touched.
HOROVOD_AUTOTUNE_CODECS = "HOROVOD_AUTOTUNE_CODECS"
HOROVOD_AUTOTUNE_CODEC_MIN_BYTES = "HOROVOD_AUTOTUNE_CODEC_MIN_BYTES"
# Deterministic test hook (the HOROVOD_ELASTIC_FAULT pattern):
# "regress@N" scales every score observed after the Nth accepted retune
# so the next measured window regresses and the revert guard must fire
# exactly once (the fault clears itself on the first revert).
HOROVOD_AUTOTUNE_FAULT = "HOROVOD_AUTOTUNE_FAULT"
# Persistent-straggler mitigation (docs/autotune.md): "off" (default) /
# "advisory" (detector verdicts are counted, logged, and pushed to the
# elastic driver, which records them) / "enforce" (the elastic driver
# additionally blacklists the named slot and relaunches through the
# elastic path). Unknown values fail loudly at detector construction.
HOROVOD_STRAGGLER_EVICT = "HOROVOD_STRAGGLER_EVICT"
# Sliding window the detector folds blame-seconds over (default 30 s)
# and the minimum attributed cycles inside it before any verdict
# (default 20) — a handful of cycles must never name a straggler.
HOROVOD_STRAGGLER_WINDOW = "HOROVOD_STRAGGLER_WINDOW_S"
HOROVOD_STRAGGLER_MIN_CYCLES = "HOROVOD_STRAGGLER_MIN_CYCLES"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"

# --- launcher / control-plane knobs (ours; role of mpirun's env in the ref) --
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_CONTROLLER_ADDR = "HOROVOD_CONTROLLER_ADDR"
HOROVOD_CONTROLLER_PORT = "HOROVOD_CONTROLLER_PORT"
# Single-host launches: the launcher binds the controller listener itself
# (port 0) and rank 0 inherits the LIVE socket via this fd — closing the
# probe-then-rebind TOCTOU window where another process could steal the
# advertised port between the launcher's probe and rank 0's bind.
HOROVOD_CONTROLLER_FD = "HOROVOD_CONTROLLER_FD"
# Hierarchical negotiation tree (docs/hierarchy.md): "flat" (default)
# keeps the rank-0 coordinator star; "auto" derives one island per host
# from the launcher's cross_size; "islands:N" forces N islands. Any
# resolved 1-island split, size-1 world, or native-controller world
# degrades deterministically to flat (warned once).
HOROVOD_HIERARCHY = "HOROVOD_HIERARCHY"
# Launcher -> rank plumbing for the negotiation tree (never set by hand;
# the launcher derives them from HOROVOD_HIERARCHY): the rank's island
# id, and the island sub-coordinator's address/port every member dials
# instead of the root. Island heads additionally inherit their
# pre-bound listener via HOROVOD_SUBCOORD_FD (same TOCTOU-closing
# pattern as HOROVOD_CONTROLLER_FD above).
HOROVOD_ISLAND = "HOROVOD_ISLAND"
HOROVOD_SUBCOORD_ADDR = "HOROVOD_SUBCOORD_ADDR"
HOROVOD_SUBCOORD_PORT = "HOROVOD_SUBCOORD_PORT"
HOROVOD_SUBCOORD_FD = "HOROVOD_SUBCOORD_FD"
HOROVOD_SECRET_KEY = "HOROVOD_SECRET_KEY"
HOROVOD_START_TIMEOUT = "HOROVOD_START_TIMEOUT"
# Force the JAX platform ("cpu", "tpu", ...) before any backend starts.
# An env var (JAX_PLATFORMS) is NOT enough on TPU images whose plugin
# prepends itself to the platform list, so ``import horovod_tpu`` applies
# this via jax.config. The debug analog of the reference running an MPI
# job with CUDA_VISIBLE_DEVICES= hidden: the same launcher command line
# can be steered onto CPU for debugging (docs/running.md).
HOROVOD_PLATFORM = "HOROVOD_PLATFORM"
# Launcher: set to "0" to stop the launcher from pinning one TPU chip per
# local rank (TPU_VISIBLE_DEVICES et al.) when a host runs several slots.
HOROVOD_LAUNCHER_PIN_DEVICES = "HOROVOD_LAUNCHER_PIN_DEVICES"
# Data plane selection for eager cross-process collectives:
#   "auto" — XLA collectives over the global device mesh when a multi-process
#            JAX runtime is initialized; TCP/host reduction otherwise.
#   "xla"  — force device collectives.
#   "host" — force host (numpy-over-TCP) reduction; used by CPU launcher tests.
HOROVOD_DATA_PLANE = "HOROVOD_DATA_PLANE"

# --- elastic fault-tolerance plane (horovod_tpu.elastic; ours) ---------------
# World epoch: 0 for the first launch, bumped by the elastic driver on every
# relaunch so workers (and elastic.State) can tell a restart from a fresh
# start.
HOROVOD_ELASTIC_EPOCH = "HOROVOD_ELASTIC_EPOCH"
# Address/port of the elastic driver's health-and-state service (heartbeats
# from every rank; committed-state store for elastic.State). Exported by
# runner.run_elastic; absent for non-elastic jobs.
HOROVOD_ELASTIC_ADDR = "HOROVOD_ELASTIC_ADDR"
HOROVOD_ELASTIC_PORT = "HOROVOD_ELASTIC_PORT"
# Seconds between worker heartbeats to the elastic driver.
HOROVOD_HEARTBEAT_INTERVAL = "HOROVOD_HEARTBEAT_INTERVAL"
# Fault-injection hook for recovery tests: "rank:commit[:epoch]" kills that
# rank with os._exit right before it persists its Nth commit (epoch
# defaults to 0 so the fault does not re-fire after the relaunch). See
# docs/elastic.md.
HOROVOD_ELASTIC_FAULT = "HOROVOD_ELASTIC_FAULT"

# --- surgical recovery plane (ours; docs/recovery.md) ------------------------
# "1" (default) arms warm-survivor relaunch: on a world fault, surviving
# worker processes park in the driver's recovery barrier instead of
# exiting, re-enter the next epoch in-process (keeping the process, its
# devices, and its compiled-program caches), and only dead slots are
# cold-forked. "0" restores the SIGTERM-everything cold relaunch.
# Degrades to cold (warned once) under the native controller and for
# rank-shifted survivors (a warm process cannot re-pin devices).
HOROVOD_RECOVERY_WARM = "HOROVOD_RECOVERY_WARM"
# Seconds the driver waits for survivors of a failed epoch to park in
# the recovery barrier before giving up on reusing them (a survivor that
# never parks is terminated and its slot cold-forked).
HOROVOD_RECOVERY_WINDOW_S = "HOROVOD_RECOVERY_WINDOW_S"
# Slot-blacklist forgiveness (docs/recovery.md): seconds after which a
# failure strike against a slot decays and the slot re-enters the pool.
# 0 (default) keeps the historical life sentence. A StragglerEvictError
# VERDICT is never forgiven regardless of this knob — eviction is a
# measured judgment, not a transient fault.
HOROVOD_BLACKLIST_FORGIVE_S = "HOROVOD_BLACKLIST_FORGIVE_S"
# Island head-rank overrides ("island:rank,island:rank"): planned
# successors published by the elastic driver's warm path when a head
# rank died, so the relaunched island rejoins under its planned
# successor instead of re-electing min(members). Never set by hand.
HOROVOD_ISLAND_HEADS = "HOROVOD_ISLAND_HEADS"
# Launcher -> successor plumbing for live head succession: the standby
# listener every island member fails over to when the head's service
# dies but its rank survives (bound by the launcher beside the primary;
# the planned successor adopts it via HOROVOD_SUBCOORD_STANDBY_FD).
HOROVOD_SUBCOORD_STANDBY_PORT = "HOROVOD_SUBCOORD_STANDBY_PORT"
HOROVOD_SUBCOORD_STANDBY_FD = "HOROVOD_SUBCOORD_STANDBY_FD"
# Deterministic fault hook for the succession drill ("headstop@cycleK"):
# the primary island head stops its sub-coordinator SERVICE (process and
# rank survive as an ordinary member) right before forwarding its Kth
# upstream island cycle — the service-death-without-rank-death shape
# live succession exists for. Epoch-0 only, the ELASTIC_FAULT convention.
HOROVOD_RECOVERY_FAULT = "HOROVOD_RECOVERY_FAULT"

# --- checkpoint plane (horovod_tpu.ckpt; ours, docs/checkpoint.md) -----------
# Per-request timeout (seconds) of elastic.State's commit push / fetch
# client. The seed hard-coded 60 s because one synchronous commit frame
# carried the whole model; the chunked async pipeline makes a generous
# whole-model timeout both wrong and a silent-hang window, so the bound
# is a declared knob (default keeps the historical 60 s for the legacy
# synchronous path).
HOROVOD_CKPT_PUSH_TIMEOUT_S = "HOROVOD_CKPT_PUSH_TIMEOUT_S"
# "1" arms the async commit pipeline: every rank hands its committed
# tree to a background streaming thread (its OWN identified connection —
# the PR-9 second-connection pattern) that ships chunked frames to the
# elastic driver's seal ledger while training keeps stepping; commit
# stall becomes O(snapshot), independent of state size. Unset/"0"
# (default) keeps the synchronous rank-0 whole-tree push bit-exactly.
HOROVOD_CKPT_ASYNC = "HOROVOD_CKPT_ASYNC"
# Chunk size (bytes) of the async commit stream (default 1 MiB): bounds
# the largest single frame a parked commit stream can occupy the wire
# with, and is the granularity the kill-between-chunks fault keys on.
HOROVOD_CKPT_CHUNK_BYTES = "HOROVOD_CKPT_CHUNK_BYTES"
# Fault-injection hook for the async pipeline: "rank:ckpt[:chunk]" kills
# that rank with os._exit right BEFORE its streaming thread sends chunk
# number `chunk` (0-based, default 0) of commit `ckpt` — the
# kill-between-chunks drill. Epoch-0 only, so the fault never re-fires
# after the relaunch (the HOROVOD_ELASTIC_FAULT convention).
HOROVOD_CKPT_FAULT = "HOROVOD_CKPT_FAULT"
# Directory the driver's seal ledger spills sealed epochs and the
# gateway's ticket journal into. Unset (default) keeps both in driver
# memory — they then survive world relaunches but not a driver restart;
# set, a restarted driver reloads the last sealed epoch (bytes-digest
# verified) and resumes journaled in-flight requests.
HOROVOD_CKPT_DIR = "HOROVOD_CKPT_DIR"
# Commit cadence of State.maybe_commit(): commit every Nth call
# (default 1 = every call). Also the checkpoint plane's knob on the
# autotune ladder (tune.policy.ckpt_interval_knob); an explicitly-set
# env pins it, per the standard pin rule.
HOROVOD_CKPT_INTERVAL_STEPS = "HOROVOD_CKPT_INTERVAL_STEPS"

# --- chaos plane + self-healing control plane (ours; docs/chaos.md) ----------
# Deterministic fault-injection spec for the controller wire, e.g.
# "drop@rank1:msg12,delay@rank0:50ms:every7,seed:7" (grammar in
# horovod_tpu.chaos). Empty = no injection. Malformed specs fail loudly at
# client construction.
HOROVOD_CHAOS = "HOROVOD_CHAOS"
# Seconds a rank-bound controller connection that dropped may reconnect
# and supersede before the drop is declared a rank death (the self-healing
# grace window). 0 restores abort-on-first-drop. Python controller service
# only; the native (C++) service keeps immediate attribution.
HOROVOD_RECONNECT_WINDOW = "HOROVOD_RECONNECT_WINDOW_S"
# Client-side transparent-reconnect budget: attempts and the initial /
# maximum exponential backoff between them. Read by
# ``runner.network.ReconnectPolicy.from_env`` at client construction, not
# through Config (clients are built in places that never see a Config).
HOROVOD_RECONNECT_ATTEMPTS = "HOROVOD_RECONNECT_ATTEMPTS"
HOROVOD_RECONNECT_BACKOFF = "HOROVOD_RECONNECT_BACKOFF_S"
HOROVOD_RECONNECT_MAX_BACKOFF = "HOROVOD_RECONNECT_MAX_BACKOFF_S"

# --- data-plane integrity plane (horovod_tpu.integrity; ours) ----------------
# Collective numerical-health sentry over reduced gradients
# (docs/integrity.md): off (default) / warn / skip / zero / abort. The
# verdict is itself collective (a one-element finite-bit exchange over the
# controller wire), so skip/zero decisions are bit-identical on every rank
# and can never desync the world. Unknown values fail loudly at engine
# construction.
HOROVOD_GRAD_SENTRY = "HOROVOD_GRAD_SENTRY"
# Cross-rank consensus verification cadence: every N fused allreduce
# batches each rank digests its post-allreduce gradients and piggybacks
# the digest on the next negotiation message; the coordinator compares
# and a mismatch escalates as a structured ConsensusError instead of
# training on silently diverged state. 0 (default) disables.
HOROVOD_CONSENSUS_INTERVAL = "HOROVOD_CONSENSUS_INTERVAL_STEPS"

# --- flight recorder (horovod_tpu.obs.flightrec; ours, docs/blackbox.md) -----
# Always-on per-rank black-box event ring: every control- and data-plane
# transition (negotiation cycles, flushes, sentry verdicts, consensus
# seals, reconnects, chaos injections, elastic commits, serving batches)
# lands in a fixed-capacity ring buffer, and any world abort dumps a
# cross-rank `blackbox-<world>-<epoch>.json` incident file for
# tools/blackbox_report.py. "0" disables (the hot path then records
# nothing and allocates nothing).
HOROVOD_FLIGHTREC = "HOROVOD_FLIGHTREC"
# Ring capacity in events (default 4096; preallocated slots, O(1)
# append — older events are overwritten, counted as dropped).
HOROVOD_FLIGHTREC_EVENTS = "HOROVOD_FLIGHTREC_EVENTS"
# Seconds the coordinator's incident collector waits for per-rank event
# tails before writing the dump with whatever arrived (best-effort,
# time-bounded by contract — a dead rank never pushes).
HOROVOD_FLIGHTREC_DUMP_TIMEOUT = "HOROVOD_FLIGHTREC_DUMP_TIMEOUT_S"
# Incident-file directory; default: beside the timeline artifact when
# HOROVOD_TIMELINE is set, else the working directory.
HOROVOD_FLIGHTREC_DIR = "HOROVOD_FLIGHTREC_DIR"
# Seconds the launcher lets SURVIVING ranks drain after a rank dies hard
# (nonzero exit) before terminating them — the window in which the
# coordinator's incident collector lands the dump that the teardown
# SIGTERM would otherwise destroy. Default: reconnect window + dump
# timeout + 1, capped at 15; "0" restores immediate fail-fast teardown.
# Only a bound on the FAILURE path: survivors that exit on their own end
# the wait early, and clean worlds never enter it.
HOROVOD_FLIGHTREC_LAUNCH_GRACE = "HOROVOD_FLIGHTREC_LAUNCH_GRACE_S"

# --- gradient numerics observatory (horovod_tpu.obs.tensorwatch; ours,
# docs/tensorwatch.md) --------------------------------------------------------
# Sampled per-tensor gradient telemetry on the eager data plane: every N
# allreduce batches the engine measures norm², max|g|, nonzero count, a
# coarse log₂-magnitude occupancy histogram, the top-k mass-coverage
# curve (sparse-readiness), and — for quantized codecs in play or
# consented via HOROVOD_AUTOTUNE_CODECS — the decode-error SNR of this
# rank's local contribution. 0 (default) disables: no observatory
# object, zero allocations on the hot path (the flightrec bar).
HOROVOD_TENSORWATCH_INTERVAL = "HOROVOD_TENSORWATCH_INTERVAL_STEPS"
# Decode-SNR floor (dB) of the evidence gate: the autotuner's lossy
# codec move is only proposed once HOROVOD_TENSORWATCH_SNR_WINDOW
# consecutive sampled SNRs certify above this floor, and a sampled SNR
# falling below it while the codec is applied triggers a revert through
# the best-known-config guard (decision-log audited).
HOROVOD_TENSORWATCH_SNR_FLOOR = "HOROVOD_TENSORWATCH_SNR_FLOOR_DB"
HOROVOD_TENSORWATCH_SNR_WINDOW = "HOROVOD_TENSORWATCH_SNR_WINDOW"
# Cardinality cap of the labeled horovod_tensor_* families: only the K
# worst tensors (lowest SNR, else largest norm) carry labels on the
# registry; the FULL per-tensor table is hvd.tensor_report() /
# GET /v1/tensors (label values must stay low-cardinality by the
# registry's contract — never one per tensor of a large model).
HOROVOD_TENSORWATCH_WORST = "HOROVOD_TENSORWATCH_WORST_K"

# --- observability plane (horovod_tpu.obs; ours, docs/metrics.md) ------------
# HTTP exposition of the metrics registry on rank 0: Prometheus text at
# /metrics, JSON snapshot at /metrics.json, loopback-bound. 0 or unset =
# no server, no thread (strictly opt-in).
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
# Seconds between each rank's registry-snapshot pushes to the coordinator
# (the cross-rank aggregation feed, an anonymous control-wire channel).
# The publisher is as opt-in as the server: it runs only when
# HOROVOD_METRICS_PORT is set or this interval is set explicitly (a job
# with neither spawns no thread and no connection); <= 0 disables it
# outright, and world snapshots then carry the calling rank only.
HOROVOD_METRICS_INTERVAL = "HOROVOD_METRICS_INTERVAL_S"

# --- inference serving plane (horovod_tpu.serving; ours, docs/serving.md) ----
# The driver-resident ServingPlane exports its coordinator RPC endpoint to
# the worker ranks through these (run_elastic merges plane.env() into every
# attempt's environment; see serving/plane.py). The secret rides the env
# exactly like HOROVOD_SECRET_KEY does from the launcher.
HOROVOD_SERVING_ADDR = "HOROVOD_SERVING_ADDR"
HOROVOD_SERVING_PORT = "HOROVOD_SERVING_PORT"
HOROVOD_SERVING_SECRET = "HOROVOD_SERVING_SECRET"
# Gateway defaults (driver-side; constructor args win over env): max live
# requests admitted to the queue, the SLO budget admission rejects past
# (429 + Retry-After), and the per-request completion deadline (503 once
# exceeded — never a hang).
HOROVOD_SERVING_QUEUE_MAX = "HOROVOD_SERVING_QUEUE_MAX"
HOROVOD_SERVING_SLO_MS = "HOROVOD_SERVING_SLO_MS"
HOROVOD_SERVING_DEADLINE_MS = "HOROVOD_SERVING_DEADLINE_MS"
# Micro-batcher knobs, both on the autotune ladder (docs/serving.md):
# largest packed batch, and either an explicit comma-separated list of
# padding-bucket edges (pins the edges knob) or the default geometric
# ladder derived from HOROVOD_SERVING_EDGE_RATIO (default 2).
HOROVOD_SERVING_BATCH_MAX = "HOROVOD_SERVING_BATCH_MAX"
HOROVOD_SERVING_BUCKET_EDGES = "HOROVOD_SERVING_BUCKET_EDGES"
HOROVOD_SERVING_EDGE_RATIO = "HOROVOD_SERVING_EDGE_RATIO"
# Closed-loop tuning of the two batcher knobs (numerics-neutral — padding
# and packing never change any request's row values — so no consent gate
# like the codec's). Off by default.
HOROVOD_SERVING_AUTOTUNE = "HOROVOD_SERVING_AUTOTUNE"
# Deterministic fault injection for the serving wire (docs/chaos.md): the
# control-wire chaos grammar (drop/delay/corrupt/close/refuse), keyed by
# the serving worker's request ordinals — its own injection domain, so
# serving faults never perturb HOROVOD_CHAOS replay on the cycle channel.
HOROVOD_SERVING_CHAOS = "HOROVOD_SERVING_CHAOS"
# Kill-mid-batch hook ("kill@rankN:batchM[@epochE]"): the named rank
# os._exits right before reporting its Mth batch result in epoch E
# (default 0) — the serving twin of HOROVOD_ELASTIC_FAULT.
HOROVOD_SERVING_FAULT = "HOROVOD_SERVING_FAULT"

# --- sparse top-k gradient wire (ops/sparse_wire.py; ours, docs/compression.md) ---
# Top-k fraction of the "topk" sparse codec, as a PERCENT key matching the
# tensorwatch sparse-readiness curve: "0.1" / "1" / "10" (default "1") —
# each fused allreduce entry ships its k = ceil(f * n) largest-magnitude
# entries as (index, value) pairs over the reference allgather shape and
# every rank decodes the dense mean locally. Unknown keys fail loudly at
# codec construction (ops/sparse_wire.py), never silently rescale.
HOROVOD_SPARSE_TOPK = "HOROVOD_SPARSE_TOPK"
# Evidence floor of the sparse codec's gate: the fraction (0..1) of
# gradient energy the top-k selection must certifiably cover (the
# horovod_tensorwatch_topk_mass curve, energy-weighted per batch) for
# HOROVOD_TENSORWATCH_SNR_WINDOW consecutive samples before the autotuner
# may propose the "topk" codec; a sampled coverage below the floor while
# the codec is applied triggers the audited collapse revert.
HOROVOD_SPARSE_COVERAGE_FLOOR = "HOROVOD_SPARSE_COVERAGE_FLOOR"
# Error feedback (residual accumulation): "1" (default) keeps the dropped
# (non-top-k) mass in a persistent per-rank residual buffer that re-enters
# the next step's selection — the convergence-preserving memory of the
# sparse wire. "0" disables it (each step's dropped mass is lost), which
# demonstrably breaks convergence parity; exposed so that claim is
# testable, not as an operational mode.
HOROVOD_SPARSE_ERROR_FEEDBACK = "HOROVOD_SPARSE_ERROR_FEEDBACK"

# Generation-ordered sub-buffer flush (docs/tensor-fusion.md; ours, the
# T3-style compute/collective overlap on the eager plane): cut each cycle
# tick's pending queue into up to N arrival-ordered sub-buffers that
# negotiate and flush independently, keeping >=2 negotiate/execute cycles
# in flight so cycle k+1's negotiation overlaps cycle k's allreduce.
# 1 (default) keeps the single-flush barrier bit-exactly; >=2 requires the
# Python controller wire (the cache-bit / metrics-RPC degrade pattern).
HOROVOD_FUSION_SUBBUFFERS = "HOROVOD_FUSION_SUBBUFFERS"

# Fused reduce+apply data plane (docs/tensor-fusion.md §fused apply;
# ours, the PAPERS 2305.06942 fused computation-collective design): "1"
# makes ``hvd.apply_step`` submit apply-capable allreduces — the engine
# lands APPLIED parameters and fresh optimizer slots from one compiled
# reduce+apply program per fused batch (psum/quantized decode, loss-scale
# unscale, nonfinite census, SGD/momentum/Adam leaf update) instead of
# handing gradients back for a separate optimizer dispatch. Unset/"0"
# (default) keeps the two-dispatch path bit-exactly. The execution
# strategy within the armed plane (fused single program vs reduce-then-
# apply) additionally sits on the autotune ladder as ``fused_apply``
# (numerics-exact, so never pinned by this env; docs/autotune.md).
HOROVOD_FUSED_APPLY = "HOROVOD_FUSED_APPLY"

# --- sharding plane (ours; docs/sharding.md) ---------------------------------
# Mesh grammar for the 2-D GSPMD planner (sharding/meshplan.py):
# "batch" (default) keeps the flat 1-D data-parallel world byte-
# identically; "batch,model:K" grows a K-way named model axis (K must
# divide the device count). The planner validates the spec loudly at
# plan time — a typo never silently falls back to an unsharded mesh.
HOROVOD_MESH = "HOROVOD_MESH"
# ZeRO stage-1 partitioned optimizer state (sharding/zero1.py): "1"
# makes apply-capable batches run reduce-scatter → local shard apply →
# all-gather as ONE donated compiled program on the XLA device plane,
# with each rank holding only its 1/N shard of the optimizer slots.
# Applied parameters are bit-exact vs the replicated fused plane (the
# single-definition ApplyRule math over a slice). Requires
# HOROVOD_FUSED_APPLY=1 to have any effect; degrades loudly to
# replicated execution on the host plane and in worlds of one.
HOROVOD_ZERO = "HOROVOD_ZERO"

# --- implementation selection + developer knobs (ours) -----------------------
# Negotiation-core selection: "0" forces the pure-Python negotiator;
# anything else prefers the C++ core where built (make_negotiator in
# ops/controller.py; also gates the native timeline writer). Availability
# is per-host — heterogeneous deployments pin it explicitly.
HOROVOD_NATIVE_CORE = "HOROVOD_NATIVE_CORE"
# Controller-service selection (ops/native_controller.py): "auto"
# (default) uses the C++ service where built, "0"/"1" force Python/C++.
HOROVOD_NATIVE_CONTROLLER = "HOROVOD_NATIVE_CONTROLLER"
# Interface the rank-0 controller service binds (default loopback);
# multi-host worlds set the DCN-reachable address (docs/running.md).
HOROVOD_CONTROLLER_BIND = "HOROVOD_CONTROLLER_BIND"
# bench.py warm-init cache (docs/benchmarks.md): "0" disables, unset/"1"
# the default repo-local directory, anything else a custom directory.
HOROVOD_BENCH_INIT_CACHE = "HOROVOD_BENCH_INIT_CACHE"
# Runtime lock witness (docs/analysis.md): "1" wraps the engine's /
# controller's / registry's locks so tests record the ACTUAL acquisition
# order into a global held-before graph and raise LockInversionError on
# inversions the AST lock-order pass (tools/hvdlint.py) cannot see.
# Strictly opt-in: unset means the raw locks, zero overhead.
HOROVOD_LOCK_WITNESS = "HOROVOD_LOCK_WITNESS"

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # operations.cc:1838
DEFAULT_CACHE_CAPACITY = 1024  # upstream response_cache.cc default
DEFAULT_CYCLE_TIME_MS = 5.0  # operations.cc:1846
DEFAULT_START_TIMEOUT_S = 30.0
STALL_WARNING_TIME_S = 60.0  # operations.cc:258


def _env_bool(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in ("", "0", "false")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


@dataclass
class Config:
    """Snapshot of all runtime knobs, taken once at ``init()`` time.

    The reference reads these in the background thread right after MPI init
    (``operations.cc:1825-1909``); we read them in ``hvd.init()``.
    """

    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    # generation-ordered sub-buffer flush (docs/tensor-fusion.md): 1 keeps
    # the single-flush barrier; explicit values pin the autotune knob
    fusion_subbuffers: int = 1
    fusion_subbuffers_explicit: bool = False
    # fused reduce+apply plane (docs/tensor-fusion.md §fused apply): the
    # front-end opt-in; the fused-vs-split execution strategy inside the
    # armed plane belongs to the autotune ladder, not this env
    fused_apply: bool = False
    # sharding plane (docs/sharding.md): the 2-D mesh grammar and the
    # ZeRO-1 partitioned-optimizer opt-in
    mesh: str = "batch"
    zero1: bool = False
    timeline_path: str = ""
    timeline_mark_cycles: bool = False
    timeline_all_ranks: bool = False
    clock_sync_interval_s: float = 30.0
    jax_profile_dir: str = ""
    stall_check_disable: bool = False
    stall_warning_time_s: float = STALL_WARNING_TIME_S
    stall_shutdown_time_s: float = 0.0  # 0 = warn forever, never abort
    heartbeat_interval_s: float = 1.0
    # hierarchical negotiation tree (docs/hierarchy.md): control-plane
    # topology — "flat", "auto", or "islands:N" (validated at init)
    hierarchy: str = "flat"
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    compression: str = "none"
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    autotune: bool = False
    autotune_log: str = ""
    # closed-loop tuning plane (docs/autotune.md)
    autotune_backend: str = "policy"
    autotune_window: int = 5
    autotune_cooldown: int = 5
    autotune_tolerance: float = 0.05
    autotune_decisions: str = ""
    autotune_codecs: tuple = ()
    autotune_codec_min_bytes: int = 4096
    autotune_fault: str = ""
    straggler_evict: str = "off"
    straggler_window_s: float = 30.0
    straggler_min_cycles: int = 20
    # data-plane integrity plane (docs/integrity.md)
    grad_sentry: str = "off"
    consensus_interval_steps: int = 0
    # gradient numerics observatory (docs/tensorwatch.md)
    tensorwatch_interval_steps: int = 0
    tensorwatch_snr_floor_db: float = 20.0
    tensorwatch_snr_window: int = 5
    tensorwatch_worst_k: int = 8
    # sparse top-k gradient wire (docs/compression.md §sparse)
    sparse_topk: str = "1"
    sparse_coverage_floor: float = 0.95
    sparse_error_feedback: bool = True
    # checkpoint plane (docs/checkpoint.md)
    ckpt_push_timeout_s: float = 60.0
    ckpt_async: bool = False
    ckpt_chunk_bytes: int = 1 << 20
    ckpt_interval_steps: int = 1
    ckpt_interval_explicit: bool = False
    ckpt_dir: str = ""
    # True when HOROVOD_CACHE_CAPACITY was set explicitly: the tuner then
    # treats the capacity knob as pinned (same contract as
    # fusion_threshold_explicit below).
    cache_capacity_explicit: bool = False
    start_timeout_s: float = DEFAULT_START_TIMEOUT_S
    data_plane: str = "auto"
    metrics_port: int = 0
    metrics_interval_s: float = 2.0
    # True when HOROVOD_METRICS_INTERVAL_S was set explicitly: the
    # publisher runs iff the port or the interval was asked for (same
    # pattern as reconnect_window_explicit)
    metrics_interval_explicit: bool = False
    chaos_spec: str = ""
    reconnect_window_s: float = 5.0
    # True when HOROVOD_RECONNECT_WINDOW_S was set explicitly: the engine
    # then applies it even to XLA-data-plane worlds, which otherwise keep
    # immediate death attribution (a compiled collective cannot outlive a
    # dead peer, and on the gloo CPU test backend it can complete with
    # GARBAGE before a delayed abort lands — see ops/engine.py).
    reconnect_window_explicit: bool = False
    # An explicitly-set env knob is pinned: the autotuner treats it as fixed
    # (reference SetValue(..., fixed=true), ``parameter_manager.cc:329-336``).
    fusion_threshold_explicit: bool = False
    cycle_time_explicit: bool = False

    @staticmethod
    def from_env() -> "Config":
        return Config(
            fusion_threshold_explicit=bool(
                os.environ.get(HOROVOD_FUSION_THRESHOLD)),
            cycle_time_explicit=bool(os.environ.get(HOROVOD_CYCLE_TIME)),
            fusion_threshold_bytes=_env_int(
                HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES),
            cycle_time_ms=_env_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS),
            fusion_subbuffers=max(
                _env_int(HOROVOD_FUSION_SUBBUFFERS, 1), 1),
            fusion_subbuffers_explicit=bool(
                os.environ.get(HOROVOD_FUSION_SUBBUFFERS)),
            fused_apply=_env_bool(HOROVOD_FUSED_APPLY),
            mesh=os.environ.get(HOROVOD_MESH, "batch"),
            zero1=_env_bool(HOROVOD_ZERO),
            timeline_path=os.environ.get(HOROVOD_TIMELINE, ""),
            timeline_mark_cycles=_env_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            timeline_all_ranks=_env_bool(HOROVOD_TIMELINE_ALL_RANKS),
            clock_sync_interval_s=_env_float(HOROVOD_CLOCK_SYNC_INTERVAL,
                                             30.0),
            jax_profile_dir=os.environ.get(HOROVOD_JAX_PROFILE, ""),
            stall_check_disable=_env_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_time_s=_env_float(HOROVOD_STALL_WARNING_TIME,
                                            STALL_WARNING_TIME_S),
            stall_shutdown_time_s=_env_float(HOROVOD_STALL_SHUTDOWN_TIME,
                                             0.0),
            heartbeat_interval_s=_env_float(HOROVOD_HEARTBEAT_INTERVAL, 1.0),
            hierarchy=(os.environ.get(HOROVOD_HIERARCHY, "flat")
                       .strip().lower() or "flat"),
            hierarchical_allreduce=_env_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=_env_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            compression=(os.environ.get(HOROVOD_COMPRESSION, "none")
                         .strip().lower() or "none"),
            cache_capacity=max(_env_int(HOROVOD_CACHE_CAPACITY,
                                        DEFAULT_CACHE_CAPACITY), 0),
            autotune=_env_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG, ""),
            autotune_backend=(os.environ.get(HOROVOD_AUTOTUNE_BACKEND,
                                             "policy").strip().lower()
                              or "policy"),
            autotune_window=max(_env_int(HOROVOD_AUTOTUNE_WINDOW, 5), 1),
            autotune_cooldown=max(_env_int(HOROVOD_AUTOTUNE_COOLDOWN, 5), 0),
            autotune_tolerance=_env_float(HOROVOD_AUTOTUNE_TOLERANCE, 0.05),
            autotune_decisions=os.environ.get(HOROVOD_AUTOTUNE_DECISIONS,
                                              ""),
            autotune_codecs=tuple(
                c.strip().lower() for c in
                os.environ.get(HOROVOD_AUTOTUNE_CODECS, "").split(",")
                if c.strip()),
            autotune_codec_min_bytes=max(
                _env_int(HOROVOD_AUTOTUNE_CODEC_MIN_BYTES, 4096), 0),
            autotune_fault=os.environ.get(HOROVOD_AUTOTUNE_FAULT, ""),
            straggler_evict=(os.environ.get(HOROVOD_STRAGGLER_EVICT, "off")
                             .strip().lower() or "off"),
            straggler_window_s=_env_float(HOROVOD_STRAGGLER_WINDOW, 30.0),
            straggler_min_cycles=max(
                _env_int(HOROVOD_STRAGGLER_MIN_CYCLES, 20), 1),
            grad_sentry=(os.environ.get(HOROVOD_GRAD_SENTRY, "off")
                         .strip().lower() or "off"),
            consensus_interval_steps=max(
                _env_int(HOROVOD_CONSENSUS_INTERVAL, 0), 0),
            tensorwatch_interval_steps=max(
                _env_int(HOROVOD_TENSORWATCH_INTERVAL, 0), 0),
            tensorwatch_snr_floor_db=_env_float(
                HOROVOD_TENSORWATCH_SNR_FLOOR, 20.0),
            tensorwatch_snr_window=max(
                _env_int(HOROVOD_TENSORWATCH_SNR_WINDOW, 5), 1),
            tensorwatch_worst_k=max(
                _env_int(HOROVOD_TENSORWATCH_WORST, 8), 1),
            sparse_topk=(os.environ.get(HOROVOD_SPARSE_TOPK, "1")
                         .strip() or "1"),
            sparse_coverage_floor=_env_float(
                HOROVOD_SPARSE_COVERAGE_FLOOR, 0.95),
            sparse_error_feedback=os.environ.get(
                HOROVOD_SPARSE_ERROR_FEEDBACK, "1").strip().lower()
            not in ("0", "false"),
            ckpt_push_timeout_s=_env_float(HOROVOD_CKPT_PUSH_TIMEOUT_S, 60.0),
            ckpt_async=_env_bool(HOROVOD_CKPT_ASYNC),
            ckpt_chunk_bytes=max(
                _env_int(HOROVOD_CKPT_CHUNK_BYTES, 1 << 20), 1),
            ckpt_interval_steps=max(
                _env_int(HOROVOD_CKPT_INTERVAL_STEPS, 1), 1),
            ckpt_interval_explicit=bool(
                os.environ.get(HOROVOD_CKPT_INTERVAL_STEPS)),
            ckpt_dir=os.environ.get(HOROVOD_CKPT_DIR, ""),
            cache_capacity_explicit=bool(
                os.environ.get(HOROVOD_CACHE_CAPACITY)),
            start_timeout_s=_env_float(
                HOROVOD_START_TIMEOUT, DEFAULT_START_TIMEOUT_S),
            data_plane=os.environ.get(HOROVOD_DATA_PLANE, "auto"),
            metrics_port=max(_env_int(HOROVOD_METRICS_PORT, 0), 0),
            metrics_interval_s=_env_float(HOROVOD_METRICS_INTERVAL, 2.0),
            metrics_interval_explicit=bool(
                os.environ.get(HOROVOD_METRICS_INTERVAL)),
            chaos_spec=os.environ.get(HOROVOD_CHAOS, ""),
            reconnect_window_s=_env_float(HOROVOD_RECONNECT_WINDOW, 5.0),
            reconnect_window_explicit=bool(
                os.environ.get(HOROVOD_RECONNECT_WINDOW)),
        )
