"""Launcher subsystem: mpirun/Spark-orchestrator replacement (SURVEY §2.6).

* ``horovodrun`` CLI: ``python -m horovod_tpu.runner -np N <cmd>``
* ``run(fn, np=N)``: ship a function to N ranks, collect per-rank results
* ``run_elastic(fn, np=N, min_np=M)``: the fault-tolerant variant —
  heartbeat monitoring, relaunch-on-death, slot blacklisting
  (``horovod_tpu.elastic``, docs/elastic.md)
* ``network``: HMAC-authenticated TCP wire shared by the launcher, the
  eager collective controller, and the elastic health plane
"""

from .launcher import LaunchError, launch, main
from .run_api import WorkerFailedError, WorkerLostError, run

__all__ = ["LaunchError", "WorkerFailedError", "WorkerLostError",
           "launch", "main", "run", "run_elastic"]


def __getattr__(name):
    # Lazy: elastic.driver builds ON this package (run_api), so a
    # module-level import here would be circular.
    if name == "run_elastic":
        from ..elastic.driver import run_elastic

        return run_elastic
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
