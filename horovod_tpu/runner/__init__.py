"""Launcher subsystem: mpirun/Spark-orchestrator replacement (SURVEY §2.6).

* ``horovodrun`` CLI: ``python -m horovod_tpu.runner -np N <cmd>``
* ``run(fn, np=N)``: ship a function to N ranks, collect per-rank results
* ``network``: HMAC-authenticated TCP wire shared by the launcher and the
  eager collective controller
"""

from .launcher import LaunchError, launch, main
from .run_api import run

__all__ = ["LaunchError", "launch", "main", "run"]
