"""Authenticated TCP wire + service/client primitives (control plane).

Rebuild of ``horovod/spark/util/network.py``: the reference frames every
message as HMAC-SHA256 digest + 4-byte length + cloudpickle body
(``network.py:44-78``), serves requests on a ``ThreadingTCPServer`` bound to
a random port on all interfaces (``network.py:81-141``), and connects with
retries (``network.py:144-236``). We keep the same design — it is the control
plane for both the launcher (driver/task services) and the eager collective
controller — with a plain-pickle body (cloudpickle only where code objects
must cross, i.e. ``runner.run``'s function shipping).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct(">Q")
_DIGEST_BYTES = hashlib.sha256().digest_size


class WireError(RuntimeError):
    pass


class ConnectionClosedError(WireError):
    """The peer closed mid-message — a transport-level loss, retryable by
    callers that can reconnect (unlike decoded server error frames, which
    are deliberate and final)."""


class RemoteError:
    """Marker a service writes back when its handler raised; the client
    re-raises it as a WireError so request() never silently returns one."""

    def __init__(self, message: str) -> None:
        self.message = message


_warned_default_secret = False


def default_secret() -> bytes:
    """Per-job HMAC key (``spark/util/secret.py``): the launcher generates a
    random key and exports it (``make_secret``); standalone single-host runs
    fall back to a fixed development key — and warn loudly, once, because a
    well-known key means any local process can speak to the controller. The
    reference never runs with a shared static key (its launcher always
    distributes a random per-job secret); here the standalone path keeps
    working for tests/dev, but production jobs must come through the
    launcher or export HOROVOD_SECRET_KEY."""
    raw = os.environ.get("HOROVOD_SECRET_KEY", "")
    if raw:
        return bytes.fromhex(raw)
    global _warned_default_secret
    if not _warned_default_secret:
        _warned_default_secret = True
        import warnings

        warnings.warn(
            "HOROVOD_SECRET_KEY is not set: falling back to the fixed "
            "development HMAC key, so ANY local process can talk to the "
            "controller. Launch through horovodrun (which exports a random "
            "per-job key) or set HOROVOD_SECRET_KEY=$(python -c 'import "
            "os; print(os.urandom(32).hex())').", RuntimeWarning,
            stacklevel=2)
    return b"horovod-tpu-insecure-default-key"


def make_secret() -> str:
    return os.urandom(32).hex()


class Preserialized:
    """A response already framed for the wire. A service whose handler
    returns the *same* object to every connected rank (the controller's
    per-cycle ResponseList, the host-plane combine result) frames it once
    instead of paying pickle+HMAC per rank — at 32+ ranks that serial work
    on the coordinator dominates cycle latency."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes) -> None:
        self.payload = payload


class Wire:
    """HMAC digest + 8-byte big-endian length + pickled body
    (reference ``Wire``, ``network.py:44-78``)."""

    def __init__(self, secret: Optional[bytes] = None) -> None:
        self._secret = secret if secret is not None else default_secret()
        # Cumulative framed bytes through this wire, for control-plane
        # observability (the response-cache bypass is sized by exactly
        # these counters; see ControllerClient.negotiation_bytes). Plain
        # ints under the GIL — callers read deltas, not exact snapshots.
        self.tx_bytes = 0
        self.rx_bytes = 0

    def frame(self, obj: Any) -> bytes:
        return self.frame_raw(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

    def frame_raw(self, body: bytes) -> bytes:
        """Frame pre-encoded bytes (the native controller's binary bodies
        ride the identical HMAC + u64-length framing, minus pickle)."""
        digest = hmac.new(self._secret, body, hashlib.sha256).digest()
        return digest + _LEN.pack(len(body)) + body

    def read_raw(self, sock: socket.socket) -> bytes:
        """Read one authenticated frame, returning the body bytes verbatim
        (no unpickling)."""
        header = _read_exact(sock, _DIGEST_BYTES + _LEN.size)
        digest = header[:_DIGEST_BYTES]
        (length,) = _LEN.unpack(header[_DIGEST_BYTES:])
        body = _read_exact(sock, length)
        expected = hmac.new(self._secret, body, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            raise WireError("message HMAC mismatch (wrong or missing secret)")
        self.rx_bytes += _DIGEST_BYTES + _LEN.size + length
        return body

    def write(self, obj: Any, sock: socket.socket) -> None:
        if isinstance(obj, Preserialized):
            self.tx_bytes += len(obj.payload)
            sock.sendall(obj.payload)
            return
        data = self.frame(obj)
        self.tx_bytes += len(data)
        sock.sendall(data)

    def read(self, sock: socket.socket) -> Any:
        header = _read_exact(sock, _DIGEST_BYTES + _LEN.size)
        digest, (length,) = header[:_DIGEST_BYTES], _LEN.unpack(header[_DIGEST_BYTES:])
        body = _read_exact(sock, length)
        expected = hmac.new(self._secret, body, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            raise WireError("message HMAC mismatch (wrong or missing secret)")
        self.rx_bytes += _DIGEST_BYTES + _LEN.size + length
        try:
            return pickle.loads(body)
        except Exception as exc:  # noqa: BLE001 - diagnose, then fail
            import logging

            # An authenticated but unpicklable body is almost always the
            # native binary-protocol controller client talking to a Python
            # service: the HOROVOD_NATIVE_CONTROLLER decision diverged
            # across ranks. Say so — the peer only sees a closed connection.
            logging.getLogger("horovod_tpu").warning(
                "authenticated message with unpicklable body (%s); if the "
                "peer runs the native controller client, "
                "HOROVOD_NATIVE_CONTROLLER diverged across ranks — set it "
                "to 0 or 1 explicitly on every rank.", exc)
            raise WireError(f"unpicklable message body: {exc}") from exc


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosedError("connection closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def local_addresses() -> Dict[str, str]:
    """IPv4 address of every NIC, keyed by interface name — the reference
    advertises every interface so peers can find a routable one
    (``network.py:117-141`` uses psutil; here the Linux SIOCGIFCONF ioctl
    with a hostname+loopback fallback for other platforms)."""
    addrs: Dict[str, str] = {}
    try:
        import array
        import fcntl

        SIOCGIFCONF = 0x8912
        IFREQ = 40  # sizeof(struct ifreq) on LP64
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            buf = array.array("B", b"\0" * (64 * IFREQ))
            out_len = struct.unpack(
                "iL", fcntl.ioctl(
                    s.fileno(), SIOCGIFCONF,
                    struct.pack("iL", len(buf), buf.buffer_info()[0])))[0]
            raw = buf.tobytes()
            for off in range(0, out_len, IFREQ):
                name = raw[off:off + 16].split(b"\0", 1)[0].decode()
                addrs[name] = socket.inet_ntoa(raw[off + 20:off + 24])
    except Exception:  # noqa: BLE001 - non-Linux / restricted environments
        pass
    if not addrs:
        addrs["lo"] = "127.0.0.1"
        try:
            addrs["host"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            pass
    return addrs


def probe_addresses(candidates: Dict[str, Tuple[str, int]],
                    timeout_s: float = 2.0) -> Dict[str, Tuple[str, int]]:
    """Probe every candidate ``(addr, port)`` with a parallel TCP connect
    and return the reachable subset — the reference's interface-matching
    probe (``BasicClient._probe``, ``network.py:144-236``; the ring probe
    of ``spark/__init__.py:35-52`` runs this against the next task)."""
    reachable: Dict[str, Tuple[str, int]] = {}
    lock = threading.Lock()

    def _try(intf: str, addr: Tuple[str, int]) -> None:
        try:
            with socket.create_connection(addr, timeout=timeout_s):
                pass
        except OSError:
            return
        with lock:
            reachable[intf] = addr

    threads = [threading.Thread(target=_try, args=item, daemon=True)
               for item in candidates.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s + 1.0)
    return reachable


class BasicService:
    """Threaded TCP request/response server on a random port
    (reference ``BasicService``, ``network.py:81-141``).

    ``handler(request, connection)`` returns the response object to write
    back, or ``None`` for one-way requests.
    """

    def __init__(self, name: str,
                 handler: Callable[[Any, socket.socket], Any],
                 secret: Optional[bytes] = None,
                 port: int = 0,
                 bind_host: str = "127.0.0.1",
                 on_disconnect: Optional[Callable[[socket.socket], None]]
                 = None,
                 listen_fd: Optional[int] = None) -> None:
        """``listen_fd``: adopt an ALREADY-LISTENING socket inherited from
        the launcher instead of binding ``port`` — the fix for the
        launcher's probe-then-rebind TOCTOU race (the port cannot be lost
        between probe and bind because it is never released; peers that
        dialed before this service started sit in the kernel backlog).
        The service owns the fd from here on (server_close closes it)."""
        self.name = name
        # The wire deserializes pickle: loopback-only by default, and a
        # non-loopback bind demands a real per-job secret — the hardcoded
        # development key must never authenticate network peers.
        if bind_host not in ("127.0.0.1", "localhost") and (
                secret is None or secret == b"horovod-tpu-insecure-default-key"):
            raise ValueError(
                f"refusing to bind service {name!r} on {bind_host!r} with "
                f"the default development secret; export HOROVOD_SECRET_KEY "
                f"(the launcher does this automatically).")
        self._wire = Wire(secret)
        self._handler = handler
        self._on_disconnect = on_disconnect
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._monitor_stop = threading.Event()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many requests
                sock = self.request
                # Cycle messages are small request/response pairs; Nagle +
                # delayed-ACK would add tens of ms per cycle.
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_lock:
                    outer._conns.add(sock)
                try:
                    while True:
                        req = outer._wire.read(sock)
                        try:
                            resp = outer._handler(req, sock)
                        except Exception as exc:  # noqa: BLE001
                            resp = RemoteError(f"{type(exc).__name__}: {exc}")
                        if resp is not None:
                            outer._wire.write(resp, sock)
                except (WireError, OSError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(sock)
                    outer._notify_disconnect(sock)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # Every rank connects at t0; the default backlog of 5 overflows
            # at ~16+ ranks and the kernel drops SYNs, adding 1s retransmit
            # stalls to world start and the first cycle.
            request_queue_size = 128

        if listen_fd is not None:
            # bind_and_activate=False: the server must not bind a fresh
            # socket — it adopts the inherited, already-listening one.
            self._server = _Server((bind_host, port), _Handler,
                                   bind_and_activate=False)
            self._server.socket.close()
            self._server.socket = socket.socket(fileno=listen_fd)
            self._server.server_address = self._server.socket.getsockname()
        else:
            self._server = _Server((bind_host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"{name}-service",
            daemon=True)
        self._thread.start()
        if on_disconnect is not None:
            # Liveness monitor: a handler thread blocked inside the handler
            # (e.g. a collective rendezvous waiting on OTHER ranks) is not
            # reading its socket, so a peer that dies mid-rendezvous would
            # go unnoticed and deadlock the world. Peek every connection for
            # EOF out-of-band — MSG_PEEK never consumes a pipelined request.
            self._monitor = threading.Thread(
                target=self._monitor_loop, name=f"{name}-liveness",
                daemon=True)
            self._monitor.start()

    def _notify_disconnect(self, sock: socket.socket) -> None:
        """Idempotence is the callback's job (disconnects are observed both
        by the handler thread and the liveness monitor)."""
        if self._on_disconnect is None:
            return
        try:
            self._on_disconnect(sock)
        except Exception:  # noqa: BLE001 - teardown path must not raise
            pass

    # MSG_DONTWAIT makes the peek non-blocking per call without touching the
    # socket's blocking mode (which the handler thread relies on). It is
    # POSIX-only. Without it there is no race-free out-of-band peek (a
    # select-then-peek can block if the handler thread consumes the bytes
    # in between), so non-POSIX platforms degrade to in-band detection by
    # the handler threads — degraded, never wedged.
    _MSG_DONTWAIT = getattr(socket, "MSG_DONTWAIT", None)

    def _monitor_loop(self) -> None:
        if self._MSG_DONTWAIT is None:  # pragma: no cover - non-POSIX
            import logging

            logging.getLogger("horovod_tpu").warning(
                "socket.MSG_DONTWAIT unavailable on this platform; "
                "out-of-band peer-death detection is disabled (dead ranks "
                "are still detected when their handler thread next reads).")
            return
        while not self._monitor_stop.wait(0.2):
            with self._conns_lock:
                conns = list(self._conns)
            for sock in conns:
                # A non-blocking MSG_PEEK never consumes a pipelined request
                # and never blocks even if the handler thread raced us to
                # the bytes; EOF shows as an empty read.
                try:
                    data = sock.recv(1, socket.MSG_PEEK | self._MSG_DONTWAIT)
                except (BlockingIOError, InterruptedError):
                    continue  # alive, no pending bytes
                except (OSError, ValueError):
                    self._notify_disconnect(sock)  # reset / already closed
                    continue
                if data == b"":  # orderly EOF: the peer process is gone
                    self._notify_disconnect(sock)

    @property
    def wire(self) -> Wire:
        """The service's framing wire — lets a handler pre-frame responses
        it will hand to many connections (see ``Preserialized``)."""
        return self._wire

    def addresses(self) -> Dict[str, Tuple[str, int]]:
        return {k: (v, self.port) for k, v in local_addresses().items()}

    def shutdown(self) -> None:
        self._monitor_stop.set()
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    """Persistent client connection with connect retries
    (reference ``BasicClient``, ``network.py:144-236``).

    ``addr`` may be a single ``(host, port)`` or a dict of candidates
    ``{intf: (host, port)}`` — multiple candidates are probed in parallel
    each attempt and the first reachable one wins, which is how a worker
    finds a routable path to a service that advertised every NIC."""

    def __init__(self, addr,
                 secret: Optional[bytes] = None,
                 attempts: int = 10,
                 retry_delay_s: float = 0.3,
                 timeout_s: Optional[float] = None) -> None:
        self._wire = Wire(secret)
        self._lock = threading.Lock()
        candidates: Dict[str, Tuple[str, int]] = (
            dict(addr) if isinstance(addr, dict) else {"addr": tuple(addr)})
        self.connected_intf: Optional[str] = None
        last_err: Optional[Exception] = None
        if not candidates:
            raise WireError("no service addresses given (empty candidate "
                            "list — check HOROVOD_CONTROLLER_ADDR)")
        for _ in range(attempts):
            if len(candidates) > 1:
                reachable = probe_addresses(
                    candidates, timeout_s=min(timeout_s or 2.0, 2.0))
                if not reachable:
                    last_err = OSError(
                        f"no candidate reachable within probe timeout "
                        f"(tried {sorted(candidates.values())})")
            else:
                reachable = candidates
            for intf, target in reachable.items():
                try:
                    self._sock = socket.create_connection(
                        target, timeout=timeout_s)
                    self._sock.settimeout(timeout_s)
                    self._sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    self.connected_intf = intf
                    return
                except OSError as exc:
                    last_err = exc
            time.sleep(retry_delay_s)
        raise WireError(
            f"unable to connect to service at any of "
            f"{sorted(candidates.values())}: {last_err}")

    def enable_keepalive(self, idle_s: int = 60, interval_s: int = 20,
                         count: int = 3) -> None:
        """TCP keepalive for long-idle connections (the controller watch
        channel parks with zero traffic for the whole job): keeps NAT /
        conntrack mappings alive and turns a silent middlebox drop into a
        detectable error instead of a black hole."""
        s = self._sock
        s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for opt, val in (("TCP_KEEPIDLE", idle_s),
                         ("TCP_KEEPINTVL", interval_s),
                         ("TCP_KEEPCNT", count)):
            if hasattr(socket, opt):  # Linux; other platforms keep defaults
                s.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)

    def request(self, obj: Any) -> Any:
        with self._lock:
            self._wire.write(obj, self._sock)
            resp = self._wire.read(self._sock)
        if isinstance(resp, RemoteError):
            raise WireError(f"service-side failure: {resp.message}")
        return resp

    def request_raw(self, body: bytes) -> bytes:
        """One round-trip of pre-encoded bytes over the same framing (the
        native controller client's path)."""
        with self._lock:
            self._sock.sendall(self._wire.frame_raw(body))
            return self._wire.read_raw(self._sock)

    def send(self, obj: Any) -> None:
        with self._lock:
            self._wire.write(obj, self._sock)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
