"""Authenticated TCP wire + service/client primitives (control plane).

Rebuild of ``horovod/spark/util/network.py``: the reference frames every
message as HMAC-SHA256 digest + 4-byte length + cloudpickle body
(``network.py:44-78``), serves requests on a ``ThreadingTCPServer`` bound to
a random port on all interfaces (``network.py:81-141``), and connects with
retries (``network.py:144-236``). We keep the same design — it is the control
plane for both the launcher (driver/task services) and the eager collective
controller — with a plain-pickle body (cloudpickle only where code objects
must cross, i.e. ``runner.run``'s function shipping).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ..obs import flightrec as _flightrec
from ..obs.registry import Counter, registry as _metrics

_LEN = struct.Struct(">Q")
_DIGEST_BYTES = hashlib.sha256().digest_size

# Process-wide wire totals (docs/metrics.md): every authenticated frame
# through ANY Wire in this process counts here, alongside the per-wire
# counters the Wire properties read. Registered once at import — the obs
# registry is stdlib-only, so this module stays importable without jax.
_WIRE_TX = _metrics().counter(
    "horovod_wire_tx_bytes_total",
    "Framed bytes sent over every authenticated control-plane wire")
_WIRE_RX = _metrics().counter(
    "horovod_wire_rx_bytes_total",
    "Framed bytes received over every authenticated control-plane wire")
_RECONNECT_ATTEMPTS = _metrics().counter(
    "horovod_reconnect_attempts_total",
    "Transparent-reconnect dial attempts after a transport fault")
_RECONNECTS_HEALED = _metrics().counter(
    "horovod_reconnects_healed_total",
    "Transport faults healed by a successful reconnect + re-identify")
_RECONNECT_FAILURES = _metrics().counter(
    "horovod_reconnect_failures_total",
    "Reconnect episodes that exhausted the backoff budget")


class WireError(RuntimeError):
    pass


class ConnectionClosedError(WireError):
    """The peer closed mid-message — a transport-level loss, retryable by
    callers that can reconnect (unlike decoded server error frames, which
    are deliberate and final)."""


class CorruptFrameError(WireError):
    """An authenticated frame failed HMAC verification. Either the secret
    is wrong (every frame fails, the retry budget exhausts immediately) or
    the frame was damaged in transit — a transport-level loss after which
    the stream cannot be trusted, so the client latches broken and
    reconnects like any other transport fault."""


class RemoteError:
    """Marker a service writes back when its handler raised; the client
    re-raises it as a WireError so request() never silently returns one."""

    def __init__(self, message: str) -> None:
        self.message = message


_warned_default_secret = False


def default_secret() -> bytes:
    """Per-job HMAC key (``spark/util/secret.py``): the launcher generates a
    random key and exports it (``make_secret``); standalone single-host runs
    fall back to a fixed development key — and warn loudly, once, because a
    well-known key means any local process can speak to the controller. The
    reference never runs with a shared static key (its launcher always
    distributes a random per-job secret); here the standalone path keeps
    working for tests/dev, but production jobs must come through the
    launcher or export HOROVOD_SECRET_KEY."""
    from ..core.config import HOROVOD_SECRET_KEY

    raw = os.environ.get(HOROVOD_SECRET_KEY, "")
    if raw:
        return bytes.fromhex(raw)
    global _warned_default_secret
    if not _warned_default_secret:
        _warned_default_secret = True
        import warnings

        warnings.warn(
            "HOROVOD_SECRET_KEY is not set: falling back to the fixed "
            "development HMAC key, so ANY local process can talk to the "
            "controller. Launch through horovodrun (which exports a random "
            "per-job key) or set HOROVOD_SECRET_KEY=$(python -c 'import "
            "os; print(os.urandom(32).hex())').", RuntimeWarning,
            stacklevel=2)
    return b"horovod-tpu-insecure-default-key"


def make_secret() -> str:
    return os.urandom(32).hex()


class Preserialized:
    """A response already framed for the wire. A service whose handler
    returns the *same* object to every connected rank (the controller's
    per-cycle ResponseList, the host-plane combine result) frames it once
    instead of paying pickle+HMAC per rank — at 32+ ranks that serial work
    on the coordinator dominates cycle latency."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes) -> None:
        self.payload = payload


class Wire:
    """HMAC digest + 8-byte big-endian length + pickled body
    (reference ``Wire``, ``network.py:44-78``)."""

    def __init__(self, secret: Optional[bytes] = None) -> None:
        self._secret = secret if secret is not None else default_secret()
        # Cumulative framed bytes through this wire, for control-plane
        # observability (the response-cache bypass is sized by exactly
        # these counters; see ControllerClient.negotiation_bytes).
        # Registry Counter primitives, not bare ints: a service's wire is
        # shared by every connection handler thread, and the old unlocked
        # `+=` could silently undercount under that interleaving. The
        # public tx_bytes/rx_bytes attributes live on as read-through
        # properties below.
        self._tx = Counter()
        self._rx = Counter()
        # Optional fault injector (``horovod_tpu.chaos``): hooks at the
        # frame boundary, None-cost when absent. Installed only on client
        # wires whose owning BasicClient was built with chaos enabled.
        self.chaos = None

    @property
    def tx_bytes(self) -> int:
        return self._tx.value

    @property
    def rx_bytes(self) -> int:
        return self._rx.value

    def frame(self, obj: Any) -> bytes:
        return self.frame_raw(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

    def frame_raw(self, body: bytes) -> bytes:
        """Frame pre-encoded bytes (the native controller's binary bodies
        ride the identical HMAC + u64-length framing, minus pickle)."""
        digest = hmac.new(self._secret, body, hashlib.sha256).digest()
        return digest + _LEN.pack(len(body)) + body

    def _read_body(self, sock: socket.socket) -> bytes:
        """Read one frame and verify its HMAC (chaos hooks bracket the
        reads: delay before the header, corrupt/drop after the body)."""
        if self.chaos is not None:
            self.chaos.on_recv_begin(sock)
        header = _read_exact(sock, _DIGEST_BYTES + _LEN.size)
        digest = header[:_DIGEST_BYTES]
        (length,) = _LEN.unpack(header[_DIGEST_BYTES:])
        body = _read_exact(sock, length)
        if self.chaos is not None:
            body = self.chaos.on_recv_frame(body)
        expected = hmac.new(self._secret, body, hashlib.sha256).digest()
        if not hmac.compare_digest(digest, expected):
            raise CorruptFrameError(
                "message HMAC mismatch (wrong or missing secret, or a "
                "frame damaged in transit)")
        n = _DIGEST_BYTES + _LEN.size + length
        self._rx.inc(n)
        _WIRE_RX.inc(n)
        return body

    def read_raw(self, sock: socket.socket) -> bytes:
        """Read one authenticated frame, returning the body bytes verbatim
        (no unpickling)."""
        return self._read_body(sock)

    def write(self, obj: Any, sock: socket.socket) -> None:
        if isinstance(obj, Preserialized):
            self.write_frame(obj.payload, sock)
            return
        self.write_frame(self.frame(obj), sock)

    def write_frame(self, frame: bytes, sock: socket.socket) -> None:
        """Send an already-framed message (counts tx bytes; chaos close
        faults fire here, before any byte leaves)."""
        if self.chaos is not None:
            self.chaos.on_send(sock)
        self._tx.inc(len(frame))
        _WIRE_TX.inc(len(frame))
        sock.sendall(frame)

    def read(self, sock: socket.socket) -> Any:
        body = self._read_body(sock)
        try:
            return pickle.loads(body)
        except Exception as exc:  # noqa: BLE001 - diagnose, then fail
            import logging

            # An authenticated but unpicklable body is almost always the
            # native binary-protocol controller client talking to a Python
            # service: the HOROVOD_NATIVE_CONTROLLER decision diverged
            # across ranks. Say so — the peer only sees a closed connection.
            logging.getLogger("horovod_tpu").warning(
                "authenticated message with unpicklable body (%s); if the "
                "peer runs the native controller client, "
                "HOROVOD_NATIVE_CONTROLLER diverged across ranks — set it "
                "to 0 or 1 explicitly on every rank.", exc)
            raise WireError(f"unpicklable message body: {exc}") from exc


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosedError("connection closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def local_addresses() -> Dict[str, str]:
    """IPv4 address of every NIC, keyed by interface name — the reference
    advertises every interface so peers can find a routable one
    (``network.py:117-141`` uses psutil; here the Linux SIOCGIFCONF ioctl
    with a hostname+loopback fallback for other platforms)."""
    addrs: Dict[str, str] = {}
    try:
        import array
        import fcntl

        SIOCGIFCONF = 0x8912
        IFREQ = 40  # sizeof(struct ifreq) on LP64
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            buf = array.array("B", b"\0" * (64 * IFREQ))
            out_len = struct.unpack(
                "iL", fcntl.ioctl(
                    s.fileno(), SIOCGIFCONF,
                    struct.pack("iL", len(buf), buf.buffer_info()[0])))[0]
            raw = buf.tobytes()
            for off in range(0, out_len, IFREQ):
                name = raw[off:off + 16].split(b"\0", 1)[0].decode()
                addrs[name] = socket.inet_ntoa(raw[off + 20:off + 24])
    except Exception:  # noqa: BLE001 - non-Linux / restricted environments
        pass
    if not addrs:
        addrs["lo"] = "127.0.0.1"
        try:
            addrs["host"] = socket.gethostbyname(socket.gethostname())
        except OSError:
            pass
    return addrs


def probe_addresses(candidates: Dict[str, Tuple[str, int]],
                    timeout_s: float = 2.0) -> Dict[str, Tuple[str, int]]:
    """Probe every candidate ``(addr, port)`` with a parallel TCP connect
    and return the reachable subset — the reference's interface-matching
    probe (``BasicClient._probe``, ``network.py:144-236``; the ring probe
    of ``spark/__init__.py:35-52`` runs this against the next task)."""
    reachable: Dict[str, Tuple[str, int]] = {}
    lock = threading.Lock()

    def _try(intf: str, addr: Tuple[str, int]) -> None:
        try:
            with socket.create_connection(addr, timeout=timeout_s):
                pass
        except OSError:
            return
        with lock:
            reachable[intf] = addr

    threads = [threading.Thread(target=_try, args=item, daemon=True)
               for item in candidates.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s + 1.0)
    return reachable


# Responses above this size are NOT retained for dedup replay (only a
# sentinel survives): the slot holds its client's last response until the
# client's NEXT request supersedes it — milliseconds in steady state, but
# a departed client's slot survives until LRU displacement, which would
# pin a fusion-threshold-sized payload frame (64MB default) for the rest
# of the job. A replayed request whose oversized response was not
# retained gets a deliberate RemoteError instead (escalation, not a
# hang): losing that response takes a transport fault in the one cycle
# whose payload exceeded the cap — rarer than the leak it prevents.
_RPC_RETAIN_MAX_BYTES = 1 << 20


class _NotRetained:
    """Sentinel slot.resp for an oversized response (see above)."""

    __slots__ = ()


_NOT_RETAINED = _NotRetained()


class _RpcSlot:
    """Dedup state for one client's latest sequenced request: the seq, the
    completed response object (re-framed on replay — response objects are
    shared/immutable by contract, so this retains no extra copies while
    the response is otherwise alive; oversized frames are dropped to a
    sentinel, see ``_RPC_RETAIN_MAX_BYTES``), and a done event duplicate
    arrivals park on while the first invocation is still running."""

    __slots__ = ("seq", "resp", "done")

    def __init__(self, seq: int) -> None:
        self.seq = seq
        self.resp = None
        self.done = threading.Event()


class BasicService:
    """Threaded TCP request/response server on a random port
    (reference ``BasicService``, ``network.py:81-141``).

    ``handler(request, connection)`` returns the response object to write
    back, or ``None`` for one-way requests.

    Self-healing wire: requests arriving inside a ``("#rpc", client_id,
    seq, obj)`` envelope (every ``BasicClient.request``) are deduplicated —
    a client that lost a response to a transport fault reconnects and
    resends the SAME seq, and the service replays the stored response
    instead of re-invoking the handler. That exactly-once handler contract
    is what makes transparent client retry safe for non-idempotent
    requests (controller cycles: table insertions and cache-bit
    transitions must never double-apply). One slot per client suffices:
    the client lock serializes its requests. A resend that arrives while
    the FIRST invocation is still running (post-timeout retry against a
    slow handler) parks until it completes and replays its response —
    never a second invocation, never a stale pairing."""

    def __init__(self, name: str,
                 handler: Callable[[Any, socket.socket], Any],
                 secret: Optional[bytes] = None,
                 port: int = 0,
                 bind_host: str = "127.0.0.1",
                 on_disconnect: Optional[Callable[[socket.socket], None]]
                 = None,
                 listen_fd: Optional[int] = None) -> None:
        """``listen_fd``: adopt an ALREADY-LISTENING socket inherited from
        the launcher instead of binding ``port`` — the fix for the
        launcher's probe-then-rebind TOCTOU race (the port cannot be lost
        between probe and bind because it is never released; peers that
        dialed before this service started sit in the kernel backlog).
        The service owns the fd from here on (server_close closes it)."""
        self.name = name
        # The wire deserializes pickle: loopback-only by default, and a
        # non-loopback bind demands a real per-job secret — the hardcoded
        # development key must never authenticate network peers.
        if bind_host not in ("127.0.0.1", "localhost") and (
                secret is None or secret == b"horovod-tpu-insecure-default-key"):
            raise ValueError(
                f"refusing to bind service {name!r} on {bind_host!r} with "
                f"the default development secret; export HOROVOD_SECRET_KEY "
                f"(the launcher does this automatically).")
        self._wire = Wire(secret)
        self._handler = handler
        self._on_disconnect = on_disconnect
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._monitor_stop = threading.Event()
        self._rpc_lock = threading.Lock()
        # client_id -> _RpcSlot, LRU-bounded (a departed client's last
        # response is retained until enough new clients displace it)
        self._rpc_slots: "OrderedDict[str, _RpcSlot]" = OrderedDict()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many requests
                sock = self.request
                # Cycle messages are small request/response pairs; Nagle +
                # delayed-ACK would add tens of ms per cycle.
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_lock:
                    outer._conns.add(sock)
                try:
                    while True:
                        req = outer._wire.read(sock)
                        slot = None
                        if type(req) is tuple and len(req) == 4 and \
                                req[0] == "#rpc":
                            _tag, client_id, seq, req = req
                            slot, replayed = outer._rpc_claim(client_id, seq)
                            if replayed:
                                # duplicate of an earlier request: wait out
                                # a still-running first invocation, then
                                # replay its response — never re-invoke
                                slot.done.wait()
                                if slot.resp is _NOT_RETAINED:
                                    outer._wire.write(RemoteError(
                                        "response exceeded the dedup "
                                        "retention cap and its original "
                                        "frame was lost in transit — "
                                        "cannot replay"), sock)
                                elif slot.resp is not None:
                                    outer._wire.write(slot.resp, sock)
                                continue
                        try:
                            resp = outer._handler(req, sock)
                        except Exception as exc:  # noqa: BLE001
                            resp = RemoteError(f"{type(exc).__name__}: {exc}")
                        if slot is not None:
                            # store BEFORE the write: if this connection is
                            # already dead, the retry on a fresh connection
                            # must still find the response
                            slot.resp = resp
                            if isinstance(resp, Preserialized) and \
                                    len(resp.payload) > _RPC_RETAIN_MAX_BYTES:
                                slot.resp = _NOT_RETAINED
                            slot.done.set()
                        if resp is not None:
                            outer._wire.write(resp, sock)
                except (WireError, OSError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(sock)
                    outer._notify_disconnect(sock)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # Every rank connects at t0; the default backlog of 5 overflows
            # at ~16+ ranks and the kernel drops SYNs, adding 1s retransmit
            # stalls to world start and the first cycle.
            request_queue_size = 128

        if listen_fd is not None:
            # bind_and_activate=False: the server must not bind a fresh
            # socket — it adopts the inherited, already-listening one.
            self._server = _Server((bind_host, port), _Handler,
                                   bind_and_activate=False)
            self._server.socket.close()
            self._server.socket = socket.socket(fileno=listen_fd)
            self._server.server_address = self._server.socket.getsockname()
        else:
            self._server = _Server((bind_host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"{name}-service",
            daemon=True)
        self._thread.start()
        if on_disconnect is not None:
            # Liveness monitor: a handler thread blocked inside the handler
            # (e.g. a collective rendezvous waiting on OTHER ranks) is not
            # reading its socket, so a peer that dies mid-rendezvous would
            # go unnoticed and deadlock the world. Peek every connection for
            # EOF out-of-band — MSG_PEEK never consumes a pipelined request.
            self._monitor = threading.Thread(
                target=self._monitor_loop, name=f"{name}-liveness",
                daemon=True)
            self._monitor.start()

    # Enough for every rank's controller client plus tooling; a real
    # world holds `size` live clients, far below the cap.
    _RPC_CLIENT_CAP = 1024

    def _rpc_claim(self, client_id: str, seq: int):
        """Claim or replay a sequenced request. Returns ``(slot,
        replayed)``: ``replayed=False`` means the caller owns the (new)
        slot and must invoke the handler; ``True`` means wait on
        ``slot.done`` and resend ``slot.resp``."""
        with self._rpc_lock:
            slot = self._rpc_slots.get(client_id)
            if slot is not None and seq == slot.seq:
                return slot, True
            if slot is not None and seq < slot.seq:
                # a sequential client can never legitimately regress; a
                # stale seq means the stream is confused — refuse loudly
                # rather than re-apply an old request
                stale = _RpcSlot(seq)
                stale.resp = RemoteError(
                    f"stale rpc seq {seq} (already at {slot.seq})")
                stale.done.set()
                return stale, True
            fresh = _RpcSlot(seq)
            self._rpc_slots[client_id] = fresh
            self._rpc_slots.move_to_end(client_id)
            if len(self._rpc_slots) > self._RPC_CLIENT_CAP:
                # LRU displacement must skip slots whose first invocation
                # is still running: evicting one lets that client's retry
                # claim a fresh slot and re-invoke the handler — the
                # double-apply the dedup layer exists to prevent. The cap
                # may be transiently exceeded by in-flight slots.
                for cid, s in list(self._rpc_slots.items()):
                    if len(self._rpc_slots) <= self._RPC_CLIENT_CAP:
                        break
                    if s.done.is_set():
                        del self._rpc_slots[cid]
            return fresh, False

    def _notify_disconnect(self, sock: socket.socket) -> None:
        """Idempotence is the callback's job (disconnects are observed both
        by the handler thread and the liveness monitor)."""
        if self._on_disconnect is None:
            return
        try:
            self._on_disconnect(sock)
        except Exception:  # noqa: BLE001 - teardown path must not raise
            pass

    # MSG_DONTWAIT makes the peek non-blocking per call without touching the
    # socket's blocking mode (which the handler thread relies on). It is
    # POSIX-only. Without it there is no race-free out-of-band peek (a
    # select-then-peek can block if the handler thread consumes the bytes
    # in between), so non-POSIX platforms degrade to in-band detection by
    # the handler threads — degraded, never wedged.
    _MSG_DONTWAIT = getattr(socket, "MSG_DONTWAIT", None)

    def _monitor_loop(self) -> None:
        if self._MSG_DONTWAIT is None:  # pragma: no cover - non-POSIX
            import logging

            logging.getLogger("horovod_tpu").warning(
                "socket.MSG_DONTWAIT unavailable on this platform; "
                "out-of-band peer-death detection is disabled (dead ranks "
                "are still detected when their handler thread next reads).")
            return
        while not self._monitor_stop.wait(0.2):
            with self._conns_lock:
                conns = list(self._conns)
            for sock in conns:
                # A non-blocking MSG_PEEK never consumes a pipelined request
                # and never blocks even if the handler thread raced us to
                # the bytes; EOF shows as an empty read.
                try:
                    data = sock.recv(1, socket.MSG_PEEK | self._MSG_DONTWAIT)
                except (BlockingIOError, InterruptedError):
                    continue  # alive, no pending bytes
                except (OSError, ValueError):
                    self._notify_disconnect(sock)  # reset / already closed
                    continue
                if data == b"":  # orderly EOF: the peer process is gone
                    self._notify_disconnect(sock)

    @property
    def wire(self) -> Wire:
        """The service's framing wire — lets a handler pre-frame responses
        it will hand to many connections (see ``Preserialized``)."""
        return self._wire

    def addresses(self) -> Dict[str, Tuple[str, int]]:
        return {k: (v, self.port) for k, v in local_addresses().items()}

    def shutdown(self) -> None:
        self._monitor_stop.set()
        self._server.shutdown()
        self._server.server_close()

    def close_connections(self) -> None:
        """Hard-close every ACCEPTED connection (``shutdown`` only stops
        the listener). The recovery plane's succession drill needs both:
        a head that stops serving must kill its members' established
        connections too, or their parked requests would wait on a dead
        service instead of failing over to the standby (docs/recovery.md).
        Clients see a clean transport EOF and retry under the same seq."""
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class ReconnectPolicy:
    """Bounded exponential backoff budget for transparent reconnect."""

    __slots__ = ("attempts", "backoff_s", "max_backoff_s")

    def __init__(self, attempts: int = 6, backoff_s: float = 0.2,
                 max_backoff_s: float = 2.0) -> None:
        self.attempts = max(int(attempts), 1)
        self.backoff_s = max(float(backoff_s), 0.0)
        self.max_backoff_s = max(float(max_backoff_s), self.backoff_s)

    @staticmethod
    def from_env() -> "ReconnectPolicy":
        # lazy import: config is a leaf module, but keep this wire layer
        # importable on its own (same idiom as connect_with_hello)
        from ..core.config import (
            HOROVOD_RECONNECT_ATTEMPTS,
            HOROVOD_RECONNECT_BACKOFF,
            HOROVOD_RECONNECT_MAX_BACKOFF,
            _env_float,
        )

        return ReconnectPolicy(
            attempts=int(_env_float(HOROVOD_RECONNECT_ATTEMPTS, 6)),
            backoff_s=_env_float(HOROVOD_RECONNECT_BACKOFF, 0.2),
            max_backoff_s=_env_float(HOROVOD_RECONNECT_MAX_BACKOFF, 2.0))

    def delay(self, attempt: int) -> float:
        return min(self.backoff_s * (2.0 ** max(attempt - 1, 0)),
                   self.max_backoff_s)


# Transport-level losses a reconnect can heal. Decoded server error frames
# (RemoteError -> "service-side failure") and protocol errors ("unpicklable
# message body") are DELIBERATE and final — never in this set.
# socket.timeout is a subclass of OSError on this Python.
_TRANSPORT_ERRORS = (ConnectionClosedError, CorruptFrameError, OSError)

def _reconnect_hello_timeout_s() -> float:
    """Ceiling on the re-identify hello during a reconnect, applied only
    when the client itself has no timeout (timeout_s=None). A live service
    answers a hello in microseconds; only an accepted-but-never-served
    connection (dying service's backlog) takes longer, and that one must
    fail the attempt, not hang it. Read per reconnect, like every other
    HOROVOD_* knob (env pins after import must take effect)."""
    from ..core.config import _env_float

    return _env_float("HOROVOD_RECONNECT_HELLO_TIMEOUT_S", 10.0)


class BasicClient:
    """Persistent client connection with connect retries, transparent
    reconnect, and a broken-connection latch
    (reference ``BasicClient``, ``network.py:144-236``).

    ``addr`` may be a single ``(host, port)`` or a dict of candidates
    ``{intf: (host, port)}`` — multiple candidates are probed in parallel
    each attempt and the first reachable one wins, which is how a worker
    finds a routable path to a service that advertised every NIC.

    Self-healing contract:

    * Any transport fault (EOF, reset, timeout, HMAC-corrupt frame)
      LATCHES the client broken and closes the socket — a timed-out
      request's late response can never be misread as the next request's
      answer (the stale frame dies with the socket).
    * ``request()`` retries transparently: reconnect with bounded
      exponential backoff (``ReconnectPolicy``), re-identify via the
      ``on_reconnect`` hook, and resend under the SAME sequence number —
      the service's dedup layer guarantees exactly-once handler
      invocation, so the retry is safe even for non-idempotent requests.
    * ``request_raw()`` (the native controller's binary wire, which has no
      dedup) never resends a possibly-delivered request: it latches and
      raises, and the NEXT call reconnects on a fresh stream.
    """

    def __init__(self, addr,
                 secret: Optional[bytes] = None,
                 attempts: int = 10,
                 retry_delay_s: float = 0.3,
                 timeout_s: Optional[float] = None,
                 chaos=None,
                 reconnect: Optional[ReconnectPolicy] = None,
                 fallback=None) -> None:
        """``fallback``: a second candidate set (standby island-head
        succession, docs/recovery.md) tried only during RECONNECTS, after
        every primary candidate failed the attempt — never on the initial
        dial, where a standby that binds before the primary would
        otherwise win the race and activate spuriously. The first
        successful fallback connect adopts the fallback set as the
        client's candidates for good: a primary that died stays dead for
        this client, and flapping back would split the request stream
        across two services' dedup slots."""
        self._wire = Wire(secret)
        self._lock = threading.Lock()
        self._candidates: Dict[str, Tuple[str, int]] = (
            dict(addr) if isinstance(addr, dict) else {"addr": tuple(addr)})
        self._fallback: Optional[Dict[str, Tuple[str, int]]] = (
            None if not fallback else
            dict(fallback) if isinstance(fallback, dict)
            else {"addr": tuple(fallback)})
        if not self._candidates:
            raise WireError("no service addresses given (empty candidate "
                            "list — check HOROVOD_CONTROLLER_ADDR)")
        self._connect_attempts = attempts
        self._retry_delay_s = retry_delay_s
        self._timeout_s = timeout_s
        self._policy = reconnect or ReconnectPolicy.from_env()
        self._chaos = chaos
        self._wire.chaos = chaos
        # Request dedup identity: the service keys its exactly-once replay
        # cache by (client_id, seq); seq advances once per logical request,
        # never on a retry of the same request.
        self._client_id = os.urandom(8).hex()
        self._seq = 0
        self._broken = False
        self._closed = False
        self.reconnects = 0  # observability: healed transport faults
        self.on_reconnect: Optional[Callable[["BasicClient"], None]] = None
        self.connected_intf: Optional[str] = None
        self._sock: Optional[socket.socket] = self._dial(
            rounds=attempts, reconnecting=False)

    # -- connection management ------------------------------------------------

    def _dial(self, rounds: int, reconnecting: bool) -> socket.socket:
        """One candidate-probing connect pass of up to ``rounds`` rounds."""
        last_err: Optional[Exception] = None
        candidates = self._candidates
        for _ in range(rounds):
            if self._chaos is not None:
                # One refusal per dial ATTEMPT, not per candidate:
                # refuse@relaunch:N means N failed reconnect attempts
                # (each burning a backoff iteration), however many NICs
                # an attempt probes — per-candidate consumption would
                # silently under-inject on multi-NIC worlds.
                try:
                    self._chaos.on_connect(reconnecting)
                except OSError as exc:
                    last_err = exc
                    time.sleep(self._retry_delay_s)
                    continue
            if len(candidates) > 1:
                reachable = probe_addresses(
                    candidates, timeout_s=min(self._timeout_s or 2.0, 2.0))
                if not reachable:
                    last_err = OSError(
                        f"no candidate reachable within probe timeout "
                        f"(tried {sorted(candidates.values())})")
            else:
                reachable = candidates
            for intf, target in reachable.items():
                try:
                    sock = self._connect_one(intf, target)
                except OSError as exc:
                    last_err = exc
                    continue
                return sock
            if reconnecting and self._fallback:
                # Every primary candidate failed this attempt: try the
                # standby set (docs/recovery.md). Success ADOPTS it — the
                # succeeded head never comes back for this client.
                for intf, target in self._fallback.items():
                    try:
                        sock = self._connect_one(intf, target)
                    except OSError as exc:
                        last_err = exc
                        continue
                    import logging

                    logging.getLogger("horovod_tpu").warning(
                        "failing over to standby service at %s "
                        "(primary unreachable: %s)", target, last_err)
                    self._candidates = dict(self._fallback)
                    self._fallback = None
                    return sock
            time.sleep(self._retry_delay_s)
        raise WireError(
            f"unable to connect to service at any of "
            f"{sorted(candidates.values())}: {last_err}")

    def _connect_one(self, intf: str, target) -> socket.socket:
        sock = socket.create_connection(target, timeout=self._timeout_s)
        sock.settimeout(self._timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.connected_intf = intf
        if self._chaos is not None:
            self._chaos.on_connected()
        return sock

    def _reconnect(self) -> None:
        """Replace a latched-broken connection: bounded exponential
        backoff, re-identify via ``on_reconnect``, and only then retire
        the old socket — the service must see the superseding identity
        before (or while) it notices the old connection die, and the old
        socket's teardown discards any stale buffered response."""
        old, self._sock = self._sock, None
        last_err: Optional[Exception] = None
        for attempt in range(1, self._policy.attempts + 1):
            if self._closed:
                # close() already ran and saw self._sock=None, so the
                # retired pre-fault socket is ours to release here
                if old is not None:
                    try:
                        old.close()
                    except OSError:
                        pass
                raise WireError("client closed during reconnect")
            if attempt > 1:
                time.sleep(self._policy.delay(attempt - 1))
            _RECONNECT_ATTEMPTS.inc()
            # flight recorder (docs/blackbox.md): reconnect attempts are
            # the black-box evidence behind a heal-vs-death postmortem
            _flightrec.record(_flightrec.EV_RECONNECT, aux=attempt)
            try:
                sock = self._dial(rounds=1, reconnecting=True)
            except (WireError, OSError) as exc:
                last_err = exc
                continue
            self._sock = sock
            if self.on_reconnect is not None:
                # The re-identify MUST be time-bounded even on clients
                # built with timeout_s=None (negotiation parks by design):
                # a reconnect can land in a dying service's kernel backlog
                # — connect succeeds, nobody ever serves it — and an
                # unbounded hello read would hang forever instead of
                # burning an attempt and escalating.
                if self._timeout_s is None:
                    sock.settimeout(_reconnect_hello_timeout_s())
                try:
                    self.on_reconnect(self)
                except _TRANSPORT_ERRORS as exc:
                    # the re-identify itself hit a transport fault: this
                    # attempt failed, back off and redial
                    last_err = exc
                    self._sock = None
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                except BaseException:
                    # a DECISION, not a loss (the service refused the
                    # hello: world over / restarting): propagate — but
                    # retire the pre-fault socket first, or its fd leaks
                    # for the client's remaining lifetime (close() only
                    # knows about self._sock)
                    if old is not None:
                        try:
                            old.close()
                        except OSError:
                            pass
                    raise
                finally:
                    if self._timeout_s is None and self._sock is not None:
                        sock.settimeout(None)
                # any other failure (e.g. the service refusing the hello:
                # world over / restarting) is a DECISION, not a loss —
                # propagate without burning the rest of the budget
            if self._closed:
                # close() may have landed while the new socket was not yet
                # visible to it (mid-dial, self._sock was None): finish the
                # close here, or the healed request parks forever in recv
                # on a socket close() can no longer reach.
                for stale in (sock, old):
                    if stale is not None:
                        try:
                            stale.close()
                        except OSError:
                            pass
                self._sock = None
                raise WireError("client closed during reconnect")
            self._broken = False
            self.reconnects += 1
            _RECONNECTS_HEALED.inc()
            _flightrec.record(_flightrec.EV_RECONNECT_HEALED, aux=attempt)
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            return
        self._sock = old  # keep ownership for close()
        _RECONNECT_FAILURES.inc()
        raise WireError(
            f"reconnect failed after {self._policy.attempts} attempts: "
            f"{last_err}") from last_err

    def sever(self) -> None:
        """Hard-close the live socket but keep the client USABLE: the
        next request latches the break and reconnects normally. This is
        the chaos partition primitive (docs/recovery.md) — the peer sees
        a clean EOF (its reconnect window starts) while this side's
        request path stays intact for the eventual heal. Never taken on
        the request lock: a partition must land even while a request is
        parked — the in-flight read dies with the socket, which is the
        point."""
        sock = self._sock
        self._broken = True
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def enable_keepalive(self, idle_s: int = 60, interval_s: int = 20,
                         count: int = 3) -> None:
        """TCP keepalive for long-idle connections (the controller watch
        channel parks with zero traffic for the whole job): keeps NAT /
        conntrack mappings alive and turns a silent middlebox drop into a
        detectable error instead of a black hole."""
        s = self._sock
        s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for opt, val in (("TCP_KEEPIDLE", idle_s),
                         ("TCP_KEEPINTVL", interval_s),
                         ("TCP_KEEPCNT", count)):
            if hasattr(socket, opt):  # Linux; other platforms keep defaults
                s.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)

    # -- request paths --------------------------------------------------------

    def request(self, obj: Any) -> Any:
        """One sequenced round trip with transparent retry: transport
        faults latch the connection broken, reconnect with backoff, and
        resend under the same seq (the service dedups — see
        ``BasicService``)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
            envelope = ("#rpc", self._client_id, seq, obj)
            if self._chaos is not None:
                self._chaos.begin_request()
            attempt = 0
            while True:
                try:
                    if self._broken or self._sock is None:
                        self._reconnect()
                    self._wire.write(envelope, self._sock)
                    resp = self._wire.read(self._sock)
                    break
                except _TRANSPORT_ERRORS as exc:
                    self._broken = True
                    attempt += 1
                    if self._closed or attempt > self._policy.attempts:
                        raise
                    _log_heal_attempt(exc, attempt)
                    time.sleep(self._policy.delay(attempt))
        if isinstance(resp, RemoteError):
            raise WireError(f"service-side failure: {resp.message}")
        return resp

    def request_raw(self, body: bytes) -> bytes:
        """One round-trip of pre-encoded bytes over the same framing (the
        native controller client's path). No dedup rides this wire, so a
        fault after the send is NOT retried (a resend could double-apply);
        the client latches broken and the next call reconnects — a timed-
        out request's stale response dies with the old socket instead of
        desyncing the stream."""
        with self._lock:
            if self._chaos is not None:
                self._chaos.begin_request()
            if self._broken or self._sock is None:
                self._reconnect()  # connect-phase only: nothing sent yet
            try:
                self._wire.write_frame(self._wire.frame_raw(body),
                                       self._sock)
                return self._wire.read_raw(self._sock)
            except _TRANSPORT_ERRORS:
                self._broken = True
                raise

    def farewell(self, obj: Any) -> Optional[Any]:
        """Best-effort final round trip (the clean-detach "bye"): never
        heals. A goodbye only means anything on the connection the
        service already knows; reconnecting to deliver one would re-hello
        through ``on_reconnect`` against a possibly dying service — whose
        backlog can accept the dial and never serve it — to say something
        the connection's own close already says. Returns None if the
        transport is (or becomes) broken."""
        with self._lock:
            if self._closed or self._broken or self._sock is None:
                return None
            seq = self._seq
            self._seq += 1
            envelope = ("#rpc", self._client_id, seq, obj)
            if self._chaos is not None:
                self._chaos.begin_request()
            try:
                self._wire.write(envelope, self._sock)
                resp = self._wire.read(self._sock)
            except _TRANSPORT_ERRORS:
                self._broken = True
                return None
        if isinstance(resp, RemoteError):
            raise WireError(f"service-side failure: {resp.message}")
        return resp

    def farewell_raw(self, body: bytes) -> Optional[bytes]:
        """Raw-wire twin of ``farewell`` (the native client's bye)."""
        with self._lock:
            if self._closed or self._broken or self._sock is None:
                return None
            if self._chaos is not None:
                self._chaos.begin_request()
            try:
                self._wire.write_frame(self._wire.frame_raw(body),
                                       self._sock)
                return self._wire.read_raw(self._sock)
            except _TRANSPORT_ERRORS:
                self._broken = True
                return None

    def bare_request(self, obj: Any) -> Any:
        """One UNSEQUENCED round trip on the current socket, no retry —
        the re-identify hello an ``on_reconnect`` hook sends (hello is
        idempotent: a superseding registration replaces the old one)."""
        self._wire.write(obj, self._sock)
        resp = self._wire.read(self._sock)
        if isinstance(resp, RemoteError):
            raise WireError(f"service-side failure: {resp.message}")
        return resp

    def rtt_probe(self, obj: Any) -> Tuple[Any, float, float]:
        """One unsequenced round trip timed tightly around the socket I/O:
        returns ``(response, sent_monotonic_s, received_monotonic_s)`` so
        the caller can do NTP midpoint math. The clock-alignment plane's
        primitive (``obs.tracing``, docs/tracing.md) — deliberately OFF the
        ``#rpc`` dedup envelope: a replayed probe would return a STALE
        server timestamp as if it were fresh, which is exactly the
        corruption the min-RTT filter exists to reject (and a reconnect
        mid-probe inflates the RTT so far the sample filters out anyway).
        Transport faults latch the connection broken and raise — the
        caller drops the sample and redials on its own cadence."""
        with self._lock:
            if self._broken or self._sock is None:
                self._reconnect()
            try:
                t0 = time.monotonic()
                self._wire.write(obj, self._sock)
                resp = self._wire.read(self._sock)
                t1 = time.monotonic()
            except _TRANSPORT_ERRORS:
                self._broken = True
                raise
        if isinstance(resp, RemoteError):
            raise WireError(f"service-side failure: {resp.message}")
        return resp, t0, t1

    def bare_request_raw(self, body: bytes) -> bytes:
        """Raw-wire twin of ``bare_request`` (the native client's
        reconnect hello)."""
        self._wire.write_frame(self._wire.frame_raw(body), self._sock)
        return self._wire.read_raw(self._sock)

    def send(self, obj: Any) -> None:
        with self._lock:
            if self._broken or self._sock is None:
                self._reconnect()
            try:
                self._wire.write(obj, self._sock)
            except _TRANSPORT_ERRORS:
                self._broken = True
                raise

    def close(self) -> None:
        # No lock: close() must be able to cut through a parked request
        # (the watch channel blocks in recv for the whole job).
        self._closed = True
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def _log_heal_attempt(exc: Exception, attempt: int) -> None:
    import logging

    logging.getLogger("horovod_tpu").warning(
        "control-plane transport fault (%s: %s); reconnect attempt %d",
        type(exc).__name__, exc, attempt)
