"""Programmatic job API: ``run(fn, args=..., np=N) -> [result per rank]``.

Rebuild of the Spark orchestrator's contract (``horovod/spark/__init__.py:80-196``,
SURVEY §3.4) without Spark: the caller's function is cloudpickled, shipped
to one worker process per rank over the driver's authenticated TCP service,
executed with the world initialized (workers call ``hvd.init()`` themselves,
exactly like reference user fns), and per-rank return values are collected
back. The driver/task split mirrors ``driver_service.py``/``task_service.py``:
registration handshake, code distribution, result registration, and
timeouts with actionable messages (``util/timeout.py``).

``_execute_world`` is the reusable single-attempt core: ``run`` is one
attempt; the elastic driver (``horovod_tpu.elastic.run_elastic``) wraps it
in a detect → abort → relaunch → restore loop.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from .launcher import LaunchCancelled, LaunchError, launch
from .network import BasicService, make_secret

_DRIVER_PORT_ENV = "HOROVOD_DRIVER_PORT"


class WorkerLostError(RuntimeError):
    """Workers exited without reporting results (e.g. ``os._exit(0)`` in
    user code): a world-level fault an elastic driver may retry, unlike
    an arbitrary RuntimeError (which should fail fast)."""

    def __init__(self, ranks: List[int], codes: List[Optional[int]]) -> None:
        super().__init__(
            f"ranks {ranks} exited (codes {codes}) without reporting a "
            f"result to the driver.")
        self.ranks = list(ranks)


class WorkerFailedError(RuntimeError):
    """The job function raised on one or more ranks; carries the rank list
    so an elastic driver can attribute the failure to slots.

    ``records`` maps rank -> the structured ``core.status.failure_record``
    the worker shipped (absent for old-format peers whose payload was a
    plain traceback string — consumers fall back to text parsing then)."""

    def __init__(self, failures: List[Tuple[int, str]],
                 records: Optional[Dict[int, dict]] = None) -> None:
        rank, detail = failures[0]
        msg = f"run(fn) failed on rank {rank}: {detail}"
        if len(failures) > 1:
            msg += (f" (and on {len(failures) - 1} more rank(s): "
                    f"{sorted(r for r, _ in failures[1:])})")
        super().__init__(msg)
        self.ranks = sorted(r for r, _ in failures)
        self.failures = failures
        self.records = records or {}


def _dumps_by_value(fn, args: Tuple, kwargs: dict) -> bytes:
    """Serialize the job function *by value*: workers need not import the
    caller's module — the launcher ships the code, as the reference driver
    does (code distribution, ``spark/driver/driver_service.py``)."""
    import sys

    module = sys.modules.get(getattr(fn, "__module__", None) or "")
    registered = False
    if module is not None and module.__name__ != "__main__":
        try:
            cloudpickle.register_pickle_by_value(module)
            registered = True
        except Exception:  # noqa: BLE001 - fall back to by-reference
            pass
    try:
        return cloudpickle.dumps((fn, args, kwargs))
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(module)


class _Driver:
    """Registration + code distribution + result collection service."""

    def __init__(self, np: int, fn, args: Tuple, kwargs: dict,
                 secret: bytes) -> None:
        self._np = np
        self._payload = _dumps_by_value(fn, args, kwargs)
        self._results: dict = {}
        self._registered: set = set()
        self._cond = threading.Condition()
        self._service = BasicService("horovod-driver", self._handle,
                                     secret=secret)
        self.port = self._service.port

    def _handle(self, req: Any, _sock) -> Any:
        kind = req[0]
        if kind == "register":
            with self._cond:
                self._registered.add(req[1])
                self._cond.notify_all()
            return ("ok",)
        if kind == "fn":
            return ("fn", self._payload)
        if kind == "result":
            _, rank, ok, payload = req
            with self._cond:
                self._results[rank] = (ok, payload)
                self._cond.notify_all()
            return ("ok",)
        raise ValueError(f"unknown driver request {req[0]!r}")

    def wait_registered(self, timeout_s: float, abort_check=None) -> None:
        """Start timeout proper: every rank must check in within
        ``timeout_s`` (the reference's registration timeout with an
        actionable message, ``util/timeout.py:21-34``)."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while len(self._registered) < self._np:
                if abort_check is not None:
                    abort_check()
                if time.monotonic() > deadline:
                    missing = sorted(
                        set(range(self._np)) - self._registered)
                    raise TimeoutError(
                        f"ranks {missing} did not register with the driver "
                        f"within {timeout_s:.0f}s. Check that worker "
                        f"processes can start (imports, device "
                        f"availability) and reach the driver port.")
                self._cond.wait(timeout=0.2)

    def wait_results(self, timeout_s: float,
                     abort_check=None) -> List[Any]:
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while len(self._results) < self._np:
                if abort_check is not None:
                    abort_check()
                if time.monotonic() > deadline:
                    missing = sorted(
                        set(range(self._np)) - set(self._results))
                    raise TimeoutError(
                        f"timed out waiting for results from ranks "
                        f"{missing}. Check worker logs; a rank may have "
                        f"stalled in a collective (see the coordinator "
                        f"stall warning).")
                self._cond.wait(timeout=0.2)
        out = []
        failures: List[Tuple[int, str]] = []
        records: Dict[int, dict] = {}
        for rank in range(self._np):
            ok, payload = self._results[rank]
            value = pickle.loads(payload)
            if not ok:
                # structured failure record (core.status.failure_record);
                # old-format peers ship a bare traceback string and stay
                # on the text-parse fallback path
                if isinstance(value, dict) and value.get("format") == 1:
                    records[rank] = value
                    failures.append((rank, str(value.get("traceback", ""))))
                else:
                    failures.append((rank, str(value)))
            out.append(value)
        if failures:
            raise WorkerFailedError(failures, records=records)
        return out

    def missing_results(self) -> List[int]:
        with self._cond:
            return sorted(set(range(self._np)) - set(self._results))

    def shutdown(self) -> None:
        self._service.shutdown()


def _execute_world(fn, args: Tuple, kwargs: dict, np: int,
                   timeout_s: float, start_timeout_s: float,
                   use_host_data_plane: bool,
                   env_extra: Optional[Dict[str, str]] = None,
                   extra_abort_check: Optional[Callable[[], None]] = None,
                   secret: Optional[str] = None,
                   capture_stderr: bool = True,
                   spawn_ranks: Optional[List[int]] = None,
                   warm_env_cb: Optional[Callable[[int, dict], None]] = None,
                   spare_pids_fn: Optional[Callable[[], set]] = None,
                   spare_grace_s: float = 0.0) -> List[Any]:
    """One world attempt: spawn ``np`` ranks, ship ``fn``, collect results.

    The building block shared by ``run`` (exactly one attempt) and
    ``elastic.run_elastic`` (retry loop). ``extra_abort_check`` runs on
    every wait tick — the elastic driver's heartbeat monitor raises there
    when a rank's beats stop. ``secret`` lets an owner with its own
    long-lived services (the elastic driver's health/state store) put the
    whole job on one HMAC key. Worker stderr is captured so a dead rank's
    LaunchError carries its last output instead of surfacing as an opaque
    result-wait timeout.

    Surgical recovery pass-throughs (docs/recovery.md): ``spawn_ranks``
    forks only those ranks — the rest are warm survivors whose env blocks
    go to ``warm_env_cb`` and who join this world by re-registering with
    this driver in-process; ``spare_pids_fn``/``spare_grace_s`` keep
    freshly-parked survivors alive through this attempt's teardown."""
    import sys

    kwargs = kwargs or {}
    secret = secret or make_secret()
    driver = _Driver(np, fn, args, kwargs, bytes.fromhex(secret))
    cancel = threading.Event()
    thread = None
    try:
        worker_cmd = [sys.executable, "-m", "horovod_tpu.runner._exec_fn"]
        merged_env = {_DRIVER_PORT_ENV: str(driver.port),
                      "HOROVOD_SECRET_KEY": secret}
        if env_extra:
            merged_env.update(env_extra)
        launch_err: List[BaseException] = []
        exit_codes: Dict[int, int] = {}
        launch_done = threading.Event()

        def _launch() -> None:
            try:
                launch(worker_cmd, np, env_extra=merged_env,
                       host_data_plane=use_host_data_plane,
                       cancel_event=cancel, capture_stderr=capture_stderr,
                       exit_codes=exit_codes, spawn_ranks=spawn_ranks,
                       warm_env_cb=warm_env_cb,
                       spare_pids_fn=spare_pids_fn,
                       spare_grace_s=spare_grace_s)
            except LaunchCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                launch_err.append(exc)
            finally:
                launch_done.set()

        thread = threading.Thread(target=_launch, daemon=True)
        thread.start()

        def _abort_check() -> None:
            # A dead rank means results will never arrive; surface the
            # launcher's error instead of waiting out the timeout (the
            # reference cancels the Spark job group the same way,
            # ``spark/__init__.py:181-188``).
            if launch_err:
                raise launch_err[0]
            if launch_done.is_set() and not cancel.is_set():
                # Every worker exited cleanly (code 0) yet results are
                # still missing: a rank died without reporting (e.g.
                # os._exit(0) in user code). Waiting out the timeout
                # would be the old opaque failure mode — name the ranks.
                # Warm survivors have no Popen under THIS attempt, so they
                # never get an exit code here — their deaths are the
                # heartbeat monitor's job (extra_abort_check), not this
                # check's; count only ranks the launcher actually reaped.
                missing = [r for r in driver.missing_results()
                           if r in exit_codes]
                if missing:
                    raise WorkerLostError(
                        missing, [exit_codes.get(r) for r in missing])
            if extra_abort_check is not None:
                extra_abort_check()

        driver.wait_registered(start_timeout_s, _abort_check)
        results = driver.wait_results(timeout_s, _abort_check)
        thread.join(timeout=30.0)
        if launch_err:
            raise launch_err[0]
        return results
    finally:
        # Tear down any still-running ranks (timeout or exception path);
        # the launcher's finally SIGTERMs the process groups.
        cancel.set()
        if thread is not None:
            thread.join(timeout=30.0)
        driver.shutdown()


def run(fn, args: Tuple = (), kwargs: Optional[dict] = None, np: int = 1,
        timeout_s: float = 300.0, start_timeout_s: float = 60.0,
        use_host_data_plane: bool = True,
        capture_stderr: bool = True) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on ``np`` ranks; return results in
    rank order (the reference returns the same, ``spark/__init__.py:192-196``).

    ``start_timeout_s`` bounds worker registration (reference
    HOROVOD_SPARK_START_TIMEOUT semantics); ``timeout_s`` bounds the whole
    job. On either timeout the workers are torn down, not orphaned.
    ``capture_stderr`` (default) buffers each rank's stderr so a dead
    rank's error carries its last output; pass False to stream worker
    stderr to this process's console instead (failures then lack the
    tail). For the fault-tolerant variant that relaunches on worker
    death, see ``horovod_tpu.elastic.run_elastic``."""
    return _execute_world(fn, args, kwargs or {}, np, timeout_s,
                          start_timeout_s, use_host_data_plane,
                          capture_stderr=capture_stderr)
