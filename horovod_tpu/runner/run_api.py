"""Programmatic job API: ``run(fn, args=..., np=N) -> [result per rank]``.

Rebuild of the Spark orchestrator's contract (``horovod/spark/__init__.py:80-196``,
SURVEY §3.4) without Spark: the caller's function is cloudpickled, shipped
to one worker process per rank over the driver's authenticated TCP service,
executed with the world initialized (workers call ``hvd.init()`` themselves,
exactly like reference user fns), and per-rank return values are collected
back. The driver/task split mirrors ``driver_service.py``/``task_service.py``:
registration handshake, code distribution, result registration, and
timeouts with actionable messages (``util/timeout.py``).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, List, Optional, Tuple

import cloudpickle

from .launcher import LaunchCancelled, LaunchError, launch
from .network import BasicService, make_secret

_DRIVER_PORT_ENV = "HOROVOD_DRIVER_PORT"


def _dumps_by_value(fn, args: Tuple, kwargs: dict) -> bytes:
    """Serialize the job function *by value*: workers need not import the
    caller's module — the launcher ships the code, as the reference driver
    does (code distribution, ``spark/driver/driver_service.py``)."""
    import sys

    module = sys.modules.get(getattr(fn, "__module__", None) or "")
    registered = False
    if module is not None and module.__name__ != "__main__":
        try:
            cloudpickle.register_pickle_by_value(module)
            registered = True
        except Exception:  # noqa: BLE001 - fall back to by-reference
            pass
    try:
        return cloudpickle.dumps((fn, args, kwargs))
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(module)


class _Driver:
    """Registration + code distribution + result collection service."""

    def __init__(self, np: int, fn, args: Tuple, kwargs: dict,
                 secret: bytes) -> None:
        self._np = np
        self._payload = _dumps_by_value(fn, args, kwargs)
        self._results: dict = {}
        self._registered: set = set()
        self._cond = threading.Condition()
        self._service = BasicService("horovod-driver", self._handle,
                                     secret=secret)
        self.port = self._service.port

    def _handle(self, req: Any, _sock) -> Any:
        kind = req[0]
        if kind == "register":
            with self._cond:
                self._registered.add(req[1])
                self._cond.notify_all()
            return ("ok",)
        if kind == "fn":
            return ("fn", self._payload)
        if kind == "result":
            _, rank, ok, payload = req
            with self._cond:
                self._results[rank] = (ok, payload)
                self._cond.notify_all()
            return ("ok",)
        raise ValueError(f"unknown driver request {req[0]!r}")

    def wait_registered(self, timeout_s: float, abort_check=None) -> None:
        """Start timeout proper: every rank must check in within
        ``timeout_s`` (the reference's registration timeout with an
        actionable message, ``util/timeout.py:21-34``)."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while len(self._registered) < self._np:
                if abort_check is not None:
                    abort_check()
                if time.monotonic() > deadline:
                    missing = sorted(
                        set(range(self._np)) - self._registered)
                    raise TimeoutError(
                        f"ranks {missing} did not register with the driver "
                        f"within {timeout_s:.0f}s. Check that worker "
                        f"processes can start (imports, device "
                        f"availability) and reach the driver port.")
                self._cond.wait(timeout=0.2)

    def wait_results(self, timeout_s: float,
                     abort_check=None) -> List[Any]:
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while len(self._results) < self._np:
                if abort_check is not None:
                    abort_check()
                if time.monotonic() > deadline:
                    missing = sorted(
                        set(range(self._np)) - set(self._results))
                    raise TimeoutError(
                        f"timed out waiting for results from ranks "
                        f"{missing}. Check worker logs; a rank may have "
                        f"stalled in a collective (see the coordinator "
                        f"stall warning).")
                self._cond.wait(timeout=0.2)
        out = []
        for rank in range(self._np):
            ok, payload = self._results[rank]
            value = pickle.loads(payload)
            if not ok:
                raise RuntimeError(
                    f"run(fn) failed on rank {rank}: {value}")
            out.append(value)
        return out

    def shutdown(self) -> None:
        self._service.shutdown()


def run(fn, args: Tuple = (), kwargs: Optional[dict] = None, np: int = 1,
        timeout_s: float = 300.0, start_timeout_s: float = 60.0,
        use_host_data_plane: bool = True) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on ``np`` ranks; return results in
    rank order (the reference returns the same, ``spark/__init__.py:192-196``).

    ``start_timeout_s`` bounds worker registration (reference
    HOROVOD_SPARK_START_TIMEOUT semantics); ``timeout_s`` bounds the whole
    job. On either timeout the workers are torn down, not orphaned."""
    import sys

    kwargs = kwargs or {}
    secret = make_secret()
    driver = _Driver(np, fn, args, kwargs, bytes.fromhex(secret))
    cancel = threading.Event()
    thread = None
    try:
        worker_cmd = [sys.executable, "-m", "horovod_tpu.runner._exec_fn"]
        env_extra = {_DRIVER_PORT_ENV: str(driver.port),
                     "HOROVOD_SECRET_KEY": secret}
        launch_err: List[BaseException] = []

        def _launch() -> None:
            try:
                launch(worker_cmd, np, env_extra=env_extra,
                       host_data_plane=use_host_data_plane,
                       cancel_event=cancel)
            except LaunchCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                launch_err.append(exc)

        thread = threading.Thread(target=_launch, daemon=True)
        thread.start()

        def _abort_on_launch_failure() -> None:
            # A dead rank means results will never arrive; surface the
            # launcher's error instead of waiting out the timeout (the
            # reference cancels the Spark job group the same way,
            # ``spark/__init__.py:181-188``).
            if launch_err:
                raise launch_err[0]

        driver.wait_registered(start_timeout_s, _abort_on_launch_failure)
        results = driver.wait_results(timeout_s, _abort_on_launch_failure)
        thread.join(timeout=30.0)
        if launch_err:
            raise launch_err[0]
        return results
    finally:
        # Tear down any still-running ranks (timeout or exception path);
        # the launcher's finally SIGTERMs the process groups.
        cancel.set()
        if thread is not None:
            thread.join(timeout=30.0)
        driver.shutdown()
