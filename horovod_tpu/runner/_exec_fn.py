"""Per-rank worker entry for ``runner.run``.

Rebuild of ``horovod/spark/task/mpirun_exec_fn.py``: a parent-death watchdog
thread (``:26-37`` — workers must die with the launcher), fetch the pickled
fn from the driver, run it, register the result or the exception.
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import time
import traceback

import cloudpickle

from .network import BasicClient, default_secret
from .run_api import _DRIVER_PORT_ENV


def _parent_death_watchdog() -> None:
    """Exit when the launcher dies (reparented to init), like the
    reference's orphan watchdog."""
    parent = os.getppid()

    def watch() -> None:
        while True:
            if os.getppid() != parent:
                os._exit(1)
            time.sleep(0.5)

    threading.Thread(target=watch, name="parent-watchdog",
                     daemon=True).start()


def main() -> int:
    _parent_death_watchdog()
    from ..core.config import HOROVOD_RANK

    rank = int(os.environ[HOROVOD_RANK])
    port = int(os.environ[_DRIVER_PORT_ENV])
    # Elastic jobs: heartbeat the driver's health plane for the whole
    # lifetime of this worker (no-op when HOROVOD_ELASTIC_PORT is absent).
    from ..elastic.health import reporter_from_env

    reporter = reporter_from_env()
    client = BasicClient(("127.0.0.1", port), secret=default_secret())
    client.request(("register", rank))
    _, payload = client.request(("fn",))
    fn, args, kwargs = cloudpickle.loads(payload)
    try:
        # Warm-survivor loop (docs/recovery.md): each iteration is one
        # world-epoch attempt. On a world fault this process parks in the
        # recovery barrier instead of exiting; a warm re-entry verdict
        # re-runs the SAME fn object (never re-fetched — jit caches key
        # on function identity, and keeping them is the point) under the
        # successor epoch's env, against the successor epoch's driver.
        while True:
            try:
                result = fn(*args, **kwargs)
                client.request(("result", rank, True, pickle.dumps(result)))
                return 0
            except BaseException as exc:  # noqa: BLE001 - ship to driver
                # Structured failure record: the abort attribution (e.g.
                # RanksAbortedError.ranks) rides the wire as data, not as
                # text the driver would have to regex out of the traceback.
                from ..core.status import failure_record

                record = failure_record(exc, traceback.format_exc())
                try:
                    client.request(("result", rank, False,
                                    pickle.dumps(record)))
                except Exception:  # noqa: BLE001 - best-effort: on a world
                    # fault the driver may already be tearing this epoch
                    # down; the recovery barrier (a different service, on
                    # the long-lived driver process) is the channel that
                    # must not be skipped
                    pass
                from ..elastic.recovery import apply_assignment, maybe_recover

                assignment = maybe_recover(rank, record)
                if assignment is None:
                    return 1
                rank = apply_assignment(assignment)
                if reporter is not None:
                    reporter.stop()
                reporter = reporter_from_env()
                client.close()
                port = int(os.environ[_DRIVER_PORT_ENV])
                client = BasicClient(("127.0.0.1", port),
                                     secret=default_secret())
                client.request(("register", rank))
    finally:
        if reporter is not None:
            # goodbye beat: a clean exit must not read as a death while
            # the driver is still collecting the other ranks' results
            reporter.stop()
        client.close()


if __name__ == "__main__":
    sys.exit(main())
