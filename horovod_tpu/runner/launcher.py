"""Process launcher: the ``mpirun`` replacement.

The reference delegates process launch to ``mpirun`` (docs/running.md) or,
on Spark clusters, to a driver that herds task services into exec'ing orted
(``horovod/spark/__init__.py``, SURVEY §3.4). On TPU there is no MPI: this
launcher spawns one process per rank on the local host with the world
described in env vars (the role ``OMPI_COMM_WORLD_RANK`` et al. play under
mpirun), wires every rank to the rank-0 controller port, and generates a
per-job HMAC secret.

Multi-host TPU pods do not use ssh fan-out: the TPU VM runtime starts one
process per host running the same program, and ``jax.distributed`` +
``core.topology`` resolve the world from the pod metadata. This launcher's
domain is single-host worlds — CPU test rigs and single-host multi-process
deployments — exactly the niche ``mpirun -np N`` fills on one node.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..core import config as _config
from .network import local_addresses, make_secret


def _free_port(bind_addr: str = "127.0.0.1") -> int:
    """Probe a free port by bind-and-release. Inherently TOCTOU-racy —
    the port can be lost to another process before its real user binds
    it — so this survives only where the bind happens on ANOTHER host
    (``launch_hosts`` with a remote hosts[0], where a collision surfaces
    as rank 0's prompt "Address already in use" LaunchError). Single-host
    launches use ``_bind_controller_listener`` instead: the launcher
    binds the live socket itself and rank 0 inherits it."""
    with socket.socket() as s:
        s.bind((bind_addr, 0))
        return s.getsockname()[1]


def _bind_controller_listener(bind_addr: str = "127.0.0.1"
                              ) -> socket.socket:
    """Bind AND LISTEN the controller socket in the launcher (port 0 — the
    kernel picks a genuinely free port) so the advertised port can never
    be lost before rank 0 starts serving: rank 0 inherits this exact
    socket (``HOROVOD_CONTROLLER_FD``), and peers that dial early wait in
    its kernel backlog instead of bouncing off a connection refused."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((bind_addr, 0))
    # match BasicService's backlog: every rank connects at t0
    s.listen(128)
    return s


def build_rank_env(rank: int, size: int, port: int, secret: str,
                   base_env: Optional[Dict[str, str]] = None,
                   host_data_plane: bool = False,
                   local_rank: Optional[int] = None,
                   local_size: Optional[int] = None,
                   cross_rank: int = 0, cross_size: int = 1,
                   controller_addr: str = "127.0.0.1",
                   env_extra: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
    """Env block one rank needs — the analog of mpirun's exported world.

    Defaults describe a single-host world (local == global); multi-host
    launches pass the per-host split the way mpirun derives
    ``OMPI_COMM_WORLD_LOCAL_RANK`` from the host slot layout."""
    env = dict(base_env if base_env is not None else os.environ)
    env.update({
        _config.HOROVOD_RANK: str(rank),
        _config.HOROVOD_SIZE: str(size),
        _config.HOROVOD_LOCAL_RANK: str(
            rank if local_rank is None else local_rank),
        _config.HOROVOD_LOCAL_SIZE: str(
            size if local_size is None else local_size),
        _config.HOROVOD_CROSS_RANK: str(cross_rank),
        _config.HOROVOD_CROSS_SIZE: str(cross_size),
        _config.HOROVOD_CONTROLLER_ADDR: controller_addr,
        _config.HOROVOD_CONTROLLER_PORT: str(port),
        _config.HOROVOD_SECRET_KEY: secret,
    })
    if host_data_plane:
        env[_config.HOROVOD_DATA_PLANE] = "host"
    if env_extra:
        # merged BEFORE the pin so user topology / the opt-out knob passed
        # programmatically are seen by (and win over) the default pin
        env.update(env_extra)
    _pin_local_device(env, local_rank if local_rank is not None else rank,
                      local_size if local_size is not None else size)
    return env


# libtpu env recipe for several independent single-chip processes on one
# host: restrict each process to its local_rank's chip and declare a
# standalone 1x1x1 process grid. The TPU analog of the reference's
# one-GPU-per-process model (mpirun rank -> ``torch.cuda.set_device(
# hvd.local_rank())`` in user code, CUDA_VISIBLE_DEVICES from the
# scheduler); on TPU the runtime locks chips to the first process that
# initializes them, so WITHOUT this every slot beyond the first would die
# with "device busy" — the pin must come from the launcher, not user code.
_TPU_PIN_VARS = ("TPU_VISIBLE_DEVICES", "TPU_CHIPS_PER_PROCESS_BOUNDS",
                 "TPU_PROCESS_BOUNDS")


def _pin_local_device(env: Dict[str, str], local_rank: int,
                      local_size: int) -> None:
    """One TPU chip per slot when a host runs several (slots > 1).

    Respects explicit user topology (any of the pin vars already set) and
    the single-process-per-host model (slots == 1 keeps all local chips —
    the TPU-native layout). ``HOROVOD_LAUNCHER_PIN_DEVICES=0`` disables.
    Harmless off-TPU: libtpu vars are ignored by CPU/GPU backends."""
    if local_size <= 1:
        return
    if env.get(_config.HOROVOD_LAUNCHER_PIN_DEVICES, "1") == "0":
        return
    if any(v in env for v in _TPU_PIN_VARS):
        return
    env["TPU_VISIBLE_DEVICES"] = str(local_rank)
    env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
    env["TPU_PROCESS_BOUNDS"] = "1,1,1"


def parse_hosts(spec: str) -> List[tuple]:
    """Parse mpirun-style ``host1:slots,host2:slots`` (slots default 1)."""
    hosts = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if ":" in item:
            name, slots_s = item.rsplit(":", 1)
            try:
                slots = int(slots_s)
            except ValueError:
                raise ValueError(f"bad host spec {item!r}: slots must be an "
                                 f"integer") from None
        else:
            name, slots = item, 1
        if not name or slots < 1:
            raise ValueError(f"bad host spec {item!r}")
        hosts.append((name, slots))
    if not hosts:
        raise ValueError(f"empty host spec {spec!r}")
    return hosts


_LOCAL_HOSTS = ("localhost", "127.0.0.1")


def _rsh_wrap(rsh_agent: Sequence[str], host: str,
              env: Dict[str, str], command: Sequence[str],
              extra_keys: Sequence[str] = ()) -> List[str]:
    """Build the remote launch line: ``<rsh...> <host> env K=V... cmd``.

    The rsh agent is pluggable exactly like mpirun's ``plm_rsh_agent`` —
    the hook the reference's Spark integration uses to route orted launches
    through its task services (``spark/driver/mpirun_rsh.py:24-38``). The
    world env vars plus any caller-supplied ``extra_keys`` (programmatic
    ``launch_hosts(env_extra=...)``) are forwarded; the remote side keeps
    the rest of its own inherited environment."""
    import shlex

    world_keys = [
        _config.HOROVOD_RANK, _config.HOROVOD_SIZE,
        _config.HOROVOD_LOCAL_RANK, _config.HOROVOD_LOCAL_SIZE,
        _config.HOROVOD_CROSS_RANK, _config.HOROVOD_CROSS_SIZE,
        _config.HOROVOD_CONTROLLER_ADDR, _config.HOROVOD_CONTROLLER_PORT,
        _config.HOROVOD_SECRET_KEY, _config.HOROVOD_DATA_PLANE,
        "HOROVOD_CONTROLLER_BIND",
        # per-slot chip pinning + platform steering must reach remote
        # workers too — they are part of the world description
        *_TPU_PIN_VARS, _config.HOROVOD_PLATFORM,
        _config.HOROVOD_LAUNCHER_PIN_DEVICES,
    ]
    keys = world_keys + [k for k in extra_keys if k not in world_keys]
    assignments = [f"{k}={env[k]}" for k in keys if k in env]
    remote = " ".join(["env"] + [shlex.quote(a) for a in assignments] +
                      [shlex.quote(c) for c in command])
    return list(rsh_agent) + [host, remote]


def launch_hosts(command: Sequence[str], hosts: List[tuple],
                 rsh_agent: Optional[Sequence[str]] = None,
                 controller_addr: Optional[str] = None,
                 env_extra: Optional[Dict[str, str]] = None,
                 host_data_plane: bool = False,
                 job_timeout_s: Optional[float] = None,
                 cancel_event: Optional["threading.Event"] = None) -> int:
    """Multi-host launch: ``mpirun -H host1:s1,host2:s2`` semantics.

    Ranks are assigned host-major (fill each host's slots before moving
    on — mpirun's by-slot default). Each host entry becomes one
    local-world: local_rank within the entry, cross_rank = entry index
    (the structure ``MPI_Comm_split_type(SHARED)`` discovers in the
    reference, ``operations.cc:1760-1797``). Remote hosts launch through
    ``rsh_agent`` (default ``ssh``); ``localhost``/``127.0.0.1`` entries
    exec directly, which is also how the multi-host code path is tested
    without a cluster."""
    size = sum(slots for _, slots in hosts)
    remote = any(h not in _LOCAL_HOSTS for h, _ in hosts)
    if controller_addr is None:
        # Rank 0 — and with it the ControllerService — runs on hosts[0],
        # which need not be this machine: workers must dial THAT host. A
        # local hosts[0] advertises every NIC (comma list; workers probe
        # for a routable one, the reference's interface-matching).
        if hosts[0][0] in _LOCAL_HOSTS:
            if remote:
                # loopback is never routable from another host — and could
                # even match an unrelated local service on the worker side —
                # so advertise only real NICs to remote workers
                nics = [a for a in dict.fromkeys(local_addresses().values())
                        if not a.startswith("127.")]
                controller_addr = ",".join(nics) if nics else "127.0.0.1"
            else:
                controller_addr = "127.0.0.1"
        else:
            controller_addr = hosts[0][0]
    # NOTE: with a remote hosts[0] the port is probed free on THIS machine
    # but bound on hosts[0]; a collision there surfaces as rank 0 exiting
    # with "Address already in use", which _wait_all turns into a prompt
    # LaunchError that tears the world down (no silent spin).
    port = _free_port("0.0.0.0" if remote else "127.0.0.1")
    secret = make_secret()
    rsh = list(rsh_agent) if rsh_agent else ["ssh"]
    extra_keys = sorted(env_extra) if env_extra else []
    procs: List[subprocess.Popen] = []
    try:
        rank = 0
        for cross_rank, (host, slots) in enumerate(hosts):
            for local_rank in range(slots):
                env = build_rank_env(
                    rank, size, port, secret,
                    host_data_plane=host_data_plane,
                    local_rank=local_rank, local_size=slots,
                    cross_rank=cross_rank, cross_size=len(hosts),
                    controller_addr=controller_addr, env_extra=env_extra)
                if rank == 0 and remote:
                    # remote workers dial in over a real NIC; the per-job
                    # secret satisfies the non-loopback bind guard
                    env["HOROVOD_CONTROLLER_BIND"] = "0.0.0.0"
                if host in _LOCAL_HOSTS and rsh_agent is None:
                    argv = list(command)
                else:
                    argv = _rsh_wrap(rsh, host, env, command,
                                     extra_keys=extra_keys)
                procs.append(subprocess.Popen(
                    argv, env=env, start_new_session=True))
                rank += 1
        return _wait_all(procs, job_timeout_s, cancel_event)
    finally:
        _terminate_all(procs)


class LaunchError(RuntimeError):
    """A rank died: names the rank, its exit code, and (when the launcher
    captured it) the tail of that rank's stderr — so a worker crash reads
    as its own traceback, not an opaque result-wait timeout."""

    def __init__(self, rank: int, returncode: int,
                 stderr_tail: str = "") -> None:
        msg = (f"rank {rank} exited with code {returncode}; terminated "
               f"remaining ranks.")
        if stderr_tail:
            msg += (f"\n--- last stderr of rank {rank} ---\n"
                    f"{stderr_tail.rstrip()}")
        super().__init__(msg)
        self.rank = rank
        self.returncode = returncode
        self.stderr_tail = stderr_tail


class LaunchCancelled(RuntimeError):
    pass


def launch(command: Sequence[str], np: int,
           env_extra: Optional[Dict[str, str]] = None,
           host_data_plane: bool = False,
           job_timeout_s: Optional[float] = None,
           cancel_event: Optional["threading.Event"] = None,
           capture_stderr: bool = False,
           exit_codes: Optional[Dict[int, int]] = None,
           spawn_ranks: Optional[Sequence[int]] = None,
           warm_env_cb: Optional[Any] = None,
           spare_pids_fn: Optional[Any] = None,
           spare_grace_s: float = 0.0) -> int:
    """Run ``command`` as ``np`` ranks; return 0 or raise LaunchError.

    ``job_timeout_s`` bounds the WHOLE job (leave None for training runs);
    ``cancel_event`` lets an owner (e.g. ``run()``'s driver) tear the world
    down early. ``capture_stderr`` redirects each rank's stderr to a temp
    file so a failure's LaunchError can carry the dead rank's last output
    (``runner.run`` enables this; the CLI keeps the passthrough).
    ``exit_codes``, if given, is filled with every observed rank exit code
    (the owner can tell a silent exit-0 from a still-running rank).

    Surgical recovery hooks (docs/recovery.md): ``spawn_ranks`` limits
    actual forking to those ranks — every OTHER rank is a warm survivor
    whose fully-built env block is handed to ``warm_env_cb(rank, env)``
    instead of a Popen (the elastic driver publishes it through the
    recovery barrier). Warm ranks cannot inherit pre-bound listener fds
    across the epoch, so a warm rank 0 / island head gets a probed port
    to bind in-process (the TOCTOU risk is accepted: a collision
    surfaces as a prompt failure and the next round goes cold).
    ``spare_pids_fn``/``spare_grace_s``: at teardown, wait up to the
    grace for still-running ranks to appear in the spare set (parked
    survivors) and leave those alive.

    Failure semantics follow the reference launcher stack: when any rank
    dies, the rest are terminated (mpirun behavior; also the Spark
    driver's job-group cancel, ``spark/__init__.py:181-188``), and children
    die with the launcher via process-group kill
    (``spark/util/safe_shell_exec.py``)."""
    import tempfile

    if np < 1:
        raise ValueError("np must be >= 1")
    spawn = (set(range(np)) if spawn_ranks is None
             else {int(r) for r in spawn_ranks})
    # TOCTOU fix: bind + listen the controller socket HERE and hand the
    # live socket to rank 0 (HOROVOD_CONTROLLER_FD) — the port cannot be
    # lost to another process between probe and bind, and early worker
    # connects park in the backlog instead of bouncing.
    listener: Optional[socket.socket] = None
    if 0 in spawn:
        listener = _bind_controller_listener()
        port = listener.getsockname()[1]
    else:
        port = _free_port()
    secret = make_secret()
    # Hierarchical negotiation tree (docs/hierarchy.md): resolve the
    # topology HERE so each island's sub-coordinator listener gets the
    # same TOCTOU-free pre-bind as the root above — the head inherits the
    # live socket (HOROVOD_SUBCOORD_FD) and its members' early connects
    # park in the backlog. The resolved "islands:N" form is exported so
    # every rank plans the identical partition. This single-host launcher
    # has no host boundary, so "auto" stays flat here by design.
    hier = None
    hier_mode = ((env_extra or {}).get(
        _config.HOROVOD_HIERARCHY,
        os.environ.get(_config.HOROVOD_HIERARCHY, "flat"))
        or "flat").strip().lower()
    if hier_mode not in ("", "flat"):
        from ..ops.hierarchy import (parse_head_overrides, plan_topology)

        # succession overrides (docs/recovery.md): after a head death the
        # elastic driver re-plans the island under its successor and
        # publishes the override for every subsequent epoch
        overrides = parse_head_overrides((env_extra or {}).get(
            _config.HOROVOD_ISLAND_HEADS,
            os.environ.get(_config.HOROVOD_ISLAND_HEADS, "")))
        hier = plan_topology(np, hier_mode, cross_size=1,
                             head_overrides=overrides)
        if hier.flat:
            hier = None
    sub_listeners: Dict[int, socket.socket] = {}
    sub_ports: Dict[int, int] = {}
    standby_listeners: Dict[int, socket.socket] = {}
    standby_ports: Dict[int, int] = {}
    if hier is not None:
        for island_id in sorted(hier.islands):
            if hier.head_of(island_id) in spawn:
                sub_listeners[island_id] = _bind_controller_listener()
                sub_ports[island_id] = \
                    sub_listeners[island_id].getsockname()[1]
            else:
                sub_ports[island_id] = _free_port()
            # standby island-head succession (docs/recovery.md): islands
            # with a planned successor get a second, dormant listener the
            # successor serves — members fail over to it when the head's
            # service dies but their own ranks survive
            succ = hier.successor_of(island_id)
            if succ is None:
                continue
            if succ in spawn:
                standby_listeners[island_id] = _bind_controller_listener()
                standby_ports[island_id] = \
                    standby_listeners[island_id].getsockname()[1]
            else:
                standby_ports[island_id] = _free_port()
    procs: Dict[int, subprocess.Popen] = {}
    stderr_files: Dict[int, Any] = {}
    try:
        for rank in range(np):
            env = build_rank_env(rank, np, port, secret,
                                 host_data_plane=host_data_plane,
                                 env_extra=env_extra)
            popen_kwargs: Dict[str, Any] = {}
            pass_fds: tuple = ()
            if rank == 0 and listener is not None:
                env[_config.HOROVOD_CONTROLLER_FD] = str(listener.fileno())
                pass_fds += (listener.fileno(),)
            if hier is not None:
                island_id = hier.island_of[rank]
                env[_config.HOROVOD_HIERARCHY] = hier.mode
                env[_config.HOROVOD_ISLAND] = str(island_id)
                env[_config.HOROVOD_SUBCOORD_ADDR] = "127.0.0.1"
                env[_config.HOROVOD_SUBCOORD_PORT] = str(
                    sub_ports[island_id])
                if hier.head_overrides:
                    from ..ops.hierarchy import format_head_overrides

                    env[_config.HOROVOD_ISLAND_HEADS] = \
                        format_head_overrides(hier.head_overrides)
                if hier.head_of(island_id) == rank and \
                        island_id in sub_listeners:
                    # the island head inherits its live listener (rank 0
                    # carries BOTH the root's fd and island 0's)
                    sub = sub_listeners[island_id]
                    env[_config.HOROVOD_SUBCOORD_FD] = str(sub.fileno())
                    pass_fds += (sub.fileno(),)
                if island_id in standby_ports:
                    env[_config.HOROVOD_SUBCOORD_STANDBY_PORT] = str(
                        standby_ports[island_id])
                    if hier.successor_of(island_id) == rank and \
                            island_id in standby_listeners:
                        stand = standby_listeners[island_id]
                        env[_config.HOROVOD_SUBCOORD_STANDBY_FD] = str(
                            stand.fileno())
                        pass_fds += (stand.fileno(),)
            if rank not in spawn:
                # warm survivor: no fork — hand the env block back to the
                # elastic driver for the recovery barrier (never contains
                # listener-fd vars: only spawned ranks inherit fds)
                if warm_env_cb is not None:
                    warm_env_cb(rank, dict(env))
                continue
            if pass_fds:
                popen_kwargs["pass_fds"] = pass_fds
            if capture_stderr:
                stderr_files[rank] = tempfile.TemporaryFile()
                popen_kwargs["stderr"] = stderr_files[rank]
            procs[rank] = subprocess.Popen(
                list(command), env=env,
                start_new_session=True,  # own process group for clean kill
                **popen_kwargs)
        # rank 0 / the heads inherited the listening sockets; drop the
        # launcher's copies so service shutdown in the workers actually
        # releases the ports
        for sock in _all_listeners(listener, sub_listeners,
                                   standby_listeners):
            sock.close()
        return _wait_all(procs, job_timeout_s, cancel_event,
                         stderr_files=stderr_files, exit_codes=exit_codes)
    finally:
        for sock in _all_listeners(listener, sub_listeners,
                                   standby_listeners):
            try:
                sock.close()
            except OSError:
                pass
        _terminate_all(list(procs.values()), spare_pids_fn=spare_pids_fn,
                       spare_grace_s=spare_grace_s)
        _replay_stderr(stderr_files)
        for fh in stderr_files.values():
            try:
                fh.close()
            except OSError:
                pass


def _all_listeners(listener, *listener_maps) -> List[socket.socket]:
    socks = [listener] if listener is not None else []
    for m in listener_maps:
        socks.extend(m.values())
    return socks


def _replay_stderr(stderr_files: Dict[int, Any],
                   max_bytes: int = 1 << 16) -> None:
    """Dump each rank's captured stderr to this process's stderr once the
    world is down. Capture exists so failures can carry the dead rank's
    output; replaying at teardown means callers lose only LIVENESS, not
    content (worker logs, warnings, user prints). Bounded per rank so a
    log-spamming job cannot flood the launcher."""
    for rank in sorted(stderr_files):
        fh = stderr_files[rank]
        try:
            fh.flush()
            size = fh.seek(0, 2)
            if size == 0:
                continue
            fh.seek(max(0, size - max_bytes))
            content = fh.read().decode("utf-8", "replace")
        except (OSError, ValueError):
            continue
        trunc = " (truncated)" if size > max_bytes else ""
        print(f"--- captured stderr, rank {rank}{trunc} ---\n"
              f"{content.rstrip()}", file=sys.stderr, flush=True)


def _stderr_tail(fh, max_bytes: int = 4096) -> str:
    """Read the trailing bytes of a captured stderr temp file. Only safe
    once the owning rank exited (the file description's offset is shared
    with the child)."""
    try:
        fh.flush()
        size = fh.seek(0, 2)
        fh.seek(max(0, size - max_bytes))
        return fh.read().decode("utf-8", "replace")
    except (OSError, ValueError):
        return ""


def _evidence_grace_s() -> float:
    """Flight-recorder evidence grace (docs/blackbox.md): how long the
    failure path lets surviving ranks drain before ``_terminate_all``
    SIGTERMs them — the window in which the coordinator's black-box
    incident collector lands its dump. 0 (today's immediate fail-fast)
    when the recorder is disabled or unimportable."""
    try:
        from ..obs.flightrec import launch_grace_s

        return launch_grace_s()
    except Exception:  # noqa: BLE001 - diagnostics must not break launch
        return 0.0


def _wait_all(procs: "Dict[int, subprocess.Popen] | List[subprocess.Popen]",
              timeout_s: Optional[float],
              cancel_event: Optional["threading.Event"] = None,
              stderr_files: Optional[Dict[int, Any]] = None,
              exit_codes: Optional[Dict[int, int]] = None) -> int:
    deadline = time.monotonic() + timeout_s if timeout_s else None
    remaining = (dict(procs) if isinstance(procs, dict)
                 else {rank: p for rank, p in enumerate(procs)})
    # First nonzero exit observed: (rank, code, stderr tail). Raised
    # after the flight-recorder evidence grace instead of immediately —
    # a hard rank death (os._exit/SIGKILL) otherwise SIGTERMs the
    # coordinator before its incident collector can land the black-box
    # dump (docs/blackbox.md). Survivors that exit on their own end the
    # grace early; reference fail-fast semantics are preserved with the
    # recorder disabled (grace 0).
    first_failure: Optional[tuple] = None
    grace_deadline = 0.0
    while remaining:
        for rank, proc in list(remaining.items()):
            code = proc.poll()
            if code is None:
                continue
            del remaining[rank]
            if exit_codes is not None:
                exit_codes[rank] = code
            if code != 0 and first_failure is None:
                tail = ""
                if stderr_files and rank in stderr_files:
                    tail = _stderr_tail(stderr_files[rank])
                first_failure = (rank, code, tail)
                grace_deadline = time.monotonic() + _evidence_grace_s()
        if first_failure is not None and (
                not remaining or time.monotonic() > grace_deadline):
            rank, code, tail = first_failure
            raise LaunchError(rank, code, stderr_tail=tail)
        if cancel_event is not None and cancel_event.is_set():
            raise LaunchCancelled("job cancelled by owner")
        if deadline and time.monotonic() > deadline:
            raise TimeoutError(
                f"ranks {sorted(remaining)} still running after "
                f"{timeout_s:.0f}s job timeout; terminating. (Check for a "
                f"stalled collective — see the stall warning in the rank 0 "
                f"log.)")
        time.sleep(0.05)
    return 0


def _terminate_all(procs: List[subprocess.Popen],
                   spare_pids_fn=None, spare_grace_s: float = 0.0) -> None:
    spared: set = set()
    if spare_pids_fn is not None:
        # Surgical teardown (docs/recovery.md): ranks that parked in the
        # recovery barrier stay ALIVE — killing them would throw away the
        # warm state the barrier exists to preserve. Wait up to the grace
        # for every still-running rank to either park or exit; whatever is
        # left after that is wedged and gets the normal kill.
        grace_deadline = time.monotonic() + max(0.0, spare_grace_s)
        while True:
            try:
                spared = set(spare_pids_fn())
            except Exception:  # noqa: BLE001 - sparing is best-effort
                spared = set()
            live = [p for p in procs
                    if p.poll() is None and p.pid not in spared]
            if not live or time.monotonic() > grace_deadline:
                break
            time.sleep(0.05)
    for proc in procs:
        if proc.pid in spared:
            continue
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    deadline = time.monotonic() + 5.0
    for proc in procs:
        if proc.pid in spared:
            continue
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``horovodrun`` CLI: ``python -m horovod_tpu.runner -np 4 python x.py``."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="horovodrun",
        description="Launch a horovod_tpu job: one process per rank on this "
                    "host (mpirun replacement; TPU pods use one process per "
                    "host via the TPU VM runtime instead).")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="number of ranks to spawn (single host)")
    parser.add_argument("-H", "--hosts", default=None,
                        help="mpirun-style host list 'host1:slots,"
                             "host2:slots'; remote hosts launch via the rsh "
                             "agent, localhost entries exec directly")
    parser.add_argument("--rsh-agent", default=None,
                        help="remote shell command for -H (default: ssh); "
                             "the plm_rsh_agent hook of mpirun")
    parser.add_argument("--controller-addr", default=None,
                        help="address workers use to reach the rank-0 "
                             "controller (default: this host's address for "
                             "remote -H, else 127.0.0.1)")
    parser.add_argument("--host-data-plane", action="store_true",
                        help="force the numpy-over-TCP eager data plane "
                             "(CPU test worlds)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="bound the WHOLE job to this many seconds "
                             "(default: unbounded, as for training runs)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="program and args to run per rank")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if (args.num_proc is None) == (args.hosts is None):
        parser.error("exactly one of -np or -H is required")
    try:
        if args.hosts is not None:
            return launch_hosts(
                args.command, parse_hosts(args.hosts),
                rsh_agent=(args.rsh_agent.split()
                           if args.rsh_agent else None),
                controller_addr=args.controller_addr,
                host_data_plane=args.host_data_plane,
                job_timeout_s=args.timeout)
        return launch(args.command, args.num_proc,
                      host_data_plane=args.host_data_plane,
                      job_timeout_s=args.timeout)
    except LaunchError as exc:
        print(f"horovodrun: {exc}", file=sys.stderr)
        return exc.returncode or 1


if __name__ == "__main__":
    sys.exit(main())
