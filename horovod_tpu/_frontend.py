"""Shared front-end implementation — the ``horovod/_keras`` of this build.

The reference keeps one Keras implementation (``horovod/_keras/__init__.py``:
``create_distributed_optimizer`` :20-70, ``load_model`` :93-109) and binds it
to each backend through thin shims (``horovod/keras``,
``horovod/tensorflow/keras``). The flax and haiku front-ends here follow the
same shape: everything framework-agnostic — the optimizer wrap, the rank-0
checkpoint round-trip, the callback surface — lives in this module; the
shims add only the framework's native unit of training state.
"""

from __future__ import annotations

from typing import Any, Optional

import optax

from . import checkpoint as _checkpoint
from .callbacks import (  # noqa: F401  (re-exported by the shims)
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from .ops.compression import Compression
from .ops.fused_apply import (  # noqa: F401  (re-exported by the shims)
    adam as fused_adam,
    momentum as fused_momentum,
    sgd as fused_sgd,
)
from .optimizers import (  # noqa: F401  (apply_step re-exported by shims)
    DistributedOptimizer,
    apply_step,
    is_distributed,
)

CALLBACK_EXPORTS = [
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
    "Callback",
    "CallbackList",
]


def create_distributed_optimizer(
        optimizer: optax.GradientTransformation,
        *,
        axis_name=None,
        compression=None,
        average: bool = True,
        backward_passes_per_step: int = 1,
        hierarchical: Optional[bool] = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates come from world-averaged gradients.

    The reference builds a dynamic subclass overriding ``get_gradients``
    (``_keras/__init__.py:20-70``); in optax the seam is the gradient
    transformation itself, so the wrap is a transformation that averages
    before delegating to the inner optimizer. ``compression=None`` follows
    the ``HOROVOD_COMPRESSION`` knob (none/fp16/bf16/int8/fp8, see
    docs/compression.md); pass ``Compression.*`` to pin a codec.
    """
    return DistributedOptimizer(
        optimizer, axis_name=axis_name, compression=compression,
        average=average, backward_passes_per_step=backward_passes_per_step,
        hierarchical=hierarchical)


def wrap_unless_distributed(tx: optax.GradientTransformation,
                            **kwargs) -> optax.GradientTransformation:
    """Wrap ``tx`` unless it already is a DistributedOptimizer — guards the
    front-ends' ``create(...)`` against double wrapping (two allreduces per
    step, double compression, N*N delay counters) when a user pre-wraps and
    then passes the result in. A pre-wrapped optimizer keeps its own knobs;
    ``kwargs`` apply only when the wrap happens here."""
    if is_distributed(tx):
        return tx
    return create_distributed_optimizer(tx, **kwargs)


def save_model(path: str, state: Any) -> None:
    """Checkpoint the training state's array leaves from rank 0 only (the
    reference's rank-0 checkpoint convention, SURVEY §5.4)."""
    _checkpoint.save(path, state)


def load_model(path: str, template: Any, root_rank: int = 0) -> Any:
    """Restore a training state saved by :func:`save_model`.

    ``template`` supplies the static structure — including the
    already-wrapped optimizer — which is how the Keras ``load_model``
    guarantee "the deserialized optimizer is still distributed"
    (``_keras/__init__.py:93-109``) carries over: the wrap never left the
    template. The restored state is broadcast from ``root_rank`` so all
    ranks resume identical (``keras/__init__.py:115-148``)."""
    return _checkpoint.restore(path, template=template, root_rank=root_rank)
