"""Mesh construction and multi-axis parallelism utilities (SURVEY §2.10)."""

from .hierarchical import (
    hierarchical_allgather,
    hierarchical_allreduce,
    hierarchical_grad_allreduce,
)
from .ring_attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from .mesh import (
    DATA_AXIS,
    DCN_AXIS,
    ICI_AXIS,
    data_parallel_mesh,
    hierarchical_mesh,
    local_mesh,
)

__all__ = [
    "DATA_AXIS", "DCN_AXIS", "ICI_AXIS",
    "data_parallel_mesh", "hierarchical_mesh", "local_mesh",
    "hierarchical_allreduce", "hierarchical_allgather",
    "hierarchical_grad_allreduce",
    "ring_attention", "ulysses_attention", "dense_attention",
]
