"""Mesh construction and multi-axis parallelism utilities (SURVEY §2.10)."""

from .mesh import (
    DATA_AXIS,
    DCN_AXIS,
    ICI_AXIS,
    data_parallel_mesh,
    hierarchical_mesh,
    local_mesh,
)

__all__ = [
    "DATA_AXIS", "DCN_AXIS", "ICI_AXIS",
    "data_parallel_mesh", "hierarchical_mesh", "local_mesh",
]
