"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention or sequence-parallel code of any kind
(SURVEY §5.7: verified absent — Horovod 0.16 predates it); this module is a
TPU-native extension so long-context training is first-class. Two
strategies, both expressed as in-jit collectives over a mesh axis:

* **Ring attention** (Liu et al. 2023, blockwise transformers): the
  sequence is sharded across the axis; K/V shards rotate around the ring
  via ``ppermute`` while each device accumulates its queries' attention
  with a numerically-stable online softmax (flash-attention style running
  max/denominator). Peak memory is O(T/S) per device and the ppermute
  transfers overlap with the per-block matmuls on TPU (ICI is
  bidirectional; XLA pipelines the ring).
* **Ulysses** (DeepSpeed-Ulysses): ``all_to_all`` re-shards from
  sequence-parallel to head-parallel, runs ordinary dense attention on full
  sequences for a head subset, and re-shards back. Cheaper at moderate
  sequence lengths when heads >= axis size.

Both match dense attention exactly (tests sweep causal and non-causal).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _rotate(x: jax.Array, axis_name: str) -> jax.Array:
    """Shift shards one step around the ring (i -> i+1 mod S)."""
    size = lax.axis_size(axis_name)
    perm = [(i, (i + 1) % size) for i in range(size)]
    return lax.ppermute(x, axis_name, perm=perm)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name``.

    Shapes (per shard): q, k, v — [batch, seq_local, heads, head_dim];
    returns [batch, seq_local, heads, head_dim]. Global sequence order is
    shard-major: shard i holds positions [i*seq_local, (i+1)*seq_local).

    Must be called inside shard_map/pjit with the sequence dimension
    sharded over ``axis_name``.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    seq_local = q.shape[1]
    size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    q_pos = my_idx * seq_local + jnp.arange(seq_local)  # [Tq]

    neg_inf = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    batch, _, heads, head_dim = q.shape
    # accumulators must be typed as varying up front (the scan carry's vma
    # type is fixed at entry) — over the ring axis AND any other mesh axis
    # the operands vary on (e.g. a batch axis when composing ring attention
    # with data parallelism on a 2-D mesh), since the body's outputs pick
    # up the operands' full vma set
    from ..ops.spmd import operand_vma

    vma = operand_vma(q, k, v)
    acc_axes = (axis_name,) if vma is None else tuple(vma | {axis_name})

    def _varying(x):
        return lax.pcast(x, acc_axes, to="varying")

    o0 = _varying(jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32))
    m0 = _varying(jnp.full((batch, heads, seq_local), neg_inf, jnp.float32))
    l0 = _varying(jnp.zeros((batch, heads, seq_local), jnp.float32))

    qf = q.astype(jnp.float32)

    def step(carry, j):
        o, m, l, k_blk, v_blk = carry
        # shard currently held after j rotations originated at (my - j) % S
        src = (my_idx - j) % size
        s = jnp.einsum("bthd,bshd->bhts", qf, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            k_pos = src * seq_local + jnp.arange(seq_local)  # [Tk]
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            s = jnp.where(mask[None, None], s, neg_inf)
        # online softmax update (flash-attention recurrence)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # rows that have seen nothing yet stay at -inf; avoid -inf - -inf
        corr = jnp.where(m == neg_inf, 0.0, jnp.exp(m - m_new))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # fully-masked rows produced exp(neg_inf - neg_inf) = 1; zero them
            p = jnp.where(m_new[..., None] == neg_inf, 0.0, p)
        l = l * corr + p.sum(axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhts,bshd->bthd", p, v_blk.astype(jnp.float32))
        k_blk = _rotate(k_blk, axis_name)
        v_blk = _rotate(v_blk, axis_name)
        return (o, m_new, l, k_blk, v_blk), None

    (o, _, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(size))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """Sequence parallelism by head re-sharding (DeepSpeed-Ulysses).

    Per-shard inputs [batch, seq_local, heads, head_dim] with heads
    divisible by the axis size. all_to_all converts to
    [batch, seq_global, heads/S, head_dim], dense attention runs per head
    subset, and the inverse all_to_all restores sequence sharding.
    """
    size = lax.axis_size(axis_name)
    if q.shape[2] % size != 0:
        raise ValueError(
            f"ulysses_attention requires heads ({q.shape[2]}) divisible by "
            f"the axis size ({size}); use ring_attention otherwise.")

    def to_headshard(x):
        # [B, Tl, H, D] -> [B, Tg, H/S, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seqshard(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_headshard(q), to_headshard(k), to_headshard(v)
    out = dense_attention(qh, kh, vh, causal=causal, scale=scale)
    return to_seqshard(out)


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: Optional[float] = None) -> jax.Array:
    """Reference dense attention, [batch, seq, heads, head_dim]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t, u = q.shape[1], k.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(u)[None, :]
        s = jnp.where(mask[None, None],
                      s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
