"""Hierarchical collectives: factored two-level reductions over (dcn, ici).

Rebuild of the reference's hierarchical allreduce/allgather
(``operations.cc:1284-1436``: NCCL ReduceScatter within the node → parallel
cross-node MPI_Allreduce → NCCL Allgather; ``:929-1033``: shared-memory
node-local allgather + cross-node Allgatherv). On TPU the same factoring is
expressed per mesh axis: the fast axis (``ici``, intra-slice interconnect)
does the scatter/gather legs; the slow axis (``dcn``, cross-slice data
center network) carries only the 1/|ici| reduced shard — exactly the
bandwidth shape the reference's hierarchy buys on GPU clusters.

Enabled the same way (``HOROVOD_HIERARCHICAL_ALLREDUCE``), or explicitly by
passing both axis names. XLA would often discover an equivalent schedule for
a flat psum over both axes; the explicit factoring guarantees it and makes
the knob meaningful on mixed ICI/DCN topologies.
"""

from __future__ import annotations

from typing import Dict, Tuple

# jax is imported inside each collective: the module also hosts the pure
# island-partition arithmetic the control-plane hierarchy planner
# (ops/hierarchy.py) reuses — the negotiation tree mirrors the SAME
# ICI-vs-DCN split these collectives factor over, and the coordinator
# must be importable in processes that never touch jax.


def island_partition(world_size: int,
                     n_islands: int) -> Dict[int, Tuple[int, ...]]:
    """Contiguous near-equal split of ``range(world_size)`` into
    ``n_islands`` islands — the control-plane mirror of the (dcn, ici)
    mesh factoring above: ranks within one island share the fast
    interconnect, island heads talk to the root over the slow one. The
    first ``world_size % n_islands`` islands take the extra rank
    (jax.sharding convention for uneven meshes). Returns
    {island id -> sorted global ranks}; every rank appears exactly once."""
    if n_islands <= 0:
        raise ValueError(f"n_islands must be positive, got {n_islands}")
    n_islands = min(n_islands, world_size) if world_size > 0 else 1
    base, extra = divmod(world_size, n_islands)
    islands: Dict[int, Tuple[int, ...]] = {}
    start = 0
    for i in range(n_islands):
        count = base + (1 if i < extra else 0)
        islands[i] = tuple(range(start, start + count))
        start += count
    return islands


def hierarchical_allreduce(x: "jax.Array", dcn_axis: str = "dcn",
                           ici_axis: str = "ici",
                           average: bool = True) -> "jax.Array":
    """reduce_scatter(ici) → allreduce(dcn) → all_gather(ici).

    The cross-slice leg moves |x| / |ici| bytes per chip instead of |x| —
    the factored form of ``operations.cc:1284-1436``. Requires the leading
    dimension be divisible by the ici axis size (pad upstream otherwise;
    the DistributedOptimizer flattens to 1-D multiples automatically)."""
    from jax import lax

    shard = lax.psum_scatter(x, ici_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, dcn_axis)
    out = lax.all_gather(shard, ici_axis, axis=0, tiled=True)
    if average:
        out = out / (lax.axis_size(ici_axis) * lax.axis_size(dcn_axis))
    return out


def hierarchical_allgather(x: "jax.Array", dcn_axis: str = "dcn",
                           ici_axis: str = "ici") -> "jax.Array":
    """all_gather(ici) then all_gather(dcn), concatenated in global rank
    order (node-local shared-memory gather + cross-node Allgatherv,
    ``operations.cc:929-1033``)."""
    from jax import lax

    local = lax.all_gather(x, ici_axis, axis=0, tiled=True)
    return lax.all_gather(local, dcn_axis, axis=0, tiled=True)


def hierarchical_quantized_allreduce(x: "jax.Array", dcn_axis: str = "dcn",
                                     ici_axis: str = "ici",
                                     average: bool = True,
                                     codec=None) -> "jax.Array":
    """The EQuARX design point: compress exactly the bandwidth-bound link.

    Same factoring as :func:`hierarchical_allreduce`, but the cross-slice
    ``psum`` — the slow DCN hop carrying 1/|ici| of the bytes — is
    replaced by :func:`ops.spmd.quantized_allreduce` (int8/fp8 wire,
    shared block scales). The ICI legs (reduce-scatter / all-gather) stay
    FULL precision: ICI bandwidth is not the bottleneck the hierarchy
    exists to protect, and keeping them exact halves the quantization
    error relative to quantizing the whole reduction."""
    from jax import lax

    from ..ops.spmd import quantized_allreduce

    shard = lax.psum_scatter(x, ici_axis, scatter_dimension=0, tiled=True)
    shard = quantized_allreduce(shard, dcn_axis, average=False, codec=codec)
    out = lax.all_gather(shard, ici_axis, axis=0, tiled=True)
    if average:
        out = out / (lax.axis_size(ici_axis) * lax.axis_size(dcn_axis))
    return out


def hierarchical_grad_allreduce(grads, dcn_axis: str = "dcn",
                                ici_axis: str = "ici",
                                average: bool = True,
                                codec=None):
    """Apply hierarchical_allreduce leaf-wise to a gradient pytree, padding
    each flattened leaf to a multiple of the ici axis size. A quantized
    ``codec`` (``Compression.int8`` / ``.fp8``) routes the DCN hop through
    :func:`hierarchical_quantized_allreduce`; float leaves only — integer
    leaves keep the exact full-precision route on both hops."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def reduce_leaf(g):
        flat = g.reshape(-1)
        ici = lax.axis_size(ici_axis)
        pad = (-flat.shape[0]) % ici
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        if codec is not None and getattr(codec, "quantized", False) and \
                jnp.issubdtype(flat.dtype, jnp.floating):
            reduced = hierarchical_quantized_allreduce(
                flat, dcn_axis, ici_axis, average, codec=codec)
        else:
            reduced = hierarchical_allreduce(flat, dcn_axis, ici_axis,
                                             average)
        if pad:
            reduced = reduced[:-pad]
        return reduced.reshape(g.shape)

    return jax.tree_util.tree_map(reduce_leaf, grads)
