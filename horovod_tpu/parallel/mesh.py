"""Device-mesh construction: the TPU replacement for communicators.

The reference builds three MPI communicators — world, node-local (shared
memory split), and cross-node (split by local rank)
(``horovod/common/operations.cc:1728-1797``) — and routes collectives over
them. On TPU the equivalent structure is a ``jax.sharding.Mesh`` whose axes
factor the device set the same way:

* 1-D ``('data',)`` mesh over every chip — the plain data-parallel world
  (analog of MPI_COMM_WORLD).
* 2-D ``('dcn', 'ici')`` mesh — hosts x local chips. Collectives factored
  per axis reproduce hierarchical allreduce/allgather (intra-node NCCL +
  inter-node MPI in the reference, ``operations.cc:1284-1436``): psum along
  ``ici`` rides the intra-slice interconnect; psum along ``dcn`` crosses the
  data-center network between slices.

XLA inserts and schedules the actual collectives; nothing here opens a
socket or owns a stream.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
ICI_AXIS = "ici"
DCN_AXIS = "dcn"


def data_parallel_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all devices: the MPI_COMM_WORLD analog."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (DATA_AXIS,))


def hierarchical_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """(dcn, ici) mesh: hosts x chips-per-host.

    Analog of the local/cross communicator pair
    (``operations.cc:1760-1797``). In a single-process world the ``dcn``
    axis has size 1 and every collective stays on ICI.
    """
    if devices is not None:
        devs = list(devices)
        n_hosts = 1
        per_host = len(devs)
    else:
        devs = jax.devices()
        n_hosts = jax.process_count()
        per_host = jax.local_device_count()
    grid = np.asarray(devs).reshape(n_hosts, per_host)
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def local_mesh() -> Mesh:
    """Mesh over this process's chips only (node-local communicator analog)."""
    return Mesh(np.asarray(jax.local_devices()), (DATA_AXIS,))
