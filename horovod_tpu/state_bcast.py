"""Parameter / optimizer-state broadcast: consistent (re)starts.

Rebuild of ``horovod/torch/__init__.py:200-348`` (``broadcast_parameters``,
``broadcast_optimizer_state`` with its scalar→tensor wrapping) and the
TF-side ``broadcast_variables``/``BroadcastGlobalVariablesHook``
(``tensorflow/__init__.py:95-148``). The reference's contribution to
checkpoint/resume is exactly this: push rank 0's state to every rank after
init or checkpoint restore (SURVEY §5.4); checkpoint *storage* is the
framework's job (orbax, here).

Works on arbitrary pytrees. Python scalars (ints/floats, e.g. optax step
counts or hyperparameters captured in state) are wrapped as 0-d arrays for
the wire and unwrapped to their original type on return — the reference does
the same dance for torch optimizer hyperparameters
(``torch/__init__.py:262-310``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import basics, ops


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Broadcast an arbitrary picklable object via a uint8 tensor.

    (Horovod grew ``broadcast_object`` in later versions; the 0.16 reference
    inlines the same pickle-to-tensor trick for optimizer state defaults —
    ``torch/__init__.py:313-326``.)"""
    import pickle

    name = name or "broadcast_object"
    if basics.size() == 1:
        return obj
    # Only root contributes bytes; everyone else submits an empty chunk, so
    # the ragged allgather (coordinator tensor_sizes) moves exactly one copy
    # of the payload — a broadcast built from allgather, like the reference's
    # sparse path builds allreduce from two allgathers
    # (``tensorflow/__init__.py:72-83``).
    if basics.rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    else:
        payload = np.zeros(0, dtype=np.uint8)
    gathered = ops.allgather(payload, name=f"{name}.data")
    return pickle.loads(np.ascontiguousarray(gathered).tobytes())


def broadcast_parameters(params: Any, root_rank: int = 0,
                         name_prefix: str = "broadcast_parameters") -> Any:
    """Return the pytree with every array leaf replaced by root's value
    (``torch/__init__.py:200-229``). Non-array leaves must already agree
    across ranks and are passed through."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = []
    handles = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, (int, float, bool, complex)) or leaf is None:
            handles.append((False, leaf))
            continue
        handles.append((True, ops.broadcast_async(
            leaf, root_rank, name=f"{name_prefix}.{i}")))
    for is_handle, value in handles:
        out.append(ops.synchronize(value) if is_handle else value)
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    """Broadcast optimizer state from root, wrapping scalar leaves as 0-d
    tensors for the wire (``torch/__init__.py:232-348``).

    All leaves are submitted asynchronously first, then synchronized in
    order — the same two-phase shape as ``broadcast_parameters`` — so the
    engine can fuse them into buckets; a synchronous per-leaf loop costs
    one full negotiation cycle per leaf (hundreds of cycles for an Adam
    state over a momentum+velocity tree)."""
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    staged = []  # (handle | None, scalar_type, passthrough)
    for i, leaf in enumerate(leaves):
        if leaf is None:
            staged.append((None, None, leaf))
            continue
        scalar_type = None
        if isinstance(leaf, (bool, int, float)):
            scalar_type = type(leaf)
            leaf = np.asarray(leaf)
        staged.append((ops.broadcast_async(
            leaf, root_rank, name=f"broadcast_optimizer_state.{i}"),
            scalar_type, None))
    out = []
    for handle, scalar_type, passthrough in staged:
        if handle is None:
            out.append(passthrough)
            continue
        result = ops.synchronize(handle)
        if scalar_type is not None:
            result = scalar_type(np.asarray(result).item())
        out.append(result)
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_global_variables(root_rank: int = 0, *, variables: Any) -> Any:
    """TF-parity name (``tensorflow/__init__.py:95-115``); identical to
    broadcast_parameters on an explicit pytree (JAX has no global variable
    collection to sweep)."""
    return broadcast_parameters(variables, root_rank)
