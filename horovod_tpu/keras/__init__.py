"""Standalone-keras front-end.

The reference ships two shims — ``horovod/keras`` (keras 1/2) and
``horovod/tensorflow/keras`` (tf.keras) — over one implementation
(``horovod/_keras``, SURVEY §2.5). In Keras 3 ``keras`` and ``tf.keras``
are the same package, so this module re-exports the single implementation
under the reference's second import path.
"""

from ..tensorflow.keras import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    allgather,
    allreduce,
    broadcast,
    broadcast_global_variables,
    broadcast_variables,
    callbacks,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size",
    "is_initialized", "mpi_threads_supported",
    "DistributedOptimizer", "Compression", "broadcast_variables",
    "broadcast_global_variables", "allreduce", "allgather", "broadcast",
    "load_model", "callbacks",
]
