"""Cross-rank consensus verification of post-allreduce state.

"All ranks hold bit-identical averaged gradients after every allreduce"
is the invariant everything else in synchronous data parallelism rests
on (1802.05799) — and nothing used to check it. Every
``HOROVOD_CONSENSUS_INTERVAL_STEPS`` fused allreduce batches each rank
digests the post-allreduce bytes it actually received (and, on commit,
its ``elastic.State`` tree) and piggybacks the digest window on its next
negotiation message (``RequestList``/``CacheRequest`` — the PR-3
cache-bit precedent for growing the cycle wire). The coordinator
compares:

* on the host data plane it holds an AUTHORITY digest — the combined
  buffer it framed for every rank — so a mismatch names the exact
  outlier rank even in a 2-rank world;
* elsewhere (XLA data plane, windows carrying state items) it falls
  back to majority vote across ranks; with no majority every
  disagreeing rank is named.

A mismatch escalates through the controller's abort machinery as a
structured :class:`core.status.ConsensusError` (ranks, tensor names) —
relaunch-and-restore through the elastic plane beats training on
silently diverged state. The native C++ controller wire predates the
digest field and degrades deterministically to local-only digesting
with a one-time warning, exactly like metrics/clock-sync did.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.logging import LOG
from ..obs.registry import registry as _metrics

# Observability plane (docs/metrics.md): windows emitted by this rank,
# windows judged by the coordinator, and mismatches per outlier rank.
_CONSENSUS_WINDOWS = _metrics().counter(
    "horovod_consensus_windows_total",
    "Digest windows this rank emitted to the coordinator")
_CONSENSUS_CHECKS = _metrics().counter(
    "horovod_consensus_checks_total",
    "Digest windows the coordinator compared across all ranks")
_CONSENSUS_MISMATCHES = _metrics().counter(
    "horovod_consensus_mismatches_total",
    "Consensus mismatches, labelled by the outlier rank",
    labels=("rank",))

# Digest item kinds: "batch" items compare positionally against the
# coordinator's authority stream; "state" items (elastic.State commits)
# only exist rank-side and compare rank-vs-rank.
BATCH = "batch"
STATE = "state"


def digest_bytes(*chunks: bytes) -> str:
    """16-hex-char blake2b — collision odds are irrelevant at gradient
    cadence, wire size is not (the digest rides every Nth cycle)."""
    h = hashlib.blake2b(digest_size=8)
    for chunk in chunks:
        h.update(chunk)
    return h.hexdigest()


def fold_digest(windows_by_rank) -> str:
    """Digest-of-digests over a {rank: windows} map in sorted-rank order —
    the per-level hierarchy fold (docs/hierarchy.md): an island head
    stamps this over the member digest windows it forwards, the root
    recomputes it over what arrived, and a mismatch means the windows
    were corrupted BETWEEN the levels (the per-rank judge then cannot be
    trusted to name the right outlier, so the island itself is named).
    ``None`` windows fold as an explicit absent marker so "rank sent
    nothing" and "rank's windows were dropped" stay distinguishable."""
    h = hashlib.blake2b(digest_size=8)
    for rank in sorted(windows_by_rank):
        h.update(str(rank).encode())
        windows = windows_by_rank[rank]
        h.update(b"\x00" if windows is None else repr(windows).encode())
    return h.hexdigest()


def tree_digest(tree) -> str:
    """Deterministic digest of a committed state pytree: per-leaf bytes +
    dtype/shape, folded in flatten order (tree_flatten sorts dict keys,
    so identical trees digest identically on every rank)."""
    import jax

    h = hashlib.blake2b(digest_size=8)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    h.update(str(treedef).encode())
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            a = np.asarray(leaf)
            h.update(str((a.dtype, a.shape)).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            h.update(repr(leaf).encode())
    return h.hexdigest()


class DigestAccumulator:
    """Rank-side half: folds executed allreduce batches (and external
    state commits) into digest windows of ``interval`` batches; completed
    windows are drained by the engine onto the next cycle message.

    A window tuple on the wire::

        (ordinal, [(kind, names, hexdigest), ...])

    Batches land in negotiated execution order — identical on every rank
    — so window N's item list is positionally comparable across ranks."""

    def __init__(self, interval: int) -> None:
        if interval < 1:
            raise ValueError(
                f"HOROVOD_CONSENSUS_INTERVAL_STEPS must be >= 1 to arm "
                f"consensus verification (got {interval})")
        self.interval = interval
        # Thread-safe: under sub-buffer flush pipelining the engine's
        # flush worker observes batches while the loop thread drains
        # completed windows onto the next cycle message.
        self._lock = threading.Lock()
        self._ordinal = 0
        self._batches = 0
        self._items: List[Tuple[str, Tuple[str, ...], str]] = []
        self._pending: List[tuple] = []
        self.windows_emitted = 0

    def observe_batch(self, names: Sequence[str], results) -> None:
        """Digest one reduced allreduce batch (pre-sentry: the bytes as
        received — a sentry rewrite is collective and would only mask the
        divergence this plane exists to catch)."""
        blobs = [np.ascontiguousarray(np.asarray(r)).tobytes()
                 for r in results]
        digest = digest_bytes(*blobs)
        with self._lock:
            self._items.append((BATCH, tuple(names), digest))
            self._batches += 1
            if self._batches >= self.interval:
                self._close_window()

    def observe_state(self, name: str, hexdigest: str) -> None:
        """External item (elastic.State commit): joins the current window
        without advancing the batch count, so window boundaries stay
        aligned with the coordinator's authority stream."""
        with self._lock:
            self._items.append((STATE, (name,), hexdigest))

    def _close_window(self) -> None:
        # caller holds self._lock
        self._ordinal += 1
        self._pending.append((self._ordinal, list(self._items)))
        self._items = []
        self._batches = 0
        self.windows_emitted += 1
        _CONSENSUS_WINDOWS.inc()
        # flight recorder (docs/blackbox.md): window seal with its
        # ordinal — what a consensus-fork verdict aligns ranks by
        from ..obs import flightrec as _flightrec

        _flightrec.record(_flightrec.EV_CONSENSUS_SEAL, self._ordinal)

    def drain(self) -> Optional[List[tuple]]:
        """Completed windows to piggyback on the next cycle message (None
        when nothing is pending — the common case, keeping the wire
        untouched between windows)."""
        with self._lock:
            if not self._pending:
                return None
            out, self._pending = self._pending, []
            return out


class ConsensusAuthority:
    """Coordinator-side authority stream: digests of the combined buffers
    the host-plane payload exchange framed — the value every rank SHOULD
    have received. Window boundaries mirror the rank accumulators (every
    ``interval`` allreduce combines), and every item carries the batch's
    tensor names: the judge only trusts an authority item whose names
    match the rank item at the same position, so a world where SOME
    batches bypass the payload exchange (device-plane reductions beside
    host-path fallbacks) can never be judged against the wrong batches —
    unmatched positions fall back to the rank-majority compare.
    Thread-safe: payload combines run on handler threads."""

    def __init__(self, interval: int) -> None:
        self.interval = interval
        self._lock = threading.Lock()
        self._ordinal = 0
        self._batches = 0
        self._items: List[Tuple[Tuple[str, ...], str]] = []
        self.windows: Dict[int, List[Tuple[Tuple[str, ...], str]]] = {}

    def observe_combine(self, names, combined: bytes) -> None:
        with self._lock:
            self._items.append((tuple(names), digest_bytes(combined)))
            self._batches += 1
            if self._batches >= self.interval:
                self._ordinal += 1
                self.windows[self._ordinal] = self._items
                self._items = []
                self._batches = 0
                # bounded memory: judged windows are popped by the judge;
                # keep a sliding guard against a world that never ships
                # digests (consensus off on the ranks)
                stale = self._ordinal - 64
                self.windows.pop(stale, None)

    def take(self, ordinal: int):
        with self._lock:
            return self.windows.pop(ordinal, None)


class ConsensusJudge:
    """Coordinator-side comparison: one verdict per (window ordinal) once
    every rank's digest arrived. Authority compare per batch position
    when the authority saw the same number of batches; rank-majority
    otherwise (XLA data plane, or windows carrying state items)."""

    # A window still short of the full rank set after this many NEWER
    # windows piled up will never complete: one rank's interval knob
    # drifted and it ships digests on a different cadence (or never).
    MAX_PENDING = 64

    def __init__(self, size: int,
                 authority: Optional[ConsensusAuthority] = None) -> None:
        self._size = size
        self._authority = authority
        self._pending: Dict[int, Dict[int, list]] = {}
        self._stale_warned = False
        self.mismatches = 0

    def submit(self, rank: int, windows: List[tuple]
               ) -> Optional[Tuple[List[int], List[str]]]:
        """Feed one rank's drained windows; returns ``(outlier_ranks,
        tensor_names)`` on the first mismatching window, else None."""
        verdict = None
        for ordinal, items in windows:
            slot = self._pending.setdefault(int(ordinal), {})
            slot[int(rank)] = list(items)
            if len(slot) < self._size:
                continue
            del self._pending[int(ordinal)]
            _CONSENSUS_CHECKS.inc()
            bad = self._judge(int(ordinal), slot)
            if bad is not None and verdict is None:
                verdict = bad
        # Bounded memory + a loud diagnosis for the reverse desync of the
        # one _judge_consensus warns about: a rank that never (or on a
        # different cadence) ships digests leaves every window one short
        # — verification silently never runs while the operator believes
        # it does, and pending windows pile up for the life of the job.
        while len(self._pending) > self.MAX_PENDING:
            stale = min(self._pending)
            short = self._pending.pop(stale)
            if not self._stale_warned:
                self._stale_warned = True
                missing = sorted(set(range(self._size)) - set(short))
                LOG.warning(
                    "consensus: window %d never received digests from "
                    "rank(s) %s and was dropped unjudged; "
                    "HOROVOD_CONSENSUS_INTERVAL_STEPS must resolve "
                    "identically on every rank — cross-rank "
                    "verification is NOT running.",
                    stale, ", ".join(map(str, missing)))
        return verdict

    def _judge(self, ordinal: int, slot: Dict[int, list]
               ) -> Optional[Tuple[List[int], List[str]]]:
        ranks = sorted(slot)
        lengths = {len(slot[r]) for r in ranks}
        if len(lengths) != 1:
            # structurally diverged windows: the ranks did not even agree
            # on what executed — name everyone, there is no arbiter
            return ranks, []
        n_items = lengths.pop()
        authority = {}
        if self._authority is not None:
            auth_items = self._authority.take(ordinal)
            batch_positions = [i for i in range(n_items)
                               if slot[ranks[0]][i][0] == BATCH]
            if auth_items is not None and \
                    len(auth_items) == len(batch_positions):
                # trust an authority item ONLY when its batch names match
                # the rank item at that position: in a mixed data-plane
                # world some rank batches never rode the payload exchange
                # and the two streams slip out of phase — an unmatched
                # position must fall to the rank-majority compare, never
                # be judged against the wrong batch's digest
                for pos, (auth_names, auth_digest) in zip(
                        batch_positions, auth_items):
                    if tuple(slot[ranks[0]][pos][1]) == auth_names:
                        authority[pos] = auth_digest
        outliers: set = set()
        names: List[str] = []
        for i in range(n_items):
            values = {r: slot[r][i][2] for r in ranks}
            item_names = list(slot[ranks[0]][i][1])
            if i in authority:
                ref = authority[i]
            else:
                # majority vote; a tie (2-rank world off the host plane)
                # has no arbiter — every disagreeing rank is named
                counts: Dict[str, int] = {}
                for v in values.values():
                    counts[v] = counts.get(v, 0) + 1
                ref, ref_n = max(counts.items(), key=lambda kv: kv[1])
                if ref_n <= len(ranks) // 2 and len(counts) > 1:
                    outliers.update(values)
                    names.extend(item_names)
                    continue
            bad = [r for r, v in values.items() if v != ref]
            if bad:
                outliers.update(bad)
                names.extend(item_names)
        if not outliers:
            return None
        for r in sorted(outliers):
            _CONSENSUS_MISMATCHES.labels(rank=r).inc()
        self.mismatches += 1
        # dedup names, preserve order
        seen: set = set()
        names = [n for n in names if not (n in seen or seen.add(n))]
        return sorted(outliers), names


def observe_commit(tree, commit_no: int) -> None:
    """elastic.State hook: fold a committed tree's digest into the live
    engine's consensus window (no-op when consensus is off or no engine
    is running — worlds outside run_elastic keep committing locally)."""
    from ..ops import engine as _engine_mod

    eng = _engine_mod._engine
    acc = getattr(eng, "_consensus_acc", None) if eng is not None else None
    if acc is None:
        return
    try:
        acc.observe_state(f"elastic.state.commit.{commit_no}",
                          tree_digest(tree))
    except Exception as exc:  # noqa: BLE001 - audit must not kill a commit
        LOG.warning("consensus: state-commit digest failed: %s", exc)
