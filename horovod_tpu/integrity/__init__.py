"""Data-plane integrity plane (docs/integrity.md).

Three coupled pieces guarding the invariant the rest of the system only
assumes — that after every allreduce all ranks hold bit-identical
reduced gradients and therefore bit-identical parameters:

* :mod:`.sentry` — the collective numerical-health sentry
  (``HOROVOD_GRAD_SENTRY=off|warn|skip|zero|abort``) over reduced
  gradients, on both the eager fused-buffer flushes and guarded SPMD
  reductions; verdicts are themselves collective, so skip/zero
  decisions can never desync the world.
* :mod:`.consensus` — cross-rank digest verification every
  ``HOROVOD_CONSENSUS_INTERVAL_STEPS`` fused batches (and of
  ``elastic.State`` on commit), escalating mismatches as structured
  :class:`~horovod_tpu.core.status.ConsensusError` through the elastic
  relaunch-and-restore path.
* data-plane chaos (``horovod_tpu.chaos``: ``nan@rankN:everyK`` /
  ``flipbits@rankN:everyK``) injected at the host-side fused-buffer
  boundary — the verifiable ground truth for both checks.
"""

from __future__ import annotations

from ..core.status import ConsensusError, NonFiniteGradError
from .consensus import (
    ConsensusAuthority,
    ConsensusJudge,
    DigestAccumulator,
    observe_commit,
    tree_digest,
)
from .sentry import POLICIES, GradSentry, spmd_guard, validate_policy

__all__ = [
    "ConsensusAuthority", "ConsensusError", "ConsensusJudge",
    "DigestAccumulator", "GradSentry", "NonFiniteGradError", "POLICIES",
    "observe_commit", "spmd_guard", "tree_digest", "validate_policy",
]
