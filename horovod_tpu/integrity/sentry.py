"""Collective numerical-health sentry over reduced gradients.

Synchronous data parallelism's core invariant (1802.05799 §2) is that
after every allreduce all ranks hold identical averaged gradients; a
single NaN/Inf entering that exchange poisons the optimizer state of
every rank forever. The sentry (``HOROVOD_GRAD_SENTRY``) screens every
reduced allreduce batch on the eager plane (``ops.engine``) and every
guarded SPMD reduction (``ops.spmd``) and applies one of four policies:

* ``warn``  — log + count, hand the values through unchanged.
* ``skip``  — zero EVERY tensor of the poisoned batch, so the optimizer
              step it feeds is a no-op (the reference-world idiom for
              "discard the step": ``params += lr * 0``).
* ``zero``  — zero only the non-finite tensors of the batch; finite
              siblings keep their values.
* ``abort`` — raise a structured :class:`core.status.NonFiniteGradError`
              through the PR-2 elastic abort path.

The verdict is COLLECTIVE: each rank ships its per-tensor finite bits
through a one-element controller rendezvous (OR across ranks, see
``ControllerService``'s ``sentry`` request) before applying the policy,
so skip/zero decisions are bit-identical on every rank and can never
desync the world — a rank whose local copy alone went bad (host bit
flip) is handled exactly like a NaN every rank can see. Where the
exchange is unavailable (size-1 worlds, the native controller's binary
wire, which predates the RPC) the sentry degrades deterministically to
the local verdict with a one-time warning — NaN propagates through a
sum, so the local views agree for every fault the reduction itself can
carry.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core.logging import LOG
from ..obs.registry import registry as _metrics

POLICIES = ("off", "warn", "skip", "zero", "abort")

# Observability plane (docs/metrics.md): trips are the operational
# signal ("is the data plane numerically healthy?"), checks make the
# clean-world zero-false-positive claim falsifiable (trips==0 is only
# meaningful when checks>0).
_SENTRY_TRIPS = _metrics().counter(
    "horovod_sentry_trips_total",
    "Non-finite reduced batches caught by the gradient sentry",
    labels=("policy", "kind"))
_SENTRY_CHECKS = _metrics().counter(
    "horovod_sentry_checks_total",
    "Reduced allreduce batches screened by the gradient sentry")


def validate_policy(policy: str) -> str:
    """A typo'd sentry policy silently checking nothing would certify
    nothing: unknown values fail LOUDLY at construction."""
    if policy not in POLICIES:
        raise ValueError(
            f"unknown HOROVOD_GRAD_SENTRY policy {policy!r}; expected one "
            f"of {'|'.join(POLICIES)}")
    return policy


def _local_bad(arr, probe=None) -> Tuple[bool, str]:
    """(non-finite?, kind) of one reduced tensor. Integer/bool dtypes are
    finite by construction. ``probe`` (the XLA plane's device-side
    census, ``XlaDataPlane.nonfinite_counts``) screens device-resident
    results by syncing two scalars; numpy results — and plane-less
    worlds — check host-side."""
    dtype = np.dtype(arr.dtype)
    if not np.issubdtype(dtype, np.floating):
        return False, ""
    if probe is not None and not isinstance(arr, np.ndarray):
        n_nan, n_inf = probe(arr)
        if n_nan:
            return True, "nan"
        if n_inf:
            return True, "inf"
        return False, ""
    a = np.asarray(arr)
    if np.isnan(a).any():
        return True, "nan"
    if not np.isfinite(a).all():
        return True, "inf"
    return False, ""


def _zero_like(arr):
    """Zero replacement preserving the result's array flavor (the engine
    hands device results to the finalizer, which expects jax arrays)."""
    if isinstance(arr, np.ndarray):
        return np.zeros_like(arr)
    try:
        import jax.numpy as jnp

        return jnp.zeros_like(arr)
    except Exception:  # noqa: BLE001 - non-jax exotic array: numpy wins
        return np.zeros_like(np.asarray(arr))


def pack_bits(bits: Sequence[bool]) -> bytes:
    """Per-tensor bad bits -> bytes for the verdict exchange."""
    out = bytearray((len(bits) + 7) // 8)
    for i, bit in enumerate(bits):
        if bit:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


def unpack_bits(data: bytes, n: int) -> List[bool]:
    return [bool(data[i // 8] >> (i % 8) & 1) for i in range(n)]


def or_bits(blobs: Sequence[bytes]) -> bytes:
    """The rendezvous combine: a tensor is bad if ANY rank saw it bad."""
    width = max(len(b) for b in blobs)
    out = bytearray(width)
    for blob in blobs:
        for i, byte in enumerate(blob):
            out[i] |= byte
    return bytes(out)


class GradSentry:
    """Per-engine sentry state: the batch ordinal (1-based; batches
    execute in negotiated order, so ordinal N names the SAME batch on
    every rank), the verdict exchange, and the audit trail.

    ``exchange(ordinal, bits) -> bits`` performs the collective OR; None
    degrades to the local verdict (size-1 worlds / native wire).
    ``on_trip(record)`` is the timeline hook (one metadata record per
    trip)."""

    def __init__(self, policy: str,
                 exchange: Optional[Callable[[int, bytes], bytes]] = None,
                 on_trip: Optional[Callable[[dict], None]] = None,
                 probe: Optional[Callable] = None) -> None:
        self.policy = validate_policy(policy)
        self._exchange = exchange
        self._on_trip = on_trip
        self._probe = probe
        self.ordinal = 0
        self.trips: List[Tuple[int, str, str]] = []  # (ordinal, action, kind)

    def screen_batch(self, names: Sequence[str], results: List,
                     precomputed: Optional[Tuple[int, int]] = None):
        """Screen one reduced allreduce batch; returns the (possibly
        policy-modified) results. Raises ``NonFiniteGradError`` under
        ``abort``. Must be called for EVERY allreduce batch while armed —
        the verdict exchange is a rendezvous, and a rank that skipped one
        would wedge the world (the same every-rank-every-cycle contract
        the negotiation itself relies on).

        ``precomputed`` is the apply-fused path's in-program two-scalar
        census ``(nan_count, inf_count)`` of the whole batch
        (docs/tensor-fusion.md §fused apply): the verdict then skips
        per-tensor probing and applies at BATCH granularity — every
        tensor's bit carries the batch verdict, so the collective
        exchange, the ordinals, and the skip/zero rewrite stay
        bit-identical on every rank, while the fused program's census
        gate has already made the poisoned step a no-op in-program."""
        if self.policy == "off":
            return results
        self.ordinal += 1
        _SENTRY_CHECKS.inc()
        if precomputed is not None:
            n_nan, n_inf = precomputed
            bad = bool(n_nan or n_inf)
            kind = "nan" if n_nan else ("inf" if n_inf else "")
            local = [(bad, kind)] * len(results)
        else:
            local = [_local_bad(r, self._probe) for r in results]
        bits = [bad for bad, _ in local]
        if self._exchange is not None:
            bits = unpack_bits(
                self._exchange(self.ordinal, pack_bits(bits)), len(bits))
        if not any(bits):
            return results
        bad_names = [n for n, bad in zip(names, bits) if bad]
        # kind: nan wins over inf for the label; a tensor bad only on a
        # PEER rank (collective bit set, local clean) reports as "peer" —
        # the local arrays cannot say which flavor the peer saw
        kinds = {k for (bad, k), bit in zip(local, bits) if bit and k}
        kind = "nan" if "nan" in kinds else ("inf" if kinds else "peer")
        action = self.policy
        _SENTRY_TRIPS.labels(policy=self.policy, kind=kind).inc()
        self.trips.append((self.ordinal, action, kind))
        # flight recorder (docs/blackbox.md): the verdict with its batch
        # ordinal — aligned across ranks by the collective exchange
        from ..obs import flightrec as _flightrec

        _flightrec.record(_flightrec.EV_SENTRY, self.ordinal,
                          detail=f"{action}:{kind}")
        record = {"step": self.ordinal, "policy": self.policy,
                  "kind": kind, "tensors": list(bad_names)}
        if self._on_trip is not None:
            try:
                self._on_trip(record)
            except Exception:  # noqa: BLE001 - audit must not kill a batch
                pass
        if self.policy == "warn":
            LOG.warning(
                "grad sentry: non-finite (%s) reduced values in %s at "
                "step %d; HOROVOD_GRAD_SENTRY=warn hands them through",
                kind, bad_names, self.ordinal)
            return results
        if self.policy == "abort":
            from ..core.status import NonFiniteGradError, format_nonfinite

            reason = (
                f"grad sentry: non-finite ({kind}) reduced values at "
                f"step {self.ordinal}; HOROVOD_GRAD_SENTRY=abort. "
                f"{format_nonfinite(self.ordinal, bad_names)}")
            LOG.error("%s", reason)
            raise NonFiniteGradError(self.ordinal, bad_names, reason)
        if self.policy == "skip":
            LOG.warning(
                "grad sentry: non-finite (%s) values in %s at step %d; "
                "zeroing the WHOLE batch (skip) — the step it feeds is a "
                "no-op on every rank", kind, bad_names, self.ordinal)
            return [_zero_like(r) for r in results]
        # zero: null only the non-finite tensors
        LOG.warning(
            "grad sentry: non-finite (%s) values at step %d; zeroing "
            "only %s (zero)", kind, self.ordinal, bad_names)
        return [_zero_like(r) if bad else r
                for r, bad in zip(results, bits)]

    def stats(self) -> dict:
        return {"policy": self.policy, "checks": self.ordinal,
                # whether verdicts actually fold across ranks: a local-
                # only degrade (native wire, size-1) reads False, so a
                # test asserting collectivity cannot pass on a silently
                # unwired exchange
                "collective": self._exchange is not None,
                "trips": list(self.trips)}


# -- SPMD guard (ops.spmd) ----------------------------------------------------

# Trace-time counter, like the other SPMD families (docs/metrics.md):
# guarded LOWERINGS, not runtime trips — inside a compiled program the
# verdict lives on-device, and the policy applies as pure jnp ops.
_SENTRY_SPMD = _metrics().counter(
    "horovod_sentry_spmd_guards_total",
    "SPMD reductions lowered with the gradient sentry guard "
    "(per trace, not per step)", labels=("policy",))

_spmd_abort_warned = False


def spmd_guard(out, operand, axis_name, policy: str):
    """In-program sentry for the SPMD reduction paths (docs/integrity.md).

    The verdict is collective BY CONSTRUCTION: the bad count of the local
    operand is psum-med alongside the data, and the reduced output is
    identical on every rank, so every rank computes the identical verdict
    and the where-policy below is bit-identical — no exchange needed.
    Policies map to tensor granularity (one call == one tensor): ``skip``
    and ``zero`` both zero this tensor on a trip; ``warn`` prints from
    the device (``jax.debug.print``); ``abort`` cannot raise from inside
    a compiled program and deterministically degrades to ``skip`` with a
    one-time trace-time warning."""
    global _spmd_abort_warned
    validate_policy(policy)
    if policy == "off":
        return out
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops.spmd import _axes

    _SENTRY_SPMD.labels(policy=policy).inc()
    if not jnp.issubdtype(out.dtype, jnp.floating):
        return out
    local_bad = (~jnp.isfinite(operand)).sum()
    world_bad = local_bad
    for a in _axes(axis_name):
        world_bad = lax.psum(world_bad, a)
    bad = world_bad + (~jnp.isfinite(out)).sum()
    if policy == "warn":
        def _say(n):
            jax.debug.print(
                "grad sentry (spmd): {n} non-finite elements in a "
                "guarded reduction (HOROVOD_GRAD_SENTRY=warn)", n=n)
        lax.cond(bad > 0, _say, lambda n: None, bad)
        return out
    if policy == "abort" and not _spmd_abort_warned:
        _spmd_abort_warned = True
        LOG.warning(
            "HOROVOD_GRAD_SENTRY=abort cannot raise from inside a "
            "compiled SPMD program; degrading to skip (zeroed tensor) "
            "there — the eager plane keeps the structured abort.")
    # skip / zero / (degraded) abort: tensor-granularity null
    return jnp.where(bad > 0, jnp.zeros_like(out), out)
