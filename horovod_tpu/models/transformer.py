"""Decoder-only Transformer LM with pluggable attention backends.

The reference has no model code at all (SURVEY §5.7: tensors are opaque
byte buffers); its examples pull models from torchvision/Keras apps. This
build's models live in-repo, and the transformer is the flagship for the
long-context extensions: the same module runs dense attention, the Pallas
flash kernel (``ops.pallas_attention``), or sequence-parallel ring/Ulysses
attention (``parallel.ring_attention``) — selected by a config knob, so the
examples/benchmarks can compare backends without touching model code.

TPU-first choices: bf16 compute with f32 params, pre-LayerNorm residual
blocks, static shapes throughout, causal masking only (an LM), positions
passed in explicitly so sequence-parallel shards (shard-major global order)
embed their true global positions.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

ATTENTION_BACKENDS = ("dense", "flash", "ring", "ulysses")


class CausalSelfAttention(nn.Module):
    """Multi-head causal self-attention over [B, T, d_model]."""

    num_heads: int
    dtype: Any = jnp.bfloat16
    attention: str = "dense"
    seq_axis: Optional[str] = None  # mesh axis for ring/ulysses

    @nn.compact
    def __call__(self, x, positions):
        if self.attention not in ATTENTION_BACKENDS:
            raise ValueError(
                f"attention must be one of {ATTENTION_BACKENDS}, "
                f"got {self.attention!r}")
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(f"d_model {d_model} not divisible by "
                             f"{self.num_heads} heads")
        head_dim = d_model // self.num_heads
        dense = partial(nn.DenseGeneral, dtype=self.dtype,
                        features=(self.num_heads, head_dim))
        q = dense(name="query")(x)
        k = dense(name="key")(x)
        v = dense(name="value")(x)  # each [B, T, H, Dh]

        if self.attention == "flash":
            from ..ops.pallas_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif self.attention == "ring":
            from ..parallel.ring_attention import ring_attention

            if self.seq_axis is None:
                raise ValueError("attention='ring' requires seq_axis")
            out = ring_attention(q, k, v, self.seq_axis, causal=True)
        elif self.attention == "ulysses":
            from ..parallel.ring_attention import ulysses_attention

            if self.seq_axis is None:
                raise ValueError("attention='ulysses' requires seq_axis")
            out = ulysses_attention(q, k, v, self.seq_axis, causal=True)
        else:
            from ..parallel.ring_attention import dense_attention

            out = dense_attention(q, k, v, causal=True)
        del positions  # causal order is positional by construction
        out = out.astype(self.dtype)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attention: str = "dense"
    seq_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, positions):
        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        x = x + CausalSelfAttention(
            num_heads=self.num_heads, dtype=self.dtype,
            attention=self.attention, seq_axis=self.seq_axis,
            name="attn")(h, positions)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        return x + nn.Dense(x.shape[-1], dtype=self.dtype, name="mlp_out")(h)


class TransformerLM(nn.Module):
    """GPT-style LM: token + learned position embeddings, N pre-LN blocks,
    tied-free output head. Returns f32 logits [B, T, vocab].

    ``positions`` (global token positions, [B, T]) defaults to
    ``arange(T)``; sequence-parallel callers pass the shard's global
    positions (shard-major: shard i holds [i*T_local, (i+1)*T_local)).
    """

    vocab_size: int
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 256
    d_ff: int = 1024
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention: str = "dense"
    seq_axis: Optional[str] = None
    # jax.checkpoint each block: only the L block-boundary activations are
    # stored; each block's interior (attention scores, MLP intermediates —
    # the dominant term) is recomputed in backward. ~1/3 more FLOPs for
    # roughly d_ff/d_model-fold less activation memory — the standard
    # lever for long sequences on HBM-bound chips.
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="tok_embed")(tokens)
        x = x + nn.Embed(self.max_seq_len, self.d_model, dtype=self.dtype,
                         name="pos_embed")(positions)
        block_cls = nn.remat(TransformerBlock) if self.remat \
            else TransformerBlock
        for i in range(self.num_layers):
            x = block_cls(
                num_heads=self.num_heads, d_ff=self.d_ff, dtype=self.dtype,
                attention=self.attention, seq_axis=self.seq_axis,
                name=f"block_{i}")(x, positions)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        logits = nn.Dense(self.vocab_size, dtype=jnp.float32,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy (shift-by-one), mean over B and T-1."""
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]).mean()
