"""Inception V3 in flax, for the reference's headline benchmark trio.

The reference's published scaling chart benchmarks Inception V3 first
(``docs/benchmarks.md:5-6``, README benchmark paragraph). Architecture
follows Szegedy et al. 2015 (the tf_cnn_benchmarks/torchvision inception_v3
graph): stem → 3x InceptionA (35x35) → ReductionA → 4x InceptionB (17x17)
→ ReductionB → 2x InceptionC (8x8) → global pool → head. The auxiliary
classifier is omitted — it exists for training regularization, not
throughput, and the synthetic benchmark protocol never reads it.

NHWC, bf16 compute with f32 params, f32 head. Input 299x299x3.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    """Conv + BatchNorm + ReLU, the Inception building block."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = 0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(64, (1, 1))(x, train)
        b5 = cbn(48, (1, 1))(x, train)
        b5 = cbn(64, (5, 5), padding=2)(b5, train)
        b3 = cbn(64, (1, 1))(x, train)
        b3 = cbn(96, (3, 3), padding=1)(b3, train)
        b3 = cbn(96, (3, 3), padding=1)(b3, train)
        bp = cbn(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(384, (3, 3), strides=(2, 2))(x, train)
        bd = cbn(64, (1, 1))(x, train)
        bd = cbn(96, (3, 3), padding=1)(bd, train)
        bd = cbn(96, (3, 3), strides=(2, 2))(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    """17x17 block with factorized 7x7 convolutions."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b7 = cbn(c7, (1, 1))(x, train)
        b7 = cbn(c7, (1, 7), padding=((0, 0), (3, 3)))(b7, train)
        b7 = cbn(192, (7, 1), padding=((3, 3), (0, 0)))(b7, train)
        bd = cbn(c7, (1, 1))(x, train)
        bd = cbn(c7, (7, 1), padding=((3, 3), (0, 0)))(bd, train)
        bd = cbn(c7, (1, 7), padding=((0, 0), (3, 3)))(bd, train)
        bd = cbn(c7, (7, 1), padding=((3, 3), (0, 0)))(bd, train)
        bd = cbn(192, (1, 7), padding=((0, 0), (3, 3)))(bd, train)
        bp = cbn(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(192, (1, 1))(x, train)
        b3 = cbn(320, (3, 3), strides=(2, 2))(b3, train)
        b7 = cbn(192, (1, 1))(x, train)
        b7 = cbn(192, (1, 7), padding=((0, 0), (3, 3)))(b7, train)
        b7 = cbn(192, (7, 1), padding=((3, 3), (0, 0)))(b7, train)
        b7 = cbn(192, (3, 3), strides=(2, 2))(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    """8x8 block with split 3x3 branches."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320, (1, 1))(x, train)
        b3 = cbn(384, (1, 1))(x, train)
        b3a = cbn(384, (1, 3), padding=((0, 0), (1, 1)))(b3, train)
        b3b = cbn(384, (3, 1), padding=((1, 1), (0, 0)))(b3, train)
        bd = cbn(448, (1, 1))(x, train)
        bd = cbn(384, (3, 3), padding=1)(bd, train)
        bda = cbn(384, (1, 3), padding=((0, 0), (1, 1)))(bd, train)
        bdb = cbn(384, (3, 1), padding=((1, 1), (0, 0)))(bd, train)
        bp = cbn(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3a, b3b, bda, bdb, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299 -> 35x35x192
        x = cbn(32, (3, 3), strides=(2, 2))(x, train)
        x = cbn(32, (3, 3))(x, train)
        x = cbn(64, (3, 3), padding=1)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cbn(80, (1, 1))(x, train)
        x = cbn(192, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = ReductionA(dtype=self.dtype)(x, train)
        # 17x17
        x = InceptionB(128, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(192, dtype=self.dtype)(x, train)
        x = ReductionB(dtype=self.dtype)(x, train)
        # 8x8
        x = InceptionC(dtype=self.dtype)(x, train)
        x = InceptionC(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x).astype(jnp.float32)
