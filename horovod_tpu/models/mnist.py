"""Small MNIST convnet matching the reference example architectures
(``examples/pytorch_mnist.py:40-55``, ``examples/keras_mnist.py``): two
convs + max-pool + dropout-free dense head, the model every end-to-end smoke
example trains data-parallel."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [batch, 28, 28, 1] NHWC
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x
