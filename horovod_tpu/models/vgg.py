"""VGG in flax, for the reference's headline benchmark trio.

The reference's published scaling chart benchmarks Inception V3, ResNet-101
and VGG-16 (``docs/benchmarks.md:5-6``: ~90%/~90%/~68% efficiency at 512
GPUs — VGG's huge FC layers make it the communication-bound worst case,
which is exactly why it belongs in the benchmark set). NHWC, bf16 compute
with f32 params, classifier head in f32.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

# 'M' = 2x2 max pool; numbers = conv output channels (3x3)
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")
_VGG19_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]] = _VGG16_CFG
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        conv_i = 0
        for item in self.cfg:
            if item == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(item, (3, 3), padding=1, dtype=self.dtype,
                            name=f"conv_{conv_i}")(x)
                x = nn.relu(x)
                conv_i += 1
        x = x.reshape((x.shape[0], -1))  # [B, 7*7*512] at 224x224
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x).astype(jnp.float32)


VGG16 = partial(VGG, cfg=_VGG16_CFG)
VGG19 = partial(VGG, cfg=_VGG19_CFG)
