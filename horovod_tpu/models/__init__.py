"""Benchmark / example model zoo.

The reference ships no model code — its examples import torchvision / Keras
applications (SURVEY §2.8). This environment has no TPU-side model zoo, so
the models the benchmarks need (ResNet-50/101, a small MNIST convnet) are
implemented here in flax, sized and configured to match the reference
benchmark protocol (``examples/pytorch_synthetic_benchmark.py``).
"""

from .inception import InceptionV3
from .mnist import MnistCNN
from .resnet import ResNet, ResNet50, ResNet101
from .transformer import TransformerLM, lm_loss
from .vgg import VGG16, VGG19

__all__ = ["MnistCNN", "ResNet", "ResNet50", "ResNet101",
           "TransformerLM", "lm_loss", "VGG16", "VGG19", "InceptionV3"]
