"""PyTorch front-end: gradient-averaging optimizer wrapper + state broadcast.

Rebuild of ``horovod/torch/__init__.py`` on the TPU-native engine: the
``_DistributedOptimizer`` registers per-parameter hooks that fire an async
allreduce as each gradient is produced (``torch/__init__.py:95-130``),
``synchronize()`` waits and installs the averaged gradients
(``:132-147``), ``step()`` = synchronize + inner step (``:149-151``), and
``backward_passes_per_step`` delays the allreduce across N backward passes
(``:71-73,114-130``). Tensor handoff is zero-copy where torch allows
(``Tensor.numpy()`` shares memory for CPU tensors); bfloat16 — which numpy
lacks — goes through an explicit f32 view on the wire.

Per BASELINE.json, gradients are handed to the XLA-compiled fused allreduce
rather than enqueued as CUDA NCCL ops; in multi-process CPU worlds the host
plane carries them (the engine decides, ``ops.engine``).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Iterator, Optional, Tuple

import numpy as np
import torch

from .. import basics
from .. import ops as _ops
# Process-control surface re-exported like the reference's
# ``horovod.torch`` namespace (``torch/mpi_ops.py:42-51``): users do
# ``import horovod_tpu.torch as hvd; hvd.init(); hvd.rank()``.
from ..basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from ..ops.compression import Compression

__all__ = [
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "mpi_threads_supported",
    "Compression",
    "DistributedOptimizer",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
    "synchronize", "poll",
]


def _to_numpy(tensor: torch.Tensor) -> Tuple[np.ndarray, Optional[torch.dtype]]:
    """CPU torch tensor → numpy (shared memory when possible). bfloat16 is
    widened to f32 for the wire; the caller narrows back."""
    t = tensor.detach()
    if t.dtype == torch.bfloat16:
        return t.float().numpy(), torch.bfloat16
    return t.numpy(), None


def _from_numpy(arr: np.ndarray, narrow_to: Optional[torch.dtype]) -> torch.Tensor:
    out = torch.from_numpy(np.ascontiguousarray(arr))
    if narrow_to is not None:
        out = out.to(narrow_to)
    return out


# -- eager ops on torch tensors ----------------------------------------------

def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None,
                    compression=Compression.none) -> int:
    arr, narrow = _to_numpy(tensor)
    handle = _ops.allreduce_async(arr, average=average, name=name,
                                  compression=compression)
    _narrow_map[handle] = narrow
    return handle


class _HorovodAllreduce(torch.autograd.Function):
    """Differentiable allreduce (reference ``mpi_ops.py:110-121``):
    the gradient of a sum-over-ranks is the same sum of the upstream
    gradients, with matching ``average`` semantics."""

    @staticmethod
    def forward(ctx, tensor, average, name, compression):
        ctx.average = average
        return synchronize(
            allreduce_async(tensor, average=average, name=name,
                            compression=compression))

    @staticmethod
    def backward(ctx, grad_output):
        return (allreduce(grad_output.contiguous(), average=ctx.average),
                None, None, None)


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None,
              compression=Compression.none) -> torch.Tensor:
    return _HorovodAllreduce.apply(tensor, average, name, compression)


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None,
                     compression=Compression.none) -> int:
    """In-place async allreduce (reference ``mpi_ops.py:156-178``): the
    result is written back into ``tensor`` when synchronized."""
    handle = allreduce_async(tensor, average=average, name=name,
                             compression=compression)
    _track_inplace(handle, tensor)
    return handle


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None,
               compression=Compression.none) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average=average, name=name,
                                        compression=compression))


def allgather_async(tensor: torch.Tensor,
                    name: Optional[str] = None) -> int:
    arr, narrow = _to_numpy(tensor)
    handle = _ops.allgather_async(arr, name=name)
    _narrow_map[handle] = narrow
    return handle


class _HorovodAllgather(torch.autograd.Function):
    """Differentiable allgather (reference ``mpi_ops.py:236-254``): the
    upstream gradient of the concatenated output is summed across ranks,
    and each rank keeps the slice matching its own contribution."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim = tensor.shape[0]
        return synchronize(allgather_async(tensor, name=name))

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = allreduce(grad_output.contiguous(), average=False)
        # int32, as the reference's IntTensor: int64 would force this
        # exchange off the XLA device plane whenever x64 is disabled
        dims = allgather(
            torch.tensor([ctx.dim], dtype=torch.int32)).view(basics.size())
        r = basics.rank()
        offset = int(dims.narrow(0, 0, r).sum()) if r != 0 else 0
        return grad_reduced.narrow(0, offset, ctx.dim), None


def allgather(tensor: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    return _HorovodAllgather.apply(tensor, name)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    arr, narrow = _to_numpy(tensor)
    handle = _ops.broadcast_async(arr, root_rank, name=name)
    _narrow_map[handle] = narrow
    return handle


class _HorovodBroadcast(torch.autograd.Function):
    """Differentiable broadcast (reference ``mpi_ops.py:318-332``): all
    gradients flow back to the root; non-root inputs never influenced the
    output, so their gradient is zero."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name=name))

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = allreduce(grad_output.contiguous(), average=False)
        if basics.rank() != ctx.root_rank:
            grad_reduced = grad_reduced * 0
        return grad_reduced, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    """In-place async broadcast (reference ``mpi_ops.py:361-382``)."""
    handle = broadcast_async(tensor, root_rank, name=name)
    _track_inplace(handle, tensor)
    return handle


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name=name))


_narrow_map: dict = {}
_inplace_map: dict = {}
# Abandoned handles (async op issued, synchronize never called — e.g. an
# exception between the two) must not pin gradient-sized tensors forever;
# mirror the engine HandleManager's bounded retention.
_MAX_TRACKED = 1 << 16


def _track_inplace(handle: int, tensor: torch.Tensor) -> None:
    _inplace_map[handle] = tensor
    while len(_inplace_map) > _MAX_TRACKED:
        _inplace_map.pop(next(iter(_inplace_map)))
    while len(_narrow_map) > _MAX_TRACKED:
        _narrow_map.pop(next(iter(_narrow_map)))


def poll(handle: int) -> bool:
    return _ops.poll(handle)


def synchronize(handle: int) -> torch.Tensor:
    narrow = _narrow_map.pop(handle, None)
    target = _inplace_map.pop(handle, None)
    result = _ops.synchronize(handle)
    out = _from_numpy(np.asarray(result), narrow)
    if target is not None:
        # In-place semantics: the caller's tensor receives the result (the
        # reference's op writes into the input buffer directly). Leaf
        # parameters with requires_grad are the canonical use — the write
        # is data movement, not an autograd-tracked operation.
        with torch.no_grad():
            target.copy_(out.reshape(target.shape))
        return target
    return out


# -- DistributedOptimizer ------------------------------------------------------

class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step) -> None:
        # These methods are transplanted into a dynamic subclass of the
        # user's optimizer class (see DistributedOptimizer below), so
        # zero-arg super() would bind the wrong class cell; the explicit
        # two-arg form resolves to the wrapped optimizer class, exactly as
        # the reference does (``torch/__init__.py:66-69``).
        super(self.__class__, self).__init__(params)
        self._compression = compression
        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            # fall back to positional names, as the reference warns about
            # (``torch/__init__.py:77-90``)
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for param_group in self.param_groups
                for i, v in enumerate(param_group["params"])]
        dups = _find_duplicates([name for name, _ in named_parameters])
        if dups:
            raise ValueError(
                f"Parameter names in named_parameters must be unique; "
                f"found duplicates: {sorted(dups)}")
        self._parameter_names = {v: name for name, v in named_parameters}
        self.backward_passes_per_step = backward_passes_per_step
        self._allreduce_delay = {}
        self._handles = {}
        self._grad_accs = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        if basics.size() > 1:
            self._register_hooks()

    def _register_hooks(self) -> None:
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    p.register_post_accumulate_grad_hook(self._make_hook(p))

    def _allreduce_grad_async(self, p: torch.Tensor) -> int:
        name = self._parameter_names.get(p)
        return allreduce_async(p.grad, average=True, name=name,
                               compression=self._compression)

    def _make_hook(self, p: torch.Tensor):
        def hook(*ignore):
            if p in self._handles and self._handles[p] is not None:
                if self._allreduce_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally.")
            assert not p.grad.requires_grad
            assert self._allreduce_delay[p] > 0
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._handles[p] = self._allreduce_grad_async(p)

        return hook

    def synchronize(self) -> None:
        """Wait for all outstanding allreduces and install averaged grads
        (``torch/__init__.py:132-147``)."""
        missing = [p for p in self._requires_update if p not in self._handles]
        for p in missing:
            # force allreduce of unused grads (reference
            # ``test_force_allreduce`` semantics): a rank must not skip a
            # collective other ranks will wait on
            if p.grad is None:
                p.grad = p.data.new_zeros(p.shape)
            self._handles[p] = self._allreduce_grad_async(p)
        for p, handle in list(self._handles.items()):
            if handle is None:
                continue
            output = synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            p.grad.copy_(output.reshape(p.grad.shape))
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self) -> Iterator[None]:
        """Let the caller run ``synchronize()`` manually before ``step()``
        (reference API, ``torch/__init__.py:153-160``)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if basics.size() > 1 and self._should_synchronize:
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)


def _find_duplicates(names):
    seen, dups = set(), set()
    for n in names:
        if n in seen:
            dups.add(n)
        seen.add(n)
    return dups


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1):
    """Wrap a torch optimizer so ``step()`` applies world-averaged gradients
    (``torch/__init__.py:163-198``: a dynamic subclass of the user's
    optimizer class, initialized from its param_groups so per-group
    hyperparameters carry over)."""
    donor = {k: v for k, v in _DistributedOptimizer.__dict__.items()
             if k not in ("__dict__", "__weakref__")}
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,), donor)
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)


# -- state broadcast -----------------------------------------------------------

def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a state_dict or named-parameter iterable
    (``torch/__init__.py:200-229``)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    if basics.size() == 1:
        return
    handles = [broadcast_async_(p, root_rank,
                                name=f"broadcast_parameters.{name}")
               for name, p in items if isinstance(p, torch.Tensor)]
    for h in handles:
        synchronize(h)  # in-place: writes straight into each parameter


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer hyperparameters and per-parameter state from
    root (``torch/__init__.py:232-348``).

    Root's state STRUCTURE is broadcast first, and every rank conforms to it
    before any tensor collective is posted — so a root that restored a
    checkpoint (populated momentum buffers) and workers with freshly
    constructed optimizers (empty state) still issue identical collectives;
    missing tensors are materialized as zeros and filled by the broadcast,
    extra local entries are dropped. The reference achieves the same
    alignment with its scalar-wrapping + recursive cast callbacks over
    root's structure."""
    from ..state_bcast import broadcast_object

    if basics.size() == 1:
        return
    state_dict = optimizer.state_dict()

    # 1) ship root's structure: param_groups + per-parameter state specs
    meta: Optional[dict] = None
    if basics.rank() == root_rank:
        meta = {"param_groups": state_dict["param_groups"], "state": {}}
        for pid, pstate in state_dict["state"].items():
            specs = {}
            for key, value in pstate.items():
                if isinstance(value, torch.Tensor):
                    specs[key] = ("tensor", list(value.shape),
                                  str(value.dtype))
                else:
                    specs[key] = ("scalar", value)
            meta["state"][pid] = specs
    meta = broadcast_object(meta, root_rank,
                            name="broadcast_optimizer_state.meta")

    # 2) conform local state to root's structure
    new_state: dict = {}
    for pid, specs in meta["state"].items():
        entry: dict = {}
        for key, spec in specs.items():
            if spec[0] == "scalar":
                entry[key] = spec[1]
                continue
            _, shape, dtype_str = spec
            dtype = getattr(torch, dtype_str.replace("torch.", ""))
            local = state_dict["state"].get(pid, {}).get(key)
            if isinstance(local, torch.Tensor) and \
                    list(local.shape) == shape and local.dtype == dtype:
                entry[key] = local
            else:
                entry[key] = torch.zeros(shape, dtype=dtype)
        new_state[pid] = entry

    # 3) identical tensor collectives on every rank, in deterministic order
    handles = [
        broadcast_async_(new_state[pid][key], root_rank,
                         name=f"broadcast_optimizer_state.{pid}.{key}")
        for pid in sorted(new_state)
        for key in sorted(k for k, s in meta["state"][pid].items()
                          if s[0] == "tensor")
    ]
    for h in handles:
        synchronize(h)  # in-place: fills the conformed state tensors

    state_dict["state"] = new_state
    for group, group_meta in zip(state_dict["param_groups"],
                                 meta["param_groups"]):
        for key, value in group_meta.items():
            if key != "params":
                group[key] = value
    optimizer.load_state_dict(state_dict)
