// Native autotuner: Gaussian-process surrogate + expected-improvement
// Bayesian optimization over (fusion threshold, cycle time).
//
// TPU-native rebuild of horovod/common/parameter_manager.{h,cc} with
// optim/gaussian_process.{h,cc} (RBF kernel + Cholesky regression) and
// optim/bayesian_optimization.{h,cc} (EI acquisition). The reference uses
// Eigen + LBFGS; this build vendors nothing — the GP works on small dense
// matrices (tens of samples) with a hand-rolled Cholesky, and the kernel
// length-scale is fixed rather than LBFGS-optimized (the reference tunes 2
// parameters over ~dozens of samples; marginal-likelihood optimization
// buys little at that scale).
//
// Scoring protocol matches parameter_manager.cc:145-171: the score of a
// parameter point is throughput in bytes/microsecond accumulated over a
// sample window, and each point is scored as the median of several windows
// before the optimizer moves on.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

// ---- tiny dense linear algebra (row-major) ---------------------------------

using Vec = std::vector<double>;
using Mat = std::vector<Vec>;

// Cholesky decomposition of a symmetric positive-definite matrix.
// Returns false if the matrix is not SPD (caller bumps the jitter).
bool Cholesky(const Mat& a, Mat* l_out) {
  const size_t n = a.size();
  Mat l(n, Vec(n, 0.0));
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double sum = a[i][j];
      for (size_t k = 0; k < j; ++k) sum -= l[i][k] * l[j][k];
      if (i == j) {
        if (sum <= 0.0) return false;
        l[i][i] = std::sqrt(sum);
      } else {
        l[i][j] = sum / l[j][j];
      }
    }
  }
  *l_out = std::move(l);
  return true;
}

Vec CholSolve(const Mat& l, const Vec& b) {
  const size_t n = l.size();
  Vec y(n), x(n);
  for (size_t i = 0; i < n; ++i) {  // forward: L y = b
    double sum = b[i];
    for (size_t k = 0; k < i; ++k) sum -= l[i][k] * y[k];
    y[i] = sum / l[i][i];
  }
  for (size_t i = n; i-- > 0;) {  // backward: L^T x = y
    double sum = y[i];
    for (size_t k = i + 1; k < n; ++k) sum -= l[k][i] * x[k];
    x[i] = sum / l[i][i];
  }
  return x;
}

// ---- Gaussian process regressor (RBF kernel) -------------------------------
// Port of the regressor design in optim/gaussian_process.cc (itself a port
// of sklearn's GPR): posterior mean/variance at test points given noisy
// observations, kernel k(a,b) = sf2 * exp(-|a-b|^2 / (2 l^2)).

class GaussianProcess {
 public:
  GaussianProcess(double length_scale, double signal_var, double noise_var)
      : l2_(length_scale * length_scale), sf2_(signal_var), sn2_(noise_var) {}

  void Fit(const Mat& x, const Vec& y) {
    x_ = x;
    const size_t n = x.size();
    Mat k(n, Vec(n));
    for (size_t i = 0; i < n; ++i)
      for (size_t j = 0; j < n; ++j) k[i][j] = Kernel(x[i], x[j]);
    double jitter = sn2_;
    for (int attempt = 0; attempt < 8; ++attempt) {
      Mat ky = k;
      for (size_t i = 0; i < n; ++i) ky[i][i] += jitter;
      if (Cholesky(ky, &l_)) {
        alpha_ = CholSolve(l_, y);
        return;
      }
      jitter *= 10.0;
    }
    // Degenerate data: fall back to zero-mean prior.
    alpha_.assign(n, 0.0);
    l_.assign(n, Vec(n, 0.0));
    for (size_t i = 0; i < n; ++i) l_[i][i] = 1.0;
  }

  void Predict(const Vec& xs, double* mean, double* var) const {
    const size_t n = x_.size();
    if (n == 0) {
      *mean = 0.0;
      *var = sf2_;
      return;
    }
    Vec ks(n);
    for (size_t i = 0; i < n; ++i) ks[i] = Kernel(xs, x_[i]);
    double m = 0.0;
    for (size_t i = 0; i < n; ++i) m += ks[i] * alpha_[i];
    // var = k(x*,x*) - k*^T (K+sn2 I)^-1 k*  via v = L^-1 k*
    Vec v(n);
    for (size_t i = 0; i < n; ++i) {
      double sum = ks[i];
      for (size_t k = 0; k < i; ++k) sum -= l_[i][k] * v[k];
      v[i] = sum / l_[i][i];
    }
    double vv = 0.0;
    for (size_t i = 0; i < n; ++i) vv += v[i] * v[i];
    *mean = m;
    *var = std::max(1e-12, sf2_ - vv);
  }

 private:
  double Kernel(const Vec& a, const Vec& b) const {
    double d2 = 0.0;
    for (size_t i = 0; i < a.size(); ++i) {
      double d = a[i] - b[i];
      d2 += d * d;
    }
    return sf2_ * std::exp(-d2 / (2.0 * l2_));
  }

  double l2_, sf2_, sn2_;
  Mat x_;
  Mat l_;
  Vec alpha_;
};

// ---- Bayesian optimizer (expected improvement) -----------------------------
// bayesian_optimization.cc: suggest the next test point by maximizing EI
// over the GP posterior; candidates come from random sampling in the unit
// box (the reference maximizes with LBFGS restarts; random search over a
// 2-D box with hundreds of candidates is equivalent in practice).

double NormPdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

double NormCdf(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }

class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dims, unsigned seed = 17)
      : dims_(dims), gp_(0.25, 1.0, 1e-4), rng_(seed) {}

  void AddSample(const Vec& x, double y) {
    xs_.push_back(x);
    ys_raw_.push_back(y);
  }

  // Next point to test, in the unit box.
  Vec Suggest() {
    if (xs_.empty()) return RandomPoint();
    // normalize scores to zero mean / unit variance for the GP
    double mu = 0.0, sd = 0.0;
    for (double y : ys_raw_) mu += y;
    mu /= ys_raw_.size();
    for (double y : ys_raw_) sd += (y - mu) * (y - mu);
    sd = std::sqrt(sd / ys_raw_.size());
    if (sd < 1e-12) sd = 1.0;
    Vec ys;
    ys.reserve(ys_raw_.size());
    double best = -1e300;
    for (double y : ys_raw_) {
      double z = (y - mu) / sd;
      ys.push_back(z);
      best = std::max(best, z);
    }
    gp_.Fit(xs_, ys);

    Vec best_x = RandomPoint();
    double best_ei = -1.0;
    const double xi = 0.01;  // exploration jitter (reference default)
    for (int c = 0; c < 512; ++c) {
      Vec cand = RandomPoint();
      double m, v;
      gp_.Predict(cand, &m, &v);
      double s = std::sqrt(v);
      double z = (m - best - xi) / s;
      double ei = (m - best - xi) * NormCdf(z) + s * NormPdf(z);
      if (ei > best_ei) {
        best_ei = ei;
        best_x = cand;
      }
    }
    return best_x;
  }

 private:
  Vec RandomPoint() {
    std::uniform_real_distribution<double> u(0.0, 1.0);
    Vec p(dims_);
    for (int i = 0; i < dims_; ++i) p[i] = u(rng_);
    return p;
  }

  int dims_;
  GaussianProcess gp_;
  std::mt19937 rng_;
  Mat xs_;
  Vec ys_raw_;
};

// ---- parameter manager ------------------------------------------------------
// parameter_manager.cc: knobs = (fusion threshold bytes, cycle time ms),
// jointly tuned; score = bytes/us over a sample window, median-of-k per
// point. Knobs explicitly pinned by env are "fixed" and never moved
// (SetValue(..., fixed=true) pattern, parameter_manager.cc:329-336).

class ParameterManager {
 public:
  static constexpr double kMaxFusionMiB = 256.0;
  static constexpr double kMaxCycleMs = 25.0;
  static constexpr int kSamplesPerPoint = 5;  // median-of-5 (reference)
  static constexpr int kWarmups = 3;          // discarded leading windows

  ParameterManager(double fusion_mib, double cycle_ms, bool fusion_fixed,
                   bool cycle_fixed)
      : opt_(2),
        fusion_mib_(fusion_mib),
        cycle_ms_(cycle_ms),
        best_fusion_mib_(fusion_mib),
        best_cycle_ms_(cycle_ms),
        fusion_fixed_(fusion_fixed),
        cycle_fixed_(cycle_fixed) {}

  // Record one completed sample window. Returns 1 if parameters changed.
  int Update(double bytes, double microseconds) {
    if (fusion_fixed_ && cycle_fixed_) return 0;
    if (microseconds <= 0.0) return 0;
    if (warmups_remaining_ > 0) {
      --warmups_remaining_;
      return 0;
    }
    scores_.push_back(bytes / microseconds);
    if (static_cast<int>(scores_.size()) < kSamplesPerPoint) return 0;
    std::sort(scores_.begin(), scores_.end());
    double median = scores_[scores_.size() / 2];
    scores_.clear();
    if (median > best_score_) {
      best_score_ = median;
      best_fusion_mib_ = fusion_mib_;
      best_cycle_ms_ = cycle_ms_;
    }
    opt_.AddSample(CurrentPoint(), median);
    Vec next = opt_.Suggest();
    if (!fusion_fixed_) fusion_mib_ = std::max(1.0, next[0] * kMaxFusionMiB);
    if (!cycle_fixed_) cycle_ms_ = std::max(0.5, next[1] * kMaxCycleMs);
    return 1;
  }

  double fusion_bytes() const { return fusion_mib_ * 1024.0 * 1024.0; }
  double cycle_ms() const { return cycle_ms_; }
  double best_fusion_bytes() const {
    return best_fusion_mib_ * 1024.0 * 1024.0;
  }
  double best_cycle_ms() const { return best_cycle_ms_; }
  double best_score() const { return best_score_; }

 private:
  Vec CurrentPoint() const {
    return {fusion_mib_ / kMaxFusionMiB, cycle_ms_ / kMaxCycleMs};
  }

  BayesianOptimizer opt_;
  Vec scores_;
  double fusion_mib_, cycle_ms_;
  double best_fusion_mib_, best_cycle_ms_;
  double best_score_ = -1e300;
  bool fusion_fixed_, cycle_fixed_;
  int warmups_remaining_ = kWarmups;
};

}  // namespace

extern "C" {

void* htpu_param_manager_new(double fusion_mib, double cycle_ms,
                             int fusion_fixed, int cycle_fixed) {
  return new ParameterManager(fusion_mib, cycle_ms, fusion_fixed != 0,
                              cycle_fixed != 0);
}

void htpu_param_manager_free(void* h) {
  delete static_cast<ParameterManager*>(h);
}

int htpu_param_manager_update(void* h, double bytes, double microseconds) {
  return static_cast<ParameterManager*>(h)->Update(bytes, microseconds);
}

double htpu_param_manager_fusion_bytes(void* h) {
  return static_cast<ParameterManager*>(h)->fusion_bytes();
}

double htpu_param_manager_cycle_ms(void* h) {
  return static_cast<ParameterManager*>(h)->cycle_ms();
}

double htpu_param_manager_best_fusion_bytes(void* h) {
  return static_cast<ParameterManager*>(h)->best_fusion_bytes();
}

double htpu_param_manager_best_cycle_ms(void* h) {
  return static_cast<ParameterManager*>(h)->best_cycle_ms();
}

double htpu_param_manager_best_score(void* h) {
  return static_cast<ParameterManager*>(h)->best_score();
}

}  // extern "C"
