// Native timeline writer: dedicated I/O thread fed by a producer queue.
//
// Rebuild of TimelineWriter in horovod/common/timeline.{h,cc}: the hot path
// only enqueues records; one background thread owns all file I/O, so
// submitting a collective never blocks on disk (the reference uses a boost
// lock-free SPSC queue; a mutex+condvar queue is equivalent at
// cycle-frequency record rates). Records arrive as preformatted Chrome-trace
// JSON objects from the Python Timeline producer.

#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace {

class TimelineWriter {
 public:
  explicit TimelineWriter(const std::string& path) {
    file_ = std::fopen(path.c_str(), "w");
    if (file_ != nullptr) {
      std::fputs("[\n", file_);
      thread_ = std::thread(&TimelineWriter::Loop, this);
    }
  }

  ~TimelineWriter() { Close(); }

  void Write(const char* record) {
    if (file_ == nullptr) return;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      queue_.emplace_back(record);
    }
    cv_.notify_one();
  }

  void Close() {
    if (file_ == nullptr) return;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      closing_ = true;
    }
    cv_.notify_one();
    if (thread_.joinable()) thread_.join();
    std::fputs("{}]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }

  bool ok() const { return file_ != nullptr; }

 private:
  void Loop() {
    for (;;) {
      std::deque<std::string> batch;
      {
        std::unique_lock<std::mutex> lock(mutex_);
        cv_.wait(lock, [&] { return closing_ || !queue_.empty(); });
        std::swap(batch, queue_);
        if (batch.empty() && closing_) return;
      }
      for (const std::string& record : batch) {
        std::fputs(record.c_str(), file_);
        std::fputs(",\n", file_);
      }
      std::fflush(file_);
    }
  }

  std::FILE* file_ = nullptr;
  std::thread thread_;
  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  bool closing_ = false;
};

}  // namespace

extern "C" {

void* htpu_timeline_open(const char* path) {
  TimelineWriter* writer = new TimelineWriter(path);
  if (!writer->ok()) {
    delete writer;
    return nullptr;
  }
  return writer;
}

void htpu_timeline_write(void* handle, const char* record) {
  static_cast<TimelineWriter*>(handle)->Write(record);
}

void htpu_timeline_close(void* handle) {
  TimelineWriter* writer = static_cast<TimelineWriter*>(handle);
  writer->Close();
  delete writer;
}

}  // extern "C"
