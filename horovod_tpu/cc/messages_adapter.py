"""JSON → message-object adapter for the native negotiator's wire format."""

from __future__ import annotations

from ..ops.messages import DataType, Response, ResponseList, ResponseType


def parse_response_json(doc: dict) -> ResponseList:
    responses = []
    for item in doc.get("responses", []):
        responses.append(Response(
            response_type=ResponseType(item["type"]),
            tensor_names=list(item["names"]),
            error_message=item.get("error", ""),
            tensor_sizes=list(item.get("sizes", [])),
            tensor_dtype=DataType(item["dtype"]),
            payload_bytes=int(item.get("bytes", 0)),
            # the native wire predates quantized codecs; absent == none
            tensor_codec=str(item.get("codec", "none")),
        ))
    stalls = list(doc.get("stall_warnings", []))
    return ResponseList(responses=responses,
                        shutdown=bool(doc.get("shutdown", 0)),
                        stall_warnings=stalls,
                        # the native wire cannot distinguish "check ran,
                        # nothing stalled" from "no check this cycle";
                        # only a non-empty batch is authoritative
                        stall_check=bool(stalls))
