// Native controller service: the rank-0 hot path of the eager control plane
// in C++ — sockets, HMAC framing, cycle rendezvous, negotiation (via the
// shared negotiator core), host-plane payload combine, and failure
// detection. TPU-native rebuild of the coordinator role of
// horovod/common/operations.cc:2030-2380 (there: MPI_Gather/Bcast each
// cycle inside the C++ background thread; here: an authenticated TCP star,
// one service thread per rank plus a liveness monitor).
//
// Behavior contract: identical to the Python ControllerService
// (horovod_tpu/ops/controller.py) — same negotiated responses, same error
// strings, same rank-death abort semantics — so the multi-process test
// battery runs against both via HOROVOD_NATIVE_CONTROLLER. Autotune works
// on both: this service streams per-cycle (bytes, active-µs) observations
// to the Python GP tuner, which pushes retuned knobs back.
//
// Wire: HMAC-SHA256 digest + u64 big-endian length + body (the exact
// framing of runner/network.py Wire), with a little-endian binary body
// (encoded/decoded by horovod_tpu/ops/native_controller.py) instead of
// pickle — a C++ service must not execute pickled payloads, and parsing
// cost on the coordinator is what bounds cycle latency at scale.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "negotiator_core.h"
#include "sha256.h"

namespace htpu {
namespace {

// ---- binary body codec ------------------------------------------------------

struct Reader {
  const uint8_t* p;
  size_t n;
  bool ok = true;

  template <typename T>
  T Get() {
    T v{};
    if (n < sizeof(T)) { ok = false; return v; }
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    n -= sizeof(T);
    return v;
  }

  std::string GetBytes(size_t len) {
    if (n < len) { ok = false; return ""; }
    std::string s(reinterpret_cast<const char*>(p), len);
    p += len;
    n -= len;
    return s;
  }
};

struct Writer {
  std::string out;

  template <typename T>
  void Put(T v) {
    out.append(reinterpret_cast<const char*>(&v), sizeof(T));
  }

  void PutBytes(const std::string& s) { out.append(s); }
};

enum MsgKind : uint8_t { kHello = 1, kBye = 2, kCycle = 3, kPayload = 4 };

// ---- half / bfloat16 arithmetic for the payload combine ---------------------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(mant & 0x400)) { mant <<= 1; ++shift; }
      mant &= 0x3ff;
      bits = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToHalf(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000u);
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (((bits >> 23) & 0xff) == 0xff)  // inf / nan
    return static_cast<uint16_t>(sign | 0x7c00u | (mant ? 0x200u : 0));
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // overflow
  if (exp <= 0) {  // subnormal or zero, round-to-nearest-even
    if (exp < -10) return sign;
    mant |= 0x800000u;
    int shift = 14 - exp;
    uint32_t q = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t half = 1u << (shift - 1);
    if (rem > half || (rem == half && (q & 1))) ++q;
    return static_cast<uint16_t>(sign | q);
  }
  uint32_t q = mant >> 13;
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (q & 1))) {
    if (++q == 0x400u) { q = 0; ++exp; if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u); }
  }
  return static_cast<uint16_t>(sign | (exp << 10) | q);
}

inline float Bf16ToFloat(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if ((bits & 0x7f800000u) == 0x7f800000u && (bits & 0x7fffffu))
    return static_cast<uint16_t>((bits >> 16) | 0x40);  // quiet nan
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7fffu + lsb;  // round-to-nearest-even
  return static_cast<uint16_t>(bits >> 16);
}

template <typename T>
void SumTyped(std::string* acc, const std::string& add) {
  T* a = reinterpret_cast<T*>(&(*acc)[0]);
  const T* b = reinterpret_cast<const T*>(add.data());
  size_t count = acc->size() / sizeof(T);
  for (size_t i = 0; i < count; ++i) a[i] += b[i];
}

void SumInto(std::string* acc, const std::string& add, int dtype) {
  switch (dtype) {
    case 0: SumTyped<uint8_t>(acc, add); break;
    case 1: SumTyped<int8_t>(acc, add); break;
    case 2: SumTyped<uint16_t>(acc, add); break;
    case 3: SumTyped<int16_t>(acc, add); break;
    case 4: SumTyped<int32_t>(acc, add); break;
    case 5: SumTyped<int64_t>(acc, add); break;
    case 6: {  // float16: numpy computes in f32 and rounds back per element
      uint16_t* a = reinterpret_cast<uint16_t*>(&(*acc)[0]);
      const uint16_t* b = reinterpret_cast<const uint16_t*>(add.data());
      for (size_t i = 0; i < acc->size() / 2; ++i)
        a[i] = FloatToHalf(HalfToFloat(a[i]) + HalfToFloat(b[i]));
      break;
    }
    case 7: SumTyped<float>(acc, add); break;
    case 8: SumTyped<double>(acc, add); break;
    case 9: {  // bool: + is logical or in numpy
      uint8_t* a = reinterpret_cast<uint8_t*>(&(*acc)[0]);
      const uint8_t* b = reinterpret_cast<const uint8_t*>(add.data());
      for (size_t i = 0; i < acc->size(); ++i) a[i] = (a[i] || b[i]) ? 1 : 0;
      break;
    }
    case 10: {  // bfloat16
      uint16_t* a = reinterpret_cast<uint16_t*>(&(*acc)[0]);
      const uint16_t* b = reinterpret_cast<const uint16_t*>(add.data());
      for (size_t i = 0; i < acc->size() / 2; ++i)
        a[i] = FloatToBf16(Bf16ToFloat(a[i]) + Bf16ToFloat(b[i]));
      break;
    }
  }
}

// ---- service ---------------------------------------------------------------

struct CycleSlot {
  std::map<int, std::pair<std::vector<Request>, bool>> lists;  // rank ->
  bool done = false;
  std::string framed;  // one frame serves every rank
  // active-window start: first rank's arrival (straggler wait + negotiate
  // count toward the autotune score; inter-cycle client idle does not)
  std::chrono::steady_clock::time_point t0 =
      std::chrono::steady_clock::now();
};

struct PayloadSlot {
  std::map<int, std::string> data;
  bool done = false;
  std::string framed;
};

class ControllerServer {
 public:
  ControllerServer(int size, std::string secret, int64_t fusion_threshold,
                   double stall_warning_s, bool stall_check_disable,
                   std::string shutdown_error, bool collect_stats)
      : size_(size),
        secret_(std::move(secret)),
        shutdown_error_(std::move(shutdown_error)),
        collect_stats_(collect_stats),
        negotiator_(size, fusion_threshold, stall_warning_s,
                    stall_check_disable) {}

  bool Start(const char* bind_host, int port, std::string* err) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) { *err = "socket() failed"; return false; }
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
      *err = "bad bind host";
      return false;
    }
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      *err = "bind() failed";
      return false;
    }
    // Every rank connects at t0 (see the Python service's backlog note).
    if (::listen(listen_fd_, 512) != 0) { *err = "listen() failed"; return false; }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    monitor_thread_ = std::thread([this] { MonitorLoop(); });
    return true;
  }

  int port() const { return port_; }

  bool world_shutdown() {
    std::lock_guard<std::mutex> guard(mutex_);
    return world_shutdown_ || !abort_reason_.empty();
  }

  int DrainStats(double* bytes_out, double* us_out, int cap) {
    std::lock_guard<std::mutex> guard(mutex_);
    int n = 0;
    for (; n < cap && n < static_cast<int>(stats_.size()); ++n) {
      bytes_out[n] = stats_[static_cast<size_t>(n)].first;
      us_out[n] = stats_[static_cast<size_t>(n)].second;
    }
    stats_.erase(stats_.begin(), stats_.begin() + n);
    return n;
  }

  void SetTuning(int64_t fusion_bytes, double cycle_ms) {
    negotiator_.SetFusionThreshold(fusion_bytes);
    std::lock_guard<std::mutex> guard(mutex_);
    tuned_cycle_ms_ = cycle_ms;
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (stopping_) return;
      stopping_ = true;
    }
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> guard(mutex_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (monitor_thread_.joinable()) monitor_thread_.join();
    for (auto& t : conn_threads_) t.join();
  }

  ~ControllerServer() { Stop(); }

 private:
  // -- framing ---------------------------------------------------------------

  bool ReadExact(int fd, uint8_t* buf, size_t n) {
    while (n > 0) {
      ssize_t got = ::recv(fd, buf, n, 0);
      if (got <= 0) return false;
      buf += got;
      n -= static_cast<size_t>(got);
    }
    return true;
  }

  bool ReadFrame(int fd, std::string* body) {
    uint8_t header[40];
    if (!ReadExact(fd, header, sizeof(header))) return false;
    uint64_t len = 0;
    for (int i = 0; i < 8; ++i) len = (len << 8) | header[32 + i];
    // The length field arrives before the body it is HMAC'd with, so it is
    // attacker-controlled on a non-loopback bind: bound it well below
    // anything that could throw bad_alloc (fused buffers are ~64 MB).
    if (len > (1ull << 31)) return false;
    try {
      body->resize(len);
    } catch (const std::bad_alloc&) {
      return false;  // drop the connection, never the coordinator
    }
    if (len && !ReadExact(fd, reinterpret_cast<uint8_t*>(&(*body)[0]), len))
      return false;
    uint8_t digest[32];
    HmacSha256(secret_, reinterpret_cast<const uint8_t*>(body->data()),
               body->size(), digest);
    return ConstTimeEqual(digest, header, 32);
  }

  std::string FrameBody(const std::string& body) {
    std::string frame;
    frame.resize(40 + body.size());
    HmacSha256(secret_, reinterpret_cast<const uint8_t*>(body.data()),
               body.size(), reinterpret_cast<uint8_t*>(&frame[0]));
    uint64_t len = body.size();
    for (int i = 0; i < 8; ++i)
      frame[32 + i] = static_cast<char>(len >> (56 - 8 * i));
    std::memcpy(&frame[40], body.data(), body.size());
    return frame;
  }

  bool WriteAll(int fd, const std::string& data) {
    size_t off = 0;
    while (off < data.size()) {
      ssize_t sent = ::send(fd, data.data() + off, data.size() - off,
                            MSG_NOSIGNAL);
      if (sent <= 0) return false;
      off += static_cast<size_t>(sent);
    }
    return true;
  }

  // -- connection handling ---------------------------------------------------

  void AcceptLoop() {
    while (true) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;  // listener closed by Stop()
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> guard(mutex_);
      if (stopping_) { ::close(fd); return; }
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { ConnLoop(fd); });
    }
  }

  void ConnLoop(int fd) {
    std::string body;
    while (ReadFrame(fd, &body)) {
      std::string resp;
      try {
        resp = Dispatch(fd, body);
      } catch (const std::exception& e) {
        // Behavior contract with the Python service: a handler failure is
        // a per-request remote error, never a coordinator crash.
        resp = ErrorResp(std::string("native controller error: ") + e.what());
      }
      if (!WriteAll(fd, resp)) break;
    }
    OnDisconnect(fd);
    ::close(fd);
  }

  // Out-of-band EOF detection: a connection thread parked in a rendezvous
  // is not reading its socket, so a peer dying mid-rendezvous would go
  // unnoticed (the Python service has the same monitor for the same hole).
  void MonitorLoop() {
    while (true) {
      {
        std::unique_lock<std::mutex> lock(mutex_);
        if (cv_.wait_for(lock, std::chrono::milliseconds(200),
                         [this] { return stopping_; }))
          return;
      }
      std::vector<int> fds;
      {
        std::lock_guard<std::mutex> guard(mutex_);
        fds = conn_fds_;
      }
      for (int fd : fds) {
        char c;
        ssize_t got = ::recv(fd, &c, 1, MSG_PEEK | MSG_DONTWAIT);
        if (got == 0) OnDisconnect(fd);  // orderly EOF
        // got<0 with EAGAIN: alive; other errors surface in the conn thread
      }
    }
  }

  void OnDisconnect(int fd) {
    std::string reason;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      // Always stop monitoring the fd (anonymous probe connections close
      // without ever identifying a rank; their number may be reused).
      for (auto fit = conn_fds_.begin(); fit != conn_fds_.end(); ++fit)
        if (*fit == fd) { conn_fds_.erase(fit); break; }
      auto it = conn_ranks_.find(fd);
      if (it == conn_ranks_.end()) return;
      int rank = it->second;
      conn_ranks_.erase(it);
      if (world_shutdown_ || stopping_) return;
      if (abort_reason_.empty())
        abort_reason_ = "rank " + std::to_string(rank) + " exited mid-job. " +
                        shutdown_error_;
      reason = abort_reason_;
    }
    std::fprintf(stderr,
                 "[horovod_tpu native controller] %s — aborting in-flight "
                 "collectives on all ranks\n",
                 reason.c_str());
    cv_.notify_all();
  }

  // -- dispatch --------------------------------------------------------------

  std::string ErrorResp(const std::string& msg) {
    Writer w;
    w.Put<uint8_t>(1);
    w.Put<uint32_t>(static_cast<uint32_t>(msg.size()));
    w.PutBytes(msg);
    return FrameBody(w.out);
  }

  std::string Dispatch(int fd, const std::string& body) {
    Reader r{reinterpret_cast<const uint8_t*>(body.data()), body.size()};
    uint8_t kind = r.Get<uint8_t>();
    if (!r.ok) return ErrorResp("malformed request");
    if (kind == 0x80) {
      // A pickle protocol marker: this rank fell back to the Python
      // controller client (native core unavailable there?) while the
      // coordinator runs the native service. It cannot parse our error
      // frame either — log the diagnosis where the operator will look.
      std::fprintf(stderr,
                   "[horovod_tpu native controller] received a PICKLE "
                   "request: a rank is running the Python controller "
                   "client against the native service. "
                   "HOROVOD_NATIVE_CONTROLLER must resolve identically on "
                   "every rank (is the native core built on every host?). "
                   "Set HOROVOD_NATIVE_CONTROLLER=0 to force the Python "
                   "service everywhere.\n");
      return ErrorResp("protocol mismatch: coordinator speaks the native "
                       "binary protocol");
    }
    switch (kind) {
      case kHello: {
        int32_t rank = r.Get<int32_t>();
        std::lock_guard<std::mutex> guard(mutex_);
        conn_ranks_[fd] = rank;
        Writer w;
        w.Put<uint8_t>(0);
        return FrameBody(w.out);
      }
      case kBye: {
        std::lock_guard<std::mutex> guard(mutex_);
        conn_ranks_.erase(fd);
        Writer w;
        w.Put<uint8_t>(0);
        return FrameBody(w.out);
      }
      case kCycle:
        return HandleCycle(fd, &r);
      case kPayload:
        return HandlePayload(fd, &r);
      default:
        return ErrorResp("unknown request kind");
    }
  }

  std::string HandleCycle(int fd, Reader* r) {
    int32_t rank = r->Get<int32_t>();
    uint8_t shutdown = r->Get<uint8_t>();
    uint32_t nreq = r->Get<uint32_t>();
    std::vector<Request> reqs;
    reqs.reserve(nreq);
    for (uint32_t i = 0; i < nreq && r->ok; ++i) {
      Request req;
      req.rank = rank;
      uint8_t op = r->Get<uint8_t>();
      uint8_t dtype = r->Get<uint8_t>();
      // Range-check wire enums before they index kDtypeBytes/kOpNames —
      // the Python twin gets this for free from DataType()/RequestType().
      if (op > 2 || dtype > 10)
        return ErrorResp("malformed cycle request (bad op or dtype)");
      req.op = static_cast<Op>(op);
      req.dtype = dtype;
      req.root_rank = r->Get<int32_t>();
      uint8_t ndim = r->Get<uint8_t>();
      for (uint8_t d = 0; d < ndim; ++d)
        req.shape.push_back(r->Get<int64_t>());
      uint16_t name_len = r->Get<uint16_t>();
      req.name = r->GetBytes(name_len);
      reqs.push_back(std::move(req));
    }
    if (!r->ok) return ErrorResp("malformed cycle request");

    std::unique_lock<std::mutex> lock(mutex_);
    conn_ranks_[fd] = rank;
    if (!abort_reason_.empty()) return ErrorResp(abort_reason_);
    int64_t key = rank_cycles_[rank]++;
    CycleSlot& slot = cycles_[key];
    slot.lists[rank] = {std::move(reqs), shutdown != 0};
    if (static_cast<int>(slot.lists.size()) == size_) {
      // rank order, matching the Python service's deterministic feed
      bool any_shutdown = false;
      for (auto& kv : slot.lists) {
        for (Request& req : kv.second.first)
          negotiator_.AddRequest(std::move(req), false);
        any_shutdown |= kv.second.second;
      }
      if (any_shutdown) negotiator_.SetShutdown();
      std::vector<std::string> stalls;
      bool world_shutdown = false;
      std::vector<Response> responses =
          negotiator_.ConstructList(&stalls, &world_shutdown);
      if (world_shutdown) world_shutdown_ = true;
      history_[cycle_no_] = responses;
      history_.erase(cycle_no_ - 16);
      ++cycle_no_;
      // Autotune observation: (payload bytes, active µs) per cycle,
      // drained by the Python tuner thread (parameter_manager.cc scoring).
      int64_t bytes = 0;
      if (collect_stats_)
        for (const Response& resp : responses)
          if (resp.type != RespType::ERROR) bytes += resp.payload_bytes;
      if (bytes > 0 && stats_.size() < 4096) {
        double us = std::chrono::duration<double, std::micro>(
                        std::chrono::steady_clock::now() - slot.t0)
                        .count();
        stats_.emplace_back(static_cast<double>(bytes), us);
      }
      slot.framed = FrameBody(EncodeCycleResponse(
          responses, stalls, world_shutdown));
      slot.done = true;
      cv_.notify_all();
    } else {
      cv_.wait(lock, [&] {
        return slot.done || !abort_reason_.empty() || stopping_;
      });
      if (!slot.done)
        return ErrorResp(abort_reason_.empty() ? "controller stopping"
                                               : abort_reason_);
    }
    std::string framed = slot.framed;
    if (++delivered_[key] == size_) {
      cycles_.erase(key);
      delivered_.erase(key);
    }
    return framed;
  }

  std::string EncodeCycleResponse(const std::vector<Response>& responses,
                                  const std::vector<std::string>& stalls,
                                  bool shutdown) {
    Writer w;
    w.Put<uint8_t>(0);
    w.Put<uint8_t>(shutdown ? 1 : 0);
    // Tuned cycle time piggybacks to every rank, the role of the
    // reference's Params broadcast (parameter_manager.cc:213 SyncParams).
    w.Put<uint8_t>(tuned_cycle_ms_ > 0 ? 1 : 0);
    w.Put<double>(tuned_cycle_ms_);
    w.Put<uint32_t>(static_cast<uint32_t>(responses.size()));
    for (const Response& resp : responses) {
      w.Put<uint8_t>(static_cast<uint8_t>(resp.type));
      w.Put<uint8_t>(static_cast<uint8_t>(resp.dtype));
      w.Put<uint64_t>(static_cast<uint64_t>(resp.payload_bytes));
      w.Put<uint16_t>(static_cast<uint16_t>(resp.names.size()));
      for (const std::string& name : resp.names) {
        w.Put<uint16_t>(static_cast<uint16_t>(name.size()));
        w.PutBytes(name);
      }
      w.Put<uint32_t>(static_cast<uint32_t>(resp.error.size()));
      w.PutBytes(resp.error);
      w.Put<uint32_t>(static_cast<uint32_t>(resp.sizes.size()));
      for (int64_t s : resp.sizes) w.Put<int64_t>(s);
    }
    w.Put<uint32_t>(static_cast<uint32_t>(stalls.size()));
    for (const std::string& s : stalls) {
      w.Put<uint32_t>(static_cast<uint32_t>(s.size()));
      w.PutBytes(s);
    }
    return w.out;
  }

  std::string HandlePayload(int fd, Reader* r) {
    int32_t rank = r->Get<int32_t>();
    uint64_t cycle_no = r->Get<uint64_t>();
    uint32_t idx = r->Get<uint32_t>();
    uint64_t data_len = r->Get<uint64_t>();
    if (!r->ok || r->n < data_len) return ErrorResp("malformed payload");
    std::string data = r->GetBytes(data_len);

    std::unique_lock<std::mutex> lock(mutex_);
    conn_ranks_[fd] = rank;
    if (!abort_reason_.empty()) return ErrorResp(abort_reason_);
    auto hist_it = history_.find(static_cast<int64_t>(cycle_no));
    if (hist_it == history_.end() ||
        idx >= hist_it->second.size())
      return ErrorResp("payload references an unknown cycle/response");
    const Response resp = hist_it->second[idx];  // copy: history may be
                                                 // pruned once unlocked
    if (resp.type == RespType::ERROR)
      return ErrorResp("payload submitted for an error response: " +
                       resp.error);
    auto key = std::make_pair(static_cast<int64_t>(cycle_no),
                              static_cast<int64_t>(idx));
    PayloadSlot& slot = payloads_[key];
    slot.data[rank] = std::move(data);
    if (static_cast<int>(slot.data.size()) == size_) {
      // Combine + frame outside the service mutex: summing a fused
      // multi-MB buffer across N ranks (plus the HMAC over the result)
      // must not block every other connection's cycle handling.
      std::map<int, std::string> gathered = std::move(slot.data);
      lock.unlock();
      std::string framed;
      std::string error;
      try {
        std::string combined = Combine(resp, gathered);
        Writer w;
        w.Put<uint8_t>(0);
        w.Put<uint64_t>(combined.size());
        w.PutBytes(combined);
        framed = FrameBody(w.out);
      } catch (const std::exception& e) {
        error = e.what();
      }
      lock.lock();
      if (!error.empty()) {
        // Poison the slot for every waiting rank, like the Python
        // rendezvous does on a compute failure.
        Writer w;
        w.Put<uint8_t>(1);
        w.Put<uint32_t>(static_cast<uint32_t>(error.size()));
        w.PutBytes(error);
        framed = FrameBody(w.out);
      }
      slot.framed = std::move(framed);
      slot.done = true;
      cv_.notify_all();
    } else {
      cv_.wait(lock, [&] {
        return slot.done || !abort_reason_.empty() || stopping_;
      });
      if (!slot.done)
        return ErrorResp(abort_reason_.empty() ? "controller stopping"
                                               : abort_reason_);
    }
    std::string framed = slot.framed;
    if (++payload_delivered_[key] == size_) {
      payloads_.erase(key);
      payload_delivered_.erase(key);
    }
    return framed;
  }

  std::string Combine(const Response& resp,
                      const std::map<int, std::string>& data) {
    if (resp.type == RespType::ALLREDUCE) {
      std::string acc = data.begin()->second;
      for (auto it = std::next(data.begin()); it != data.end(); ++it) {
        // The Python twin's numpy add raises on ragged buffers; an
        // unchecked sum here would read past the shorter one.
        if (it->second.size() != acc.size())
          throw std::runtime_error(
              "allreduce payload size mismatch across ranks (" +
              std::to_string(acc.size()) + " vs " +
              std::to_string(it->second.size()) + " bytes)");
        SumInto(&acc, it->second, resp.dtype);
      }
      return acc;
    }
    if (resp.type == RespType::ALLGATHER) {
      std::string out;
      for (const auto& kv : data) out += kv.second;
      return out;
    }
    // BROADCAST: sizes[0] is the root rank
    if (resp.sizes.empty())
      throw std::runtime_error("broadcast response carries no root rank");
    auto it = data.find(static_cast<int>(resp.sizes[0]));
    if (it == data.end())
      throw std::runtime_error("broadcast root sent no payload");
    return it->second;
  }

  const int size_;
  const std::string secret_;
  const std::string shutdown_error_;
  const bool collect_stats_;
  Negotiator negotiator_;

  int listen_fd_ = -1;
  int port_ = 0;
  std::thread accept_thread_;
  std::thread monitor_thread_;
  std::vector<std::thread> conn_threads_;

  std::mutex mutex_;
  std::condition_variable cv_;
  bool stopping_ = false;
  bool world_shutdown_ = false;
  std::string abort_reason_;
  std::vector<int> conn_fds_;
  std::unordered_map<int, int> conn_ranks_;  // fd -> rank
  std::unordered_map<int, int64_t> rank_cycles_;
  std::map<int64_t, CycleSlot> cycles_;
  std::map<int64_t, int> delivered_;
  int64_t cycle_no_ = 0;
  double tuned_cycle_ms_ = 0;  // 0 = untuned; guarded by mutex_
  std::vector<std::pair<double, double>> stats_;  // (bytes, active_us)
  std::map<int64_t, std::vector<Response>> history_;
  std::map<std::pair<int64_t, int64_t>, PayloadSlot> payloads_;
  std::map<std::pair<int64_t, int64_t>, int> payload_delivered_;
};

}  // namespace
}  // namespace htpu

extern "C" {

void* htpu_controller_start(int size, const char* bind_host, int port,
                            const uint8_t* secret, int secret_len,
                            long long fusion_threshold,
                            double stall_warning_s, int stall_check_disable,
                            const char* shutdown_error, int collect_stats,
                            char* err_out, int err_cap) {
  auto* server = new htpu::ControllerServer(
      size, std::string(reinterpret_cast<const char*>(secret),
                        static_cast<size_t>(secret_len)),
      fusion_threshold, stall_warning_s, stall_check_disable != 0,
      shutdown_error, collect_stats != 0);
  std::string err;
  if (!server->Start(bind_host, port, &err)) {
    std::snprintf(err_out, static_cast<size_t>(err_cap), "%s", err.c_str());
    delete server;
    return nullptr;
  }
  return server;
}

int htpu_controller_port(void* handle) {
  return static_cast<htpu::ControllerServer*>(handle)->port();
}

int htpu_controller_world_shutdown(void* handle) {
  return static_cast<htpu::ControllerServer*>(handle)->world_shutdown() ? 1
                                                                        : 0;
}

int htpu_controller_drain_stats(void* handle, double* bytes_out,
                                double* us_out, int cap) {
  return static_cast<htpu::ControllerServer*>(handle)->DrainStats(
      bytes_out, us_out, cap);
}

void htpu_controller_set_tuning(void* handle, long long fusion_bytes,
                                double cycle_ms) {
  static_cast<htpu::ControllerServer*>(handle)->SetTuning(fusion_bytes,
                                                          cycle_ms);
}

void htpu_controller_stop(void* handle) {
  auto* server = static_cast<htpu::ControllerServer*>(handle);
  server->Stop();
  delete server;
}

}  // extern "C"
