// Native controller service: the rank-0 hot path of the eager control plane
// in C++ — sockets, HMAC framing, cycle rendezvous, negotiation (via the
// shared negotiator core), host-plane payload combine, and failure
// detection. TPU-native rebuild of the coordinator role of
// horovod/common/operations.cc:2030-2380 (there: MPI_Gather/Bcast each
// cycle inside the C++ background thread; here: an authenticated TCP star
// serviced by ONE epoll event loop).
//
// Scaling design: a single event-loop thread owns every connection. A rank
// whose rendezvous is incomplete is *parked* — its fd simply has no queued
// response yet — instead of blocking an OS thread, so coordinator memory
// and scheduler load are O(1) in world size where the previous
// thread-per-connection design (and a 512-rank MPI coordinator) are O(N).
// Completing a cycle queues the one shared framed response onto every
// parked fd. EOF on a parked fd is seen directly by epoll, which replaces
// the out-of-band liveness monitor thread. The payload combine runs inline
// on the loop (the reference combines on its single background thread the
// same way); cycle negotiation, the latency-critical path at scale, never
// waits behind a peer's combine in practice because host-plane payloads
// and control cycles are phase-separated per world.
//
// Behavior contract: identical to the Python ControllerService
// (horovod_tpu/ops/controller.py) — same negotiated responses, same error
// strings, same rank-death abort semantics — so the multi-process test
// battery runs against both via HOROVOD_NATIVE_CONTROLLER. Autotune works
// on both: this service streams per-cycle (bytes, active-µs) observations
// to the Python GP tuner, which pushes retuned knobs back.
//
// Wire: HMAC-SHA256 digest + u64 big-endian length + body (the exact
// framing of runner/network.py Wire), with a little-endian binary body
// (encoded/decoded by horovod_tpu/ops/native_controller.py) instead of
// pickle — a C++ service must not execute pickled payloads, and parsing
// cost on the coordinator is what bounds cycle latency at scale.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "negotiator_core.h"
#include "sha256.h"

namespace htpu {
namespace {

// Refusal for a hello/watch whose world identity differs from this
// service's (co-scheduled worlds share the port under subset schedules).
// Exact-text contract with controller.world_mismatch_error().
std::string WorldMismatchError(const std::string& service_id,
                               const std::string& caller_id) {
  return "controller serves a different world (service=" + service_id +
         ", caller=" + caller_id + "); retry against this port's "
         "successor service";
}

// Retryable refusal for next-world clients reaching a dying service on a
// re-used port. EXACT text contract with core/status.py
// CONTROLLER_RESTARTING and both controller clients' retry checks
// (tests/test_native_controller.py pins the equivalence).
constexpr const char* kControllerRestarting =
    "controller world has shut down; a next-world client should retry "
    "its connect against the successor service";

// ---- binary body codec ------------------------------------------------------

struct Reader {
  const uint8_t* p;
  size_t n;
  bool ok = true;

  template <typename T>
  T Get() {
    T v{};
    if (n < sizeof(T)) { ok = false; return v; }
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    n -= sizeof(T);
    return v;
  }

  std::string GetBytes(size_t len) {
    if (n < len) { ok = false; return ""; }
    std::string s(reinterpret_cast<const char*>(p), len);
    p += len;
    n -= len;
    return s;
  }
};

struct Writer {
  std::string out;

  template <typename T>
  void Put(T v) {
    out.append(reinterpret_cast<const char*>(&v), sizeof(T));
  }

  void PutBytes(const std::string& s) { out.append(s); }
};

enum MsgKind : uint8_t {
  kHello = 1, kBye = 2, kCycle = 3, kPayload = 4,
  // Abort push channel: the response is deferred until the world aborts
  // (rank death) or the service stops — the signal for ranks blocked
  // inside a compiled device collective, which no poisoned rendezvous
  // response can reach. Watch connections stay anonymous (rank -1), so
  // their own teardown is never mistaken for a rank death.
  kWatch = 5,
};

// ---- half / bfloat16 arithmetic for the payload combine ---------------------

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ff;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(mant & 0x400)) { mant <<= 1; ++shift; }
      mant &= 0x3ff;
      bits = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7f800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToHalf(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000u);
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (((bits >> 23) & 0xff) == 0xff)  // inf / nan
    return static_cast<uint16_t>(sign | 0x7c00u | (mant ? 0x200u : 0));
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // overflow
  if (exp <= 0) {  // subnormal or zero, round-to-nearest-even
    if (exp < -10) return sign;
    mant |= 0x800000u;
    int shift = 14 - exp;
    uint32_t q = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t half = 1u << (shift - 1);
    if (rem > half || (rem == half && (q & 1))) ++q;
    return static_cast<uint16_t>(sign | q);
  }
  uint32_t q = mant >> 13;
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (q & 1))) {
    if (++q == 0x400u) { q = 0; ++exp; if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u); }
  }
  return static_cast<uint16_t>(sign | (exp << 10) | q);
}

inline float Bf16ToFloat(uint16_t b) {
  uint32_t bits = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if ((bits & 0x7f800000u) == 0x7f800000u && (bits & 0x7fffffu))
    return static_cast<uint16_t>((bits >> 16) | 0x40);  // quiet nan
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7fffu + lsb;  // round-to-nearest-even
  return static_cast<uint16_t>(bits >> 16);
}

template <typename T>
void SumTyped(std::string* acc, const std::string& add) {
  T* a = reinterpret_cast<T*>(&(*acc)[0]);
  const T* b = reinterpret_cast<const T*>(add.data());
  size_t count = acc->size() / sizeof(T);
  for (size_t i = 0; i < count; ++i) a[i] += b[i];
}

void SumInto(std::string* acc, const std::string& add, int dtype) {
  switch (dtype) {
    case 0: SumTyped<uint8_t>(acc, add); break;
    case 1: SumTyped<int8_t>(acc, add); break;
    case 2: SumTyped<uint16_t>(acc, add); break;
    case 3: SumTyped<int16_t>(acc, add); break;
    case 4: SumTyped<int32_t>(acc, add); break;
    case 5: SumTyped<int64_t>(acc, add); break;
    case 6: {  // float16: numpy computes in f32 and rounds back per element
      uint16_t* a = reinterpret_cast<uint16_t*>(&(*acc)[0]);
      const uint16_t* b = reinterpret_cast<const uint16_t*>(add.data());
      for (size_t i = 0; i < acc->size() / 2; ++i)
        a[i] = FloatToHalf(HalfToFloat(a[i]) + HalfToFloat(b[i]));
      break;
    }
    case 7: SumTyped<float>(acc, add); break;
    case 8: SumTyped<double>(acc, add); break;
    case 9: {  // bool: + is logical or in numpy
      uint8_t* a = reinterpret_cast<uint8_t*>(&(*acc)[0]);
      const uint8_t* b = reinterpret_cast<const uint8_t*>(add.data());
      for (size_t i = 0; i < acc->size(); ++i) a[i] = (a[i] || b[i]) ? 1 : 0;
      break;
    }
    case 10: {  // bfloat16
      uint16_t* a = reinterpret_cast<uint16_t*>(&(*acc)[0]);
      const uint16_t* b = reinterpret_cast<const uint16_t*>(add.data());
      for (size_t i = 0; i < acc->size() / 2; ++i)
        a[i] = FloatToBf16(Bf16ToFloat(a[i]) + Bf16ToFloat(b[i]));
      break;
    }
  }
}

// ---- service ---------------------------------------------------------------

struct CycleSlot {
  std::map<int, std::pair<std::vector<Request>, bool>> lists;  // rank ->
  // fds parked on this rendezvous — no thread blocks; the completing
  // request queues the one shared framed response onto each of these
  std::vector<int> waiters;
  // active-window start: first rank's arrival (straggler wait + negotiate
  // count toward the autotune score; inter-cycle client idle does not)
  std::chrono::steady_clock::time_point t0 =
      std::chrono::steady_clock::now();
};

struct PayloadSlot {
  std::map<int, std::string> data;
  std::vector<int> waiters;
};

class ControllerServer {
 public:
  ControllerServer(int size, std::string secret, int64_t fusion_threshold,
                   double stall_warning_s, bool stall_check_disable,
                   std::string shutdown_error, bool collect_stats,
                   std::string world_id)
      : size_(size),
        secret_(std::move(secret)),
        shutdown_error_(std::move(shutdown_error)),
        collect_stats_(collect_stats),
        negotiator_(size, fusion_threshold, stall_warning_s,
                    stall_check_disable),
        world_id_(std::move(world_id)) {}

  bool Start(const char* bind_host, int port, std::string* err) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) { *err = "socket() failed"; return false; }
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
      *err = "bad bind host";
      return false;
    }
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      *err = "bind() failed";
      return false;
    }
    // Every rank connects at t0 (see the Python service's backlog note).
    if (::listen(listen_fd_, 1024) != 0) { *err = "listen() failed"; return false; }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    ::fcntl(listen_fd_, F_SETFL, O_NONBLOCK);
    epoll_fd_ = ::epoll_create1(0);
    wake_fd_ = ::eventfd(0, EFD_NONBLOCK);
    if (epoll_fd_ < 0 || wake_fd_ < 0) { *err = "epoll/eventfd failed"; return false; }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = Tag(listen_fd_, 0);
    ::epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.data.u64 = Tag(wake_fd_, 0);
    ::epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    loop_thread_ = std::thread([this] { EventLoop(); });
    return true;
  }

  int port() const { return port_; }

  bool world_shutdown() {
    std::lock_guard<std::mutex> guard(mutex_);
    return world_shutdown_ || !abort_reason_.empty();
  }

  int DrainStats(double* bytes_out, double* us_out, int cap) {
    std::lock_guard<std::mutex> guard(mutex_);
    int n = 0;
    for (; n < cap && n < static_cast<int>(stats_.size()); ++n) {
      bytes_out[n] = stats_[static_cast<size_t>(n)].first;
      us_out[n] = stats_[static_cast<size_t>(n)].second;
    }
    stats_.erase(stats_.begin(), stats_.begin() + n);
    return n;
  }

  void SetTuning(int64_t fusion_bytes, double cycle_ms) {
    negotiator_.SetFusionThreshold(fusion_bytes);
    std::lock_guard<std::mutex> guard(mutex_);
    tuned_cycle_ms_ = cycle_ms;
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (stopping_) return;
      stopping_ = true;
    }
    uint64_t one = 1;
    (void)!::write(wake_fd_, &one, sizeof(one));
    if (loop_thread_.joinable()) loop_thread_.join();
  }

  ~ControllerServer() { Stop(); }

 private:
  // -- framing ---------------------------------------------------------------

  std::string FrameBody(const std::string& body) {
    std::string frame;
    frame.resize(40 + body.size());
    HmacSha256(secret_, reinterpret_cast<const uint8_t*>(body.data()),
               body.size(), reinterpret_cast<uint8_t*>(&frame[0]));
    uint64_t len = body.size();
    for (int i = 0; i < 8; ++i)
      frame[32 + i] = static_cast<char>(len >> (56 - 8 * i));
    std::memcpy(&frame[40], body.data(), body.size());
    return frame;
  }

  // -- event loop ------------------------------------------------------------
  // Everything below runs on the single loop thread; conns_ / cycles_ /
  // payloads_ / history_ / rank_cycles_ are loop-thread-owned and need no
  // lock. mutex_ guards only the state shared with external API threads
  // (stopping_, world_shutdown_, abort_reason_, stats_, tuned_cycle_ms_).

  struct Conn {
    std::string rbuf;   // inbound bytes, possibly a partial frame
    std::string wbuf;   // outbound framed responses not yet written
    size_t woff = 0;
    int rank = -1;      // set by hello/cycle/payload; -1 = anonymous probe
    bool out_armed = false;
    uint32_t gen = 0;   // guards against stale events after fd reuse
  };

  // epoll event payload: (generation << 32) | fd. A CloseConn + accept
  // inside one epoll_wait batch can reuse the fd number; a stale event
  // captured before the close must not act on the NEW connection (worst
  // case: its EPOLLHUP would drop a fresh rank at init). The generation
  // check makes stale entries inert.
  static uint64_t Tag(int fd, uint32_t gen) {
    return (static_cast<uint64_t>(gen) << 32) |
           static_cast<uint32_t>(fd);
  }

  void EventLoop() {
    std::vector<epoll_event> events(256);
    for (;;) {
      int n = ::epoll_wait(epoll_fd_, events.data(),
                           static_cast<int>(events.size()), -1);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n; ++i) {
        int fd = static_cast<int>(events[i].data.u64 & 0xffffffffu);
        uint32_t gen = static_cast<uint32_t>(events[i].data.u64 >> 32);
        uint32_t ev = events[i].events;
        if (fd == wake_fd_) {
          uint64_t v;
          (void)!::read(wake_fd_, &v, sizeof(v));
          continue;
        }
        if (fd == listen_fd_) {
          AcceptAll();
          continue;
        }
        auto it = conns_.find(fd);
        if (it == conns_.end() || it->second.gen != gen)
          continue;  // closed earlier this batch (or the fd was reused)
        if (ev & (EPOLLHUP | EPOLLERR)) {
          CloseConn(fd);
          continue;
        }
        if (ev & EPOLLIN) {
          if (!ReadAvailable(fd)) continue;  // conn closed
        }
        if (ev & EPOLLOUT) {
          auto it2 = conns_.find(fd);
          if (it2 != conns_.end()) FlushWrites(fd, &it2->second);
        }
      }
      bool stop;
      {
        std::lock_guard<std::mutex> guard(mutex_);
        stop = stopping_;
      }
      if (stop) break;
    }
    // Contract parity with the blocking design: ranks parked in a
    // rendezvous get an explicit "controller stopping" error (or the
    // abort reason) before their sockets close, not a bare EOF.
    std::string reason;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      reason = abort_reason_.empty() ? "controller stopping" : abort_reason_;
    }
    const std::string resp = ErrorResp(reason);
    for (int fd : DrainWaiters()) QueueWrite(fd, resp);
    for (int fd : DrainWatchers()) QueueWrite(fd, resp);
    for (auto& kv : conns_) ::close(kv.first);
    conns_.clear();
    ::close(listen_fd_);
    ::close(epoll_fd_);
    ::close(wake_fd_);
  }

  // Collect every parked fd and clear the slots FIRST: QueueWrite can fail
  // into CloseConn, which walks the waiter lists and can re-enter
  // AbortWorld — the maps must already be empty by then.
  std::vector<int> DrainWaiters() {
    std::vector<int> waiters;
    for (auto& kv : cycles_)
      waiters.insert(waiters.end(), kv.second.waiters.begin(),
                     kv.second.waiters.end());
    for (auto& kv : payloads_)
      waiters.insert(waiters.end(), kv.second.waiters.begin(),
                     kv.second.waiters.end());
    cycles_.clear();
    payloads_.clear();
    return waiters;
  }

  std::vector<int> DrainWatchers() {
    std::vector<int> watchers = std::move(watch_fds_);
    watch_fds_.clear();
    return watchers;
  }

  void AcceptAll() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;  // EAGAIN: drained
      ::fcntl(fd, F_SETFL, O_NONBLOCK);
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Keepalive: watch-channel connections idle for the whole job; this
      // keeps NAT/conntrack mappings alive and surfaces silent drops.
      ::setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
      int idle = 60, intvl = 20, cnt = 3;
      ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
      ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
      ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
      Conn& c = conns_[fd];
      c = Conn{};
      c.gen = ++conn_gen_;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u64 = Tag(fd, c.gen);
      ::epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  // false = the connection was closed (caller must not touch it again)
  bool ReadAvailable(int fd) {
    Conn& c = conns_[fd];
    char buf[65536];
    for (;;) {
      ssize_t got = ::recv(fd, buf, sizeof(buf), 0);
      if (got > 0) {
        c.rbuf.append(buf, static_cast<size_t>(got));
        if (got < static_cast<ssize_t>(sizeof(buf))) break;
        continue;
      }
      if (got < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      CloseConn(fd);  // EOF or hard error — possibly a dead rank
      return false;
    }
    return ProcessFrames(fd);
  }

  bool ProcessFrames(int fd) {
    for (;;) {
      Conn& c = conns_[fd];
      if (c.rbuf.size() < 40) return true;
      uint64_t len = 0;
      for (int i = 0; i < 8; ++i)
        len = (len << 8) | static_cast<uint8_t>(c.rbuf[32 + i]);
      // The length field arrives before the body it is HMAC'd with, so it
      // is attacker-controlled on a non-loopback bind: bound it well below
      // anything that could throw bad_alloc (fused buffers are ~64 MB).
      if (len > (1ull << 31)) {
        CloseConn(fd);
        return false;
      }
      std::string body;
      try {
        if (c.rbuf.size() < 40 + len) {
          c.rbuf.reserve(40 + len);  // one allocation for the rest
          return true;
        }
        uint8_t digest[32];
        HmacSha256(secret_,
                   reinterpret_cast<const uint8_t*>(c.rbuf.data()) + 40,
                   len, digest);
        if (!ConstTimeEqual(digest,
                            reinterpret_cast<const uint8_t*>(c.rbuf.data()),
                            32)) {
          CloseConn(fd);  // unauthenticated frame: drop, as ReadFrame did
          return false;
        }
        body = c.rbuf.substr(40, len);
      } catch (const std::bad_alloc&) {
        // The claimed length precedes its HMAC check, so it is
        // attacker-controlled: drop the connection, never the coordinator.
        CloseConn(fd);
        return false;
      }
      c.rbuf.erase(0, 40 + len);
      try {
        Dispatch(fd, body);
      } catch (const std::exception& e) {
        // Behavior contract with the Python service: a handler failure is
        // a per-request remote error, never a coordinator crash.
        QueueWrite(fd, ErrorResp(std::string("native controller error: ") +
                                 e.what()));
      }
      if (conns_.find(fd) == conns_.end()) return false;
    }
  }

  void QueueWrite(int fd, const std::string& framed) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;  // waiter died before completion
    it->second.wbuf.append(framed);
    FlushWrites(fd, &it->second);
  }

  void FlushWrites(int fd, Conn* c) {
    while (c->woff < c->wbuf.size()) {
      ssize_t sent = ::send(fd, c->wbuf.data() + c->woff,
                            c->wbuf.size() - c->woff, MSG_NOSIGNAL);
      if (sent > 0) {
        c->woff += static_cast<size_t>(sent);
        continue;
      }
      if (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      CloseConn(fd);
      return;
    }
    bool need_out = c->woff < c->wbuf.size();
    if (!need_out && c->woff) {
      c->wbuf.clear();
      c->woff = 0;
    }
    if (need_out != c->out_armed) {
      epoll_event ev{};
      ev.events = EPOLLIN | (need_out ? EPOLLOUT : 0u);
      ev.data.u64 = Tag(fd, c->gen);
      ::epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, fd, &ev);
      c->out_armed = need_out;
    }
  }

  void CloseConn(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    int rank = it->second.rank;
    if (rank >= 0) DeidentifyConn(fd, rank);
    ::epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, fd, nullptr);
    ::close(fd);
    conns_.erase(it);
    // A parked fd can no longer receive its rendezvous response.
    for (auto& kv : cycles_) EraseWaiter(&kv.second.waiters, fd);
    for (auto& kv : payloads_) EraseWaiter(&kv.second.waiters, fd);
    EraseWaiter(&watch_fds_, fd);
    if (rank >= 0) AbortWorld(rank);
  }

  // Bind fd to rank; a NEW connection for a rank SUPERSEDES any previous
  // one (de-identified, not closed), so a client that reconnects — e.g.
  // its hello reply was lost to a transient reset and it retried — does
  // not get the stale connection's eventual close attributed as its own
  // death. The rank_fds_ reverse map keeps the supersede O(1): an init
  // hello storm at large world sizes must not become an O(N^2) scan on
  // the one event-loop thread.
  void IdentifyConn(int fd, int rank) {
    Conn& c = conns_[fd];
    if (c.rank == rank) return;
    auto it = rank_fds_.find(rank);
    if (it != rank_fds_.end() && it->second != fd) {
      auto old = conns_.find(it->second);
      if (old != conns_.end()) old->second.rank = -1;
    }
    rank_fds_[rank] = fd;
    c.rank = rank;
  }

  void DeidentifyConn(int fd, int rank) {
    auto it = rank_fds_.find(rank);
    if (it != rank_fds_.end() && it->second == fd) rank_fds_.erase(it);
  }

  static void EraseWaiter(std::vector<int>* waiters, int fd) {
    for (auto it = waiters->begin(); it != waiters->end(); ++it)
      if (*it == fd) {
        waiters->erase(it);
        return;
      }
  }

  // An identified rank's connection died mid-job: attribute, record the
  // abort reason, and poison every parked rendezvous so survivors unblock
  // with SHUT_DOWN_ERROR (reference semantics, operations.cc:1942-1957).
  void AbortWorld(int rank) {
    std::string reason;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (world_shutdown_ || stopping_) return;
      if (abort_reason_.empty())
        abort_reason_ = "rank " + std::to_string(rank) + " exited mid-job. " +
                        shutdown_error_;
      reason = abort_reason_;
    }
    std::fprintf(stderr,
                 "[horovod_tpu native controller] %s — aborting in-flight "
                 "collectives on all ranks\n",
                 reason.c_str());
    const std::string resp = ErrorResp(reason);
    for (int fd : DrainWaiters()) QueueWrite(fd, resp);
    for (int fd : DrainWatchers()) QueueWrite(fd, resp);
  }

  // -- dispatch --------------------------------------------------------------

  std::string ErrorResp(const std::string& msg) {
    Writer w;
    w.Put<uint8_t>(1);
    w.Put<uint32_t>(static_cast<uint32_t>(msg.size()));
    w.PutBytes(msg);
    return FrameBody(w.out);
  }

  void Dispatch(int fd, const std::string& body) {
    Reader r{reinterpret_cast<const uint8_t*>(body.data()), body.size()};
    uint8_t kind = r.Get<uint8_t>();
    if (!r.ok) return QueueWrite(fd, ErrorResp("malformed request"));
    if (kind == 0x80) {
      // A pickle protocol marker: this rank fell back to the Python
      // controller client (native core unavailable there?) while the
      // coordinator runs the native service. It cannot parse our error
      // frame either — log the diagnosis where the operator will look.
      std::fprintf(stderr,
                   "[horovod_tpu native controller] received a PICKLE "
                   "request: a rank is running the Python controller "
                   "client against the native service. "
                   "HOROVOD_NATIVE_CONTROLLER must resolve identically on "
                   "every rank (is the native core built on every host?). "
                   "Set HOROVOD_NATIVE_CONTROLLER=0 to force the Python "
                   "service everywhere.\n");
      return QueueWrite(fd,
                        ErrorResp("protocol mismatch: coordinator speaks "
                                  "the native binary protocol"));
    }
    switch (kind) {
      case kHello: {
        int32_t rank = r.Get<int32_t>();
        std::string caller_wid;
        if (r.n >= 2) {
          uint16_t wid_len = r.Get<uint16_t>();
          caller_wid = r.GetBytes(wid_len);
        }
        // A declared world-id length that overruns the frame (r.ok false)
        // must REFUSE, not fall through as if the hello carried no world
        // id — that would let a corrupt frame from a wrong-world client
        // bypass the identity guard (the Python service errors on a
        // malformed request tuple the same way).
        if (!r.ok)
          return QueueWrite(fd, ErrorResp("malformed hello: world id "
                                          "length overruns the frame"));
        if (!caller_wid.empty() && !world_id_.empty() &&
            caller_wid != world_id_) {
          // a co-scheduled different world's client (subset schedules
          // share this port): refusing prevents its remapped rank from
          // superseding a LIVE member of this world
          return QueueWrite(
              fd, ErrorResp(WorldMismatchError(world_id_, caller_wid)));
        }
        bool world_over = world_shutdown_;
        std::string extra;
        if (!world_over) {
          std::lock_guard<std::mutex> guard(mutex_);
          if (!abort_reason_.empty()) {  // aborted world: same race; the
            world_over = true;           // reason rides inside the
            extra = " (predecessor world aborted: " + abort_reason_ + ")";
          }
        }
        if (world_over) {
          // A hello after this world's negotiated shutdown is a
          // NEXT-world client reaching the dying service on the shared
          // port. Refuse with the retryable sentinel (exact text shared
          // with the Python service and both clients' retry checks) —
          // serving it would leave its first cycle to EOF at stop,
          // which surfaced as a spurious world abort (re-init soak).
          return QueueWrite(
              fd, ErrorResp(std::string(kControllerRestarting) + extra));
        }
        IdentifyConn(fd, rank);
        Writer w;
        w.Put<uint8_t>(0);
        return QueueWrite(fd, FrameBody(w.out));
      }
      case kBye: {
        // De-identify: the close that follows a farewell is orderly, not a
        // rank death (the threaded design erased conn_ranks_ the same way).
        Conn& c = conns_[fd];
        if (c.rank >= 0) DeidentifyConn(fd, c.rank);
        c.rank = -1;
        Writer w;
        w.Put<uint8_t>(0);
        return QueueWrite(fd, FrameBody(w.out));
      }
      case kCycle:
        return HandleCycle(fd, &r);
      case kPayload:
        return HandlePayload(fd, &r);
      case kWatch: {
        std::string caller_wid;
        if (r.n >= 2) {
          uint16_t wid_len = r.Get<uint16_t>();
          caller_wid = r.GetBytes(wid_len);
        }
        if (!r.ok)  // same malformed-length refusal as kHello above
          return QueueWrite(fd, ErrorResp("malformed watch: world id "
                                          "length overruns the frame"));
        if (!caller_wid.empty() && !world_id_.empty() &&
            caller_wid != world_id_) {
          // wrong world: must neither park nor receive THIS world's abort
          return QueueWrite(
              fd, ErrorResp(WorldMismatchError(world_id_, caller_wid)));
        }
        {
          std::lock_guard<std::mutex> guard(mutex_);
          if (!abort_reason_.empty())
            return QueueWrite(fd, ErrorResp(abort_reason_));
        }
        if (world_shutdown_) {
          // next-world watcher on the shared port: refuse retryably
          // instead of parking (a park would answer "clean stop" and
          // leave the successor world silently unwatched)
          return QueueWrite(fd, ErrorResp(kControllerRestarting));
        }
        watch_fds_.push_back(fd);  // parked until abort or stop
        return;
      }
      default:
        return QueueWrite(fd, ErrorResp("unknown request kind"));
    }
  }

  void HandleCycle(int fd, Reader* r) {
    int32_t rank = r->Get<int32_t>();
    uint8_t shutdown = r->Get<uint8_t>();
    uint32_t nreq = r->Get<uint32_t>();
    std::vector<Request> reqs;
    reqs.reserve(nreq);
    for (uint32_t i = 0; i < nreq && r->ok; ++i) {
      Request req;
      req.rank = rank;
      uint8_t op = r->Get<uint8_t>();
      uint8_t dtype = r->Get<uint8_t>();
      // Range-check wire enums before they index kDtypeBytes/kOpNames —
      // the Python twin gets this for free from DataType()/RequestType().
      if (op > 2 || dtype > 10)
        return QueueWrite(
            fd, ErrorResp("malformed cycle request (bad op or dtype)"));
      req.op = static_cast<Op>(op);
      req.dtype = dtype;
      req.root_rank = r->Get<int32_t>();
      uint8_t ndim = r->Get<uint8_t>();
      for (uint8_t d = 0; d < ndim; ++d)
        req.shape.push_back(r->Get<int64_t>());
      uint16_t name_len = r->Get<uint16_t>();
      req.name = r->GetBytes(name_len);
      reqs.push_back(std::move(req));
    }
    if (!r->ok) return QueueWrite(fd, ErrorResp("malformed cycle request"));

    IdentifyConn(fd, rank);
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (!abort_reason_.empty())
        return QueueWrite(fd, ErrorResp(abort_reason_));
    }
    int64_t key = rank_cycles_[rank]++;
    CycleSlot& slot = cycles_[key];
    slot.lists[rank] = {std::move(reqs), shutdown != 0};
    if (static_cast<int>(slot.lists.size()) < size_) {
      slot.waiters.push_back(fd);  // parked: no thread, no response yet
      return;
    }
    // Last rank in: negotiate once, answer everyone.
    // rank order, matching the Python service's deterministic feed
    bool any_shutdown = false;
    for (auto& kv : slot.lists) {
      for (Request& req : kv.second.first)
        negotiator_.AddRequest(std::move(req), false);
      any_shutdown |= kv.second.second;
    }
    if (any_shutdown) negotiator_.SetShutdown();
    std::vector<std::string> stalls;
    bool world_shutdown = false;
    std::vector<Response> responses =
        negotiator_.ConstructList(&stalls, &world_shutdown);
    history_[cycle_no_] = responses;
    history_.erase(cycle_no_ - 16);
    ++cycle_no_;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (world_shutdown) world_shutdown_ = true;
      // Autotune observation: (payload bytes, active µs) per cycle,
      // drained by the Python tuner thread (parameter_manager.cc scoring).
      int64_t bytes = 0;
      if (collect_stats_)
        for (const Response& resp : responses)
          if (resp.type != RespType::ERROR) bytes += resp.payload_bytes;
      if (bytes > 0 && stats_.size() < 4096) {
        double us = std::chrono::duration<double, std::micro>(
                        std::chrono::steady_clock::now() - slot.t0)
                        .count();
        stats_.emplace_back(static_cast<double>(bytes), us);
      }
    }
    const std::string framed =
        FrameBody(EncodeCycleResponse(responses, stalls, world_shutdown));
    std::vector<int> waiters = std::move(slot.waiters);
    cycles_.erase(key);  // queued responses ARE delivery; GC the slot now
    for (int w : waiters) QueueWrite(w, framed);
    QueueWrite(fd, framed);
  }

  std::string EncodeCycleResponse(const std::vector<Response>& responses,
                                  const std::vector<std::string>& stalls,
                                  bool shutdown) {
    double tuned_cycle_ms;
    {
      std::lock_guard<std::mutex> guard(mutex_);
      tuned_cycle_ms = tuned_cycle_ms_;
    }
    Writer w;
    w.Put<uint8_t>(0);
    w.Put<uint8_t>(shutdown ? 1 : 0);
    // Tuned cycle time piggybacks to every rank, the role of the
    // reference's Params broadcast (parameter_manager.cc:213 SyncParams).
    w.Put<uint8_t>(tuned_cycle_ms > 0 ? 1 : 0);
    w.Put<double>(tuned_cycle_ms);
    w.Put<uint32_t>(static_cast<uint32_t>(responses.size()));
    for (const Response& resp : responses) {
      w.Put<uint8_t>(static_cast<uint8_t>(resp.type));
      w.Put<uint8_t>(static_cast<uint8_t>(resp.dtype));
      w.Put<uint64_t>(static_cast<uint64_t>(resp.payload_bytes));
      w.Put<uint16_t>(static_cast<uint16_t>(resp.names.size()));
      for (const std::string& name : resp.names) {
        w.Put<uint16_t>(static_cast<uint16_t>(name.size()));
        w.PutBytes(name);
      }
      w.Put<uint32_t>(static_cast<uint32_t>(resp.error.size()));
      w.PutBytes(resp.error);
      w.Put<uint32_t>(static_cast<uint32_t>(resp.sizes.size()));
      for (int64_t s : resp.sizes) w.Put<int64_t>(s);
    }
    w.Put<uint32_t>(static_cast<uint32_t>(stalls.size()));
    for (const std::string& s : stalls) {
      w.Put<uint32_t>(static_cast<uint32_t>(s.size()));
      w.PutBytes(s);
    }
    return w.out;
  }

  void HandlePayload(int fd, Reader* r) {
    int32_t rank = r->Get<int32_t>();
    uint64_t cycle_no = r->Get<uint64_t>();
    uint32_t idx = r->Get<uint32_t>();
    uint64_t data_len = r->Get<uint64_t>();
    if (!r->ok || r->n < data_len)
      return QueueWrite(fd, ErrorResp("malformed payload"));
    std::string data = r->GetBytes(data_len);

    IdentifyConn(fd, rank);
    {
      std::lock_guard<std::mutex> guard(mutex_);
      if (!abort_reason_.empty())
        return QueueWrite(fd, ErrorResp(abort_reason_));
    }
    auto hist_it = history_.find(static_cast<int64_t>(cycle_no));
    if (hist_it == history_.end() || idx >= hist_it->second.size())
      return QueueWrite(
          fd, ErrorResp("payload references an unknown cycle/response"));
    const Response resp = hist_it->second[idx];  // copy: history may be
                                                 // pruned before combine
    if (resp.type == RespType::ERROR)
      return QueueWrite(
          fd, ErrorResp("payload submitted for an error response: " +
                        resp.error));
    auto key = std::make_pair(static_cast<int64_t>(cycle_no),
                              static_cast<int64_t>(idx));
    PayloadSlot& slot = payloads_[key];
    slot.data[rank] = std::move(data);
    if (static_cast<int>(slot.data.size()) < size_) {
      slot.waiters.push_back(fd);
      return;
    }
    // Last payload in: combine on the loop thread (the reference's
    // coordinator likewise combines on its one background thread) and
    // answer everyone.
    std::map<int, std::string> gathered = std::move(slot.data);
    std::string framed;
    try {
      std::string combined = Combine(resp, gathered);
      Writer w;
      w.Put<uint8_t>(0);
      w.Put<uint64_t>(combined.size());
      w.PutBytes(combined);
      framed = FrameBody(w.out);
    } catch (const std::exception& e) {
      // Poison the slot for every waiting rank, like the Python
      // rendezvous does on a compute failure.
      const std::string error = e.what();
      Writer w;
      w.Put<uint8_t>(1);
      w.Put<uint32_t>(static_cast<uint32_t>(error.size()));
      w.PutBytes(error);
      framed = FrameBody(w.out);
    }
    std::vector<int> waiters = std::move(slot.waiters);
    payloads_.erase(key);
    for (int w : waiters) QueueWrite(w, framed);
    QueueWrite(fd, framed);
  }

  std::string Combine(const Response& resp,
                      const std::map<int, std::string>& data) {
    if (resp.type == RespType::ALLREDUCE) {
      std::string acc = data.begin()->second;
      for (auto it = std::next(data.begin()); it != data.end(); ++it) {
        // The Python twin's numpy add raises on ragged buffers; an
        // unchecked sum here would read past the shorter one.
        if (it->second.size() != acc.size())
          throw std::runtime_error(
              "allreduce payload size mismatch across ranks (" +
              std::to_string(acc.size()) + " vs " +
              std::to_string(it->second.size()) + " bytes)");
        SumInto(&acc, it->second, resp.dtype);
      }
      return acc;
    }
    if (resp.type == RespType::ALLGATHER) {
      std::string out;
      for (const auto& kv : data) out += kv.second;
      return out;
    }
    // BROADCAST: sizes[0] is the root rank
    if (resp.sizes.empty())
      throw std::runtime_error("broadcast response carries no root rank");
    auto it = data.find(static_cast<int>(resp.sizes[0]));
    if (it == data.end())
      throw std::runtime_error("broadcast root sent no payload");
    return it->second;
  }

  const int size_;
  const std::string secret_;
  const std::string shutdown_error_;
  const bool collect_stats_;
  Negotiator negotiator_;

  int listen_fd_ = -1;
  int port_ = 0;
  int epoll_fd_ = -1;
  int wake_fd_ = -1;
  std::thread loop_thread_;

  // loop-thread-owned (no lock):
  std::unordered_map<int, Conn> conns_;
  std::unordered_map<int, int> rank_fds_;  // rank -> identified fd
  uint32_t conn_gen_ = 0;  // per-accept generation for stale-event guard
  std::vector<int> watch_fds_;  // parked abort-watch connections
  std::unordered_map<int, int64_t> rank_cycles_;
  std::map<int64_t, CycleSlot> cycles_;
  int64_t cycle_no_ = 0;
  std::map<int64_t, std::vector<Response>> history_;
  std::map<std::pair<int64_t, int64_t>, PayloadSlot> payloads_;

  // shared with external API threads; guarded by mutex_:
  std::string world_id_;  // loop-thread-read only after construction
  std::mutex mutex_;
  bool stopping_ = false;
  bool world_shutdown_ = false;
  std::string abort_reason_;
  double tuned_cycle_ms_ = 0;  // 0 = untuned
  std::vector<std::pair<double, double>> stats_;  // (bytes, active_us)
};

}  // namespace
}  // namespace htpu

extern "C" {

void* htpu_controller_start(int size, const char* bind_host, int port,
                            const uint8_t* secret, int secret_len,
                            long long fusion_threshold,
                            double stall_warning_s, int stall_check_disable,
                            const char* shutdown_error, int collect_stats,
                            const char* world_id,
                            char* err_out, int err_cap) {
  auto* server = new htpu::ControllerServer(
      size, std::string(reinterpret_cast<const char*>(secret),
                        static_cast<size_t>(secret_len)),
      fusion_threshold, stall_warning_s, stall_check_disable != 0,
      shutdown_error, collect_stats != 0,
      world_id ? world_id : "");
  std::string err;
  if (!server->Start(bind_host, port, &err)) {
    std::snprintf(err_out, static_cast<size_t>(err_cap), "%s", err.c_str());
    delete server;
    return nullptr;
  }
  return server;
}

int htpu_controller_port(void* handle) {
  return static_cast<htpu::ControllerServer*>(handle)->port();
}

int htpu_controller_world_shutdown(void* handle) {
  return static_cast<htpu::ControllerServer*>(handle)->world_shutdown() ? 1
                                                                        : 0;
}

int htpu_controller_drain_stats(void* handle, double* bytes_out,
                                double* us_out, int cap) {
  return static_cast<htpu::ControllerServer*>(handle)->DrainStats(
      bytes_out, us_out, cap);
}

void htpu_controller_set_tuning(void* handle, long long fusion_bytes,
                                double cycle_ms) {
  static_cast<htpu::ControllerServer*>(handle)->SetTuning(fusion_bytes,
                                                          cycle_ms);
}

void htpu_controller_stop(void* handle) {
  auto* server = static_cast<htpu::ControllerServer*>(handle);
  server->Stop();
  delete server;
}

}  // extern "C"
