// Negotiation core shared by the ctypes negotiator shim and the native
// controller service (single definition; see negotiator.cc for provenance
// and reference citations).
#ifndef HTPU_NEGOTIATOR_CORE_H_
#define HTPU_NEGOTIATOR_CORE_H_

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace htpu {

enum class Op : int { ALLREDUCE = 0, ALLGATHER = 1, BROADCAST = 2 };
enum class RespType : int { ALLREDUCE = 0, ALLGATHER = 1, BROADCAST = 2, ERROR = 3 };

inline const char* const kOpNames[] = {"ALLREDUCE", "ALLGATHER", "BROADCAST"};
inline const char* const kDtypeNames[] = {"UINT8",   "INT8",    "UINT16",  "INT16",
                             "INT32",   "INT64",   "FLOAT16", "FLOAT32",
                             "FLOAT64", "BOOL",    "BFLOAT16"};
inline const int64_t kDtypeBytes[] = {1, 1, 2, 2, 4, 8, 2, 4, 8, 1, 2};

struct Request {
  int rank = -1;
  Op op = Op::ALLREDUCE;
  int dtype = 0;
  std::string name;
  int root_rank = -1;
  std::vector<int64_t> shape;

  int64_t nbytes() const {
    int64_t n = kDtypeBytes[dtype];
    for (int64_t d : shape) n *= d;
    return n;
  }
};

struct Response {
  RespType type = RespType::ALLREDUCE;
  std::vector<std::string> names;
  std::string error;
  std::vector<int64_t> sizes;
  int dtype = 0;
  int64_t payload_bytes = 0;
};

struct TableEntry {
  std::map<int, Request> requests;  // rank -> request (sorted by rank)
  std::chrono::steady_clock::time_point first_seen =
      std::chrono::steady_clock::now();
  int64_t arrival = 0;
};

inline std::string ShapeStr(const std::vector<int64_t>& shape) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < shape.size(); ++i) {
    if (i) os << ", ";
    os << shape[i];
  }
  os << "]";
  return os.str();
}

inline std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

class Negotiator {
 public:
  Negotiator(int size, int64_t fusion_threshold, double stall_warning_s,
             bool stall_check_disable)
      : size_(size),
        fusion_threshold_(fusion_threshold),
        stall_warning_s_(stall_warning_s),
        stall_check_disable_(stall_check_disable),
        last_stall_check_(std::chrono::steady_clock::now()) {}

  void AddRequest(Request req, bool shutdown) {
    std::lock_guard<std::mutex> guard(mutex_);
    if (shutdown) shutdown_ = true;
    TableEntry& entry = table_[req.name];
    std::string name = req.name;
    entry.requests[req.rank] = std::move(req);
    if (static_cast<int>(entry.requests.size()) == size_) {
      entry.arrival = ++arrivals_;
      ready_.emplace_back(entry.arrival, name);
    }
  }

  void SetShutdown() {
    std::lock_guard<std::mutex> guard(mutex_);
    shutdown_ = true;
  }

  // Autotuner hook: the coordinator retunes the fusion window between
  // cycles (parameter_manager.cc Tune/SyncParams).
  void SetFusionThreshold(int64_t bytes) {
    std::lock_guard<std::mutex> guard(mutex_);
    fusion_threshold_ = bytes;
  }

  // Drain ready tensors into the cycle's fused ResponseList (struct form,
  // used directly by the native controller service). Outputs the stall
  // warnings and whether the world has negotiated shutdown.
  std::vector<Response> ConstructList(std::vector<std::string>* stalls,
                                      bool* shutdown) {
    std::lock_guard<std::mutex> guard(mutex_);
    std::sort(ready_.begin(), ready_.end());
    std::vector<Response> responses;
    for (const auto& item : ready_) {
      const std::string& name = item.second;
      auto it = table_.find(name);
      if (it == table_.end()) continue;
      Response resp = ConstructResponse(name, it->second);
      const Request& first = it->second.requests.begin()->second;
      resp.dtype = first.dtype;
      resp.payload_bytes = first.nbytes();
      responses.push_back(std::move(resp));
      table_.erase(it);
    }
    ready_.clear();
    *stalls = MaybeCheckStalls();
    *shutdown = shutdown_;
    return Fuse(responses);
  }

  // Drain ready tensors into the cycle's ResponseList JSON (the ctypes
  // negotiator shim's wire).
  std::string Construct() {
    std::vector<std::string> stalls;
    bool shutdown = false;
    std::vector<Response> fused = ConstructList(&stalls, &shutdown);
    return ToJson(fused, stalls, shutdown);
  }

 private:
  Response ConstructResponse(const std::string& name, const TableEntry& entry) {
    std::vector<const Request*> reqs;
    for (const auto& kv : entry.requests) reqs.push_back(&kv.second);
    const Request& first = *reqs[0];

    auto error = [&](const std::string& msg) {
      Response r;
      r.type = RespType::ERROR;
      r.names = {name};
      r.error = msg;
      return r;
    };

    for (size_t i = 1; i < reqs.size(); ++i) {
      const Request& req = *reqs[i];
      if (req.op != first.op) {
        std::ostringstream os;
        os << "Mismatched collective operations: rank " << first.rank
           << " requested " << kOpNames[static_cast<int>(first.op)]
           << ", but rank " << req.rank << " requested "
           << kOpNames[static_cast<int>(req.op)] << " for tensor " << name
           << ".";
        return error(os.str());
      }
      if (req.dtype != first.dtype) {
        std::ostringstream os;
        os << "Mismatched data types: rank " << first.rank << " sent "
           << kDtypeNames[first.dtype] << ", but rank " << req.rank
           << " sent " << kDtypeNames[req.dtype] << " for tensor " << name
           << ".";
        return error(os.str());
      }
    }

    if (first.op == Op::ALLREDUCE) {
      for (size_t i = 1; i < reqs.size(); ++i) {
        if (reqs[i]->shape != first.shape) {
          std::ostringstream os;
          os << "Mismatched allreduce tensor shapes: rank " << first.rank
             << " sent shape " << ShapeStr(first.shape) << ", but rank "
             << reqs[i]->rank << " sent shape " << ShapeStr(reqs[i]->shape)
             << " for tensor " << name << ".";
          return error(os.str());
        }
      }
      Response r;
      r.type = RespType::ALLREDUCE;
      r.names = {name};
      return r;
    }

    if (first.op == Op::BROADCAST) {
      for (size_t i = 1; i < reqs.size(); ++i) {
        if (reqs[i]->root_rank != first.root_rank) {
          std::ostringstream os;
          os << "Mismatched broadcast root ranks: rank " << first.rank
             << " specified root " << first.root_rank << ", but rank "
             << reqs[i]->rank << " specified root " << reqs[i]->root_rank
             << " for tensor " << name << ".";
          return error(os.str());
        }
      }
      if (first.root_rank < 0 || first.root_rank >= size_) {
        std::ostringstream os;
        os << "Invalid broadcast root rank " << first.root_rank
           << " for a world of size " << size_ << " (tensor " << name << ").";
        return error(os.str());
      }
      auto root_it = entry.requests.find(first.root_rank);
      const std::vector<int64_t>& root_shape =
          root_it != entry.requests.end() ? root_it->second.shape : first.shape;
      for (const Request* req : reqs) {
        if (req->shape != root_shape) {
          std::ostringstream os;
          os << "Mismatched broadcast tensor shapes: root sent shape "
             << ShapeStr(root_shape) << ", but rank " << req->rank
             << " has shape " << ShapeStr(req->shape) << " for tensor "
             << name << ".";
          return error(os.str());
        }
      }
      Response r;
      r.type = RespType::BROADCAST;
      r.names = {name};
      r.sizes = {first.root_rank};
      return r;
    }

    // ALLGATHER: ragged first dim allowed, trailing dims must match
    // (operations.cc:382-430); sizes = rank-ordered recvcounts.
    for (size_t i = 1; i < reqs.size(); ++i) {
      const Request& req = *reqs[i];
      bool trailing_match =
          req.shape.size() == first.shape.size() &&
          std::equal(req.shape.begin() + 1, req.shape.end(),
                     first.shape.begin() + 1);
      if (!trailing_match) {
        std::ostringstream os;
        os << "Mismatched allgather tensor shapes: every dimension except "
              "the first must match; rank "
           << first.rank << " sent " << ShapeStr(first.shape) << ", rank "
           << req.rank << " sent " << ShapeStr(req.shape) << " for tensor "
           << name << ".";
        return error(os.str());
      }
    }
    if (first.shape.empty()) {
      std::ostringstream os;
      os << "Rank zero tried to allgather a rank-zero tensor (" << name
         << "); allgather requires at least one dimension.";
      return error(os.str());
    }
    Response r;
    r.type = RespType::ALLGATHER;
    r.names = {name};
    for (const Request* req : reqs) r.sizes.push_back(req->shape[0]);
    return r;
  }

  std::vector<Response> Fuse(const std::vector<Response>& responses) {
    std::vector<Response> fused;
    size_t i = 0;
    while (i < responses.size()) {
      const Response& resp = responses[i];
      if (resp.type != RespType::ALLREDUCE) {
        fused.push_back(resp);
        ++i;
        continue;
      }
      Response batch = resp;
      int64_t total = resp.payload_bytes;
      size_t j = i + 1;
      while (j < responses.size()) {
        const Response& nxt = responses[j];
        if (nxt.type != RespType::ALLREDUCE || nxt.dtype != batch.dtype) break;
        if (total + nxt.payload_bytes > fusion_threshold_) break;
        batch.names.insert(batch.names.end(), nxt.names.begin(),
                           nxt.names.end());
        total += nxt.payload_bytes;
        ++j;
      }
      batch.payload_bytes = total;
      fused.push_back(std::move(batch));
      i = j;
    }
    return fused;
  }

  std::vector<std::string> MaybeCheckStalls() {
    std::vector<std::string> warnings;
    if (stall_check_disable_) return warnings;
    auto now = std::chrono::steady_clock::now();
    double since = std::chrono::duration<double>(now - last_stall_check_).count();
    if (since < stall_warning_s_) return warnings;
    last_stall_check_ = now;
    for (const auto& kv : table_) {
      double age =
          std::chrono::duration<double>(now - kv.second.first_seen).count();
      if (age <= stall_warning_s_) continue;
      std::ostringstream missing, ready;
      bool mfirst = true, rfirst = true;
      std::set<int> have;
      for (const auto& rkv : kv.second.requests) have.insert(rkv.first);
      for (int r = 0; r < size_; ++r) {
        if (have.count(r)) {
          if (!rfirst) ready << ", ";
          ready << r;
          rfirst = false;
        } else {
          if (!mfirst) missing << ", ";
          missing << r;
          mfirst = false;
        }
      }
      std::ostringstream os;
      os << "One or more tensors were submitted to be reduced, gathered or "
            "broadcasted by subset of ranks and are waiting for remainder of "
            "ranks for more than "
         << static_cast<int>(stall_warning_s_)
         << " seconds. This may indicate that different ranks are trying to "
            "submit different tensors or that only subset of ranks is "
            "submitting tensors, which will cause deadlock. Stalled ops: "
         << kv.first << " [missing ranks: " << missing.str()
         << "] [ready ranks: " << ready.str() << "]";
      warnings.push_back(os.str());
    }
    return warnings;
  }

  std::string ToJson(const std::vector<Response>& responses,
                     const std::vector<std::string>& stalls,
                     bool shutdown) {
    std::ostringstream os;
    os << "{\"shutdown\":" << (shutdown ? 1 : 0) << ",\"responses\":[";
    for (size_t i = 0; i < responses.size(); ++i) {
      const Response& r = responses[i];
      if (i) os << ",";
      os << "{\"type\":" << static_cast<int>(r.type) << ",\"names\":[";
      for (size_t k = 0; k < r.names.size(); ++k) {
        if (k) os << ",";
        os << "\"" << JsonEscape(r.names[k]) << "\"";
      }
      os << "],\"error\":\"" << JsonEscape(r.error) << "\",\"sizes\":[";
      for (size_t k = 0; k < r.sizes.size(); ++k) {
        if (k) os << ",";
        os << r.sizes[k];
      }
      os << "],\"dtype\":" << r.dtype
         << ",\"bytes\":" << r.payload_bytes << "}";
    }
    os << "],\"stall_warnings\":[";
    for (size_t i = 0; i < stalls.size(); ++i) {
      if (i) os << ",";
      os << "\"" << JsonEscape(stalls[i]) << "\"";
    }
    os << "]}";
    return os.str();
  }

  const int size_;
  int64_t fusion_threshold_;
  const double stall_warning_s_;
  const bool stall_check_disable_;
  std::mutex mutex_;
  std::unordered_map<std::string, TableEntry> table_;
  std::vector<std::pair<int64_t, std::string>> ready_;
  int64_t arrivals_ = 0;
  bool shutdown_ = false;
  std::chrono::steady_clock::time_point last_stall_check_;
};

}  // namespace htpu

#endif  // HTPU_NEGOTIATOR_CORE_H_
