// Native negotiation core: message table, response construction, fusion.
//
// TPU-native rebuild of the coordinator logic of
// horovod/common/operations.cc — IncrementTensorCount (operations.cc:287-319),
// ConstructResponse (:321-523), the fusion-packing loop (:2154-2266) and
// CheckForStalledTensors (:1625-1672) — as a framework-agnostic shared
// library with a C API (loaded via ctypes; no pybind11 in this build).
//
// Behavior contract: byte-identical response ordering, fusion decisions and
// error strings to the Python Negotiator (horovod_tpu/ops/controller.py);
// the test suite runs the same cases against both implementations. The wire
// out of construct() is a compact JSON document — the control plane carries
// names and shapes at cycle frequency, never tensor data.

#include "negotiator_core.h"

using htpu::Negotiator;
using htpu::Op;
using htpu::Request;


extern "C" {

void* htpu_negotiator_new(int size, long long fusion_threshold,
                          double stall_warning_s, int stall_check_disable) {
  return new Negotiator(size, fusion_threshold, stall_warning_s,
                        stall_check_disable != 0);
}

void htpu_negotiator_free(void* handle) {
  delete static_cast<Negotiator*>(handle);
}

void htpu_negotiator_add_request(void* handle, int rank, int op, int dtype,
                                 const char* name, int root_rank, int ndim,
                                 const long long* dims) {
  Request req;
  req.rank = rank;
  req.op = static_cast<Op>(op);
  req.dtype = dtype;
  req.name = name;
  req.root_rank = root_rank;
  req.shape.assign(dims, dims + ndim);
  static_cast<Negotiator*>(handle)->AddRequest(std::move(req), false);
}

void htpu_negotiator_shutdown(void* handle) {
  static_cast<Negotiator*>(handle)->SetShutdown();
}

void htpu_negotiator_set_fusion_threshold(void* handle, long long bytes) {
  static_cast<Negotiator*>(handle)->SetFusionThreshold(bytes);
}

// Returns a malloc'd JSON string; free with htpu_free.
char* htpu_negotiator_construct(void* handle) {
  std::string json = static_cast<Negotiator*>(handle)->Construct();
  char* out = static_cast<char*>(std::malloc(json.size() + 1));
  std::memcpy(out, json.c_str(), json.size() + 1);
  return out;
}

void htpu_free(char* ptr) { std::free(ptr); }

}  // extern "C"
