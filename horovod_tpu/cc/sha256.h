// SHA-256 + HMAC-SHA256 (FIPS 180-4 / RFC 2104) for the native controller
// service's wire authentication — the same framing as the Python Wire
// (runner/network.py: HMAC digest + u64 length + body). Self-contained so
// the shared library needs no OpenSSL; validated against hashlib/hmac by
// tests/test_native_core.py.
#ifndef HTPU_SHA256_H_
#define HTPU_SHA256_H_

#include <cstdint>
#include <cstring>
#include <string>

namespace htpu {

class Sha256 {
 public:
  Sha256() { Reset(); }

  void Reset() {
    static const uint32_t kInit[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h_, kInit, sizeof(h_));
    len_ = 0;
    buf_len_ = 0;
  }

  void Update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len_ += n;
    while (n > 0) {
      size_t take = 64 - buf_len_;
      if (take > n) take = n;
      std::memcpy(buf_ + buf_len_, p, take);
      buf_len_ += take;
      p += take;
      n -= take;
      if (buf_len_ == 64) {
        Compress(buf_);
        buf_len_ = 0;
      }
    }
  }

  void Final(uint8_t out[32]) {
    uint64_t bit_len = len_ * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len_ != 56) Update(&zero, 1);
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i)
      len_be[i] = static_cast<uint8_t>(bit_len >> (56 - 8 * i));
    // bypass Update's length accounting for the trailer
    std::memcpy(buf_ + 56, len_be, 8);
    Compress(buf_);
    for (int i = 0; i < 8; ++i) {
      out[4 * i + 0] = static_cast<uint8_t>(h_[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(h_[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(h_[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(h_[i]);
    }
  }

 private:
  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Compress(const uint8_t block[64]) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (static_cast<uint32_t>(block[4 * i]) << 24) |
             (static_cast<uint32_t>(block[4 * i + 1]) << 16) |
             (static_cast<uint32_t>(block[4 * i + 2]) << 8) |
             static_cast<uint32_t>(block[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h_[0], b = h_[1], c = h_[2], d = h_[3];
    uint32_t e = h_[4], f = h_[5], g = h_[6], h = h_[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h_[0] += a; h_[1] += b; h_[2] += c; h_[3] += d;
    h_[4] += e; h_[5] += f; h_[6] += g; h_[7] += h;
  }

  uint32_t h_[8];
  uint64_t len_ = 0;
  uint8_t buf_[64];
  size_t buf_len_ = 0;
};

inline void HmacSha256(const std::string& key, const uint8_t* data, size_t n,
                       uint8_t out[32]) {
  uint8_t k[64];
  std::memset(k, 0, sizeof(k));
  if (key.size() > 64) {
    Sha256 kh;
    kh.Update(key.data(), key.size());
    kh.Final(k);  // first 32 bytes; rest stay zero
  } else {
    std::memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 hi;
  hi.Update(ipad, 64);
  hi.Update(data, n);
  hi.Final(inner);
  Sha256 ho;
  ho.Update(opad, 64);
  ho.Update(inner, 32);
  ho.Final(out);
}

inline bool ConstTimeEqual(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t diff = 0;
  for (size_t i = 0; i < n; ++i) diff |= a[i] ^ b[i];
  return diff == 0;
}

}  // namespace htpu

#endif  // HTPU_SHA256_H_
