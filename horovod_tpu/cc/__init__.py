"""ctypes binding for the native core (``libhtpu_core.so``).

The reference binds C++ to Python through per-framework FFI (TF custom op
loading, torch pybind11/cffi, mxnet ctypes — SURVEY L2/L3). This build has
one framework-agnostic shared library and one binding mechanism: ctypes on
an ``extern "C"`` API (pybind11 is not in the image, per the environment
contract). The library is rebuilt on demand when sources are newer than the
binary — the role setup.py's extension builders play in the reference.

Exports:
* ``NativeNegotiator`` — drop-in for ``ops.controller.Negotiator``
* ``NativeParameterManager`` — GP/Bayesian autotuner (parameter_manager.cc)
* ``NativeTimelineWriter`` — background-thread trace writer (timeline.cc)
* ``available()`` — whether the native core loaded
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
import time
from typing import List, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libhtpu_core.so")
_SOURCES = ("negotiator.cc", "autotune.cc", "timeline_writer.cc",
            "controller_service.cc", "negotiator_core.h", "sha256.h",
            "Makefile")

_lib = None
_lib_lock = threading.Lock()
_load_error: Optional[str] = None


def _build_locked() -> None:
    """Serialize builds across processes: every rank of a fresh checkout may
    race into the first build (the launcher spawns them together); an
    exclusive flock makes one rank build while the rest wait, then re-check."""
    import fcntl

    os.makedirs(os.path.join(_DIR, "build"), exist_ok=True)
    lock_path = os.path.join(_DIR, "build", ".build.lock")
    with open(lock_path, "w", encoding="utf-8") as lock_fh:
        fcntl.flock(lock_fh, fcntl.LOCK_EX)
        try:
            if _needs_build():
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, text=True, timeout=120)
        finally:
            fcntl.flock(lock_fh, fcntl.LOCK_UN)


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_DIR, src)) > lib_mtime
        for src in _SOURCES if os.path.exists(os.path.join(_DIR, src)))


def _load():
    global _lib, _load_error
    with _lib_lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            if _needs_build():
                _build_locked()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.SubprocessError) as exc:
            _load_error = str(exc)
            return None
        _declare(lib)
        _lib = lib
        return _lib


def _declare(lib) -> None:
    c = ctypes
    lib.htpu_negotiator_new.restype = c.c_void_p
    lib.htpu_negotiator_new.argtypes = [c.c_int, c.c_longlong, c.c_double,
                                        c.c_int]
    lib.htpu_negotiator_free.argtypes = [c.c_void_p]
    lib.htpu_negotiator_add_request.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_int, c.c_int,
        c.POINTER(c.c_longlong)]
    lib.htpu_negotiator_shutdown.argtypes = [c.c_void_p]
    lib.htpu_negotiator_set_fusion_threshold.argtypes = [c.c_void_p,
                                                         c.c_longlong]
    lib.htpu_negotiator_construct.restype = c.c_void_p  # manual free
    lib.htpu_negotiator_construct.argtypes = [c.c_void_p]
    lib.htpu_free.argtypes = [c.c_void_p]

    lib.htpu_param_manager_new.restype = c.c_void_p
    lib.htpu_param_manager_new.argtypes = [c.c_double, c.c_double, c.c_int,
                                           c.c_int]
    lib.htpu_param_manager_free.argtypes = [c.c_void_p]
    lib.htpu_param_manager_update.restype = c.c_int
    lib.htpu_param_manager_update.argtypes = [c.c_void_p, c.c_double,
                                              c.c_double]
    for fn in ("fusion_bytes", "cycle_ms", "best_fusion_bytes",
               "best_cycle_ms", "best_score"):
        getattr(lib, f"htpu_param_manager_{fn}").restype = c.c_double
        getattr(lib, f"htpu_param_manager_{fn}").argtypes = [c.c_void_p]

    lib.htpu_timeline_open.restype = c.c_void_p
    lib.htpu_timeline_open.argtypes = [c.c_char_p]
    lib.htpu_timeline_write.argtypes = [c.c_void_p, c.c_char_p]
    lib.htpu_timeline_close.argtypes = [c.c_void_p]

    lib.htpu_controller_start.restype = c.c_void_p
    lib.htpu_controller_start.argtypes = [
        c.c_int, c.c_char_p, c.c_int, c.c_char_p, c.c_int, c.c_longlong,
        c.c_double, c.c_int, c.c_char_p, c.c_int, c.c_char_p, c.c_char_p,
        c.c_int]
    lib.htpu_controller_port.restype = c.c_int
    lib.htpu_controller_port.argtypes = [c.c_void_p]
    lib.htpu_controller_world_shutdown.restype = c.c_int
    lib.htpu_controller_world_shutdown.argtypes = [c.c_void_p]
    lib.htpu_controller_drain_stats.restype = c.c_int
    lib.htpu_controller_drain_stats.argtypes = [
        c.c_void_p, c.POINTER(c.c_double), c.POINTER(c.c_double), c.c_int]
    lib.htpu_controller_set_tuning.argtypes = [c.c_void_p, c.c_longlong,
                                               c.c_double]
    lib.htpu_controller_stop.argtypes = [c.c_void_p]


def available() -> bool:
    return _load() is not None


def load_error() -> Optional[str]:
    _load()
    return _load_error


class NativeNegotiator:
    """Same interface as ``ops.controller.Negotiator``, backed by C++.

    Wire-compression codecs (``Request.codec``, the EQuARX int8/fp8 data
    plane) postdate the C++ core's request/response schema, so this
    wrapper keeps the codec bookkeeping in Python: codecs are recorded
    per tensor name at ``add_request_list`` time and stamped onto the
    constructed responses, with mixed-codec fused batches SPLIT into
    codec-pure sub-batches (the C++ fusion loop cannot key on a field it
    does not know). The negotiator runs once per world — on the
    controller service (or the size-1 local world) — and its ResponseList
    is what every rank executes, so the stamping is rank-consistent by
    construction. Cross-rank codec mismatches become coordinator ERROR
    responses, the same contract the Python ``Negotiator`` enforces for
    dtype and codec mismatches."""

    def __init__(self, size: int, fusion_threshold_bytes: int,
                 stall_warning_s: float = 60.0,
                 stall_check_disable: bool = False) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native core unavailable: {_load_error}")
        self._lib = lib
        self._codecs: dict = {}  # in-flight tensor name -> codec tag
        self._mismatched: dict = {}  # name -> (codec_a, codec_b)
        # Idle/hit-cycle bookkeeping for the response-cache bypass
        # (docs/response-cache.md): an all-ranks cache hit adds no
        # requests, so the only reason to cross the FFI boundary is the
        # interval-gated stall check (or a latched shutdown). Track both
        # in Python — same pattern PR 1 uses for codec bookkeeping the
        # C++ wire predates — so steady-state hit cycles skip the
        # construct FFI + JSON parse entirely between stall intervals.
        self._dirty = False
        self._shutdown_latched = False
        self._stall_warning_s = stall_warning_s
        self._stall_check_disable = stall_check_disable
        self._last_ffi_pass = time.monotonic()
        self._handle = lib.htpu_negotiator_new(
            size, fusion_threshold_bytes, stall_warning_s,
            1 if stall_check_disable else 0)

    def set_fusion_threshold(self, threshold_bytes: int) -> None:
        self._lib.htpu_negotiator_set_fusion_threshold(
            self._handle, int(threshold_bytes))

    def request_shutdown(self) -> None:
        """Force shutdown on subsequent response lists (stall-escalation
        path; same contract as ``Negotiator.request_shutdown``)."""
        self._shutdown_latched = True
        self._lib.htpu_negotiator_shutdown(self._handle)

    def add_request_list(self, rl) -> None:
        if rl.shutdown:
            self._shutdown_latched = True
            self._lib.htpu_negotiator_shutdown(self._handle)
        if rl.requests:
            self._dirty = True
        for req in rl.requests:
            # one (codec, apply-fingerprint) wire identity per tensor:
            # both postdate the C++ schema, so both ride this Python
            # bookkeeping and stamp onto the constructed responses
            wire = (getattr(req, "codec", "none"),
                    getattr(req, "apply_fingerprint", ""))
            prev = self._codecs.setdefault(req.tensor_name, wire)
            if prev != wire:
                self._mismatched.setdefault(req.tensor_name, (prev, wire))
            dims = (ctypes.c_longlong * len(req.tensor_shape))(
                *req.tensor_shape)
            self._lib.htpu_negotiator_add_request(
                self._handle, req.request_rank, int(req.request_type),
                int(req.tensor_type), req.tensor_name.encode("utf-8"),
                req.root_rank, len(req.tensor_shape), dims)

    def _stamp_codecs(self, responses):
        """Attach the negotiated (codec, apply-fingerprint) wire
        identities. Mixed-identity ALLREDUCE batches split into adjacent
        identity-pure runs (execution order preserved); cross-rank
        mismatches carve out per-tensor ERROR responses (the Python
        Negotiator's contract for codecs and fused-apply rules
        alike)."""
        from ..ops.messages import Response, ResponseType

        out: List = []
        for resp in responses:
            codecs = []
            for n in resp.tensor_names:
                codec = self._codecs.pop(n, ("none", ""))
                if n in self._mismatched:
                    (a, fa), (b, fb) = self._mismatched.pop(n)
                    what = "compression codecs" if a != b \
                        else "fused-apply rules"
                    one, other = (a, b) if a != b else (fa, fb)
                    codec = Response(
                        ResponseType.ERROR, tensor_names=[n],
                        error_message=(
                            f"Mismatched {what}: one rank sent {one!r}, "
                            f"another sent {other!r} for tensor {n}."))
                codecs.append(codec)
            if resp.response_type != ResponseType.ALLREDUCE:
                # non-fused ops carry one name; a mismatch there still
                # surfaces as the carved-out error
                if codecs and isinstance(codecs[0], Response):
                    out.append(codecs[0])
                    continue
                resp.tensor_codec = codecs[0][0] if codecs else "none"
                out.append(resp)
                continue
            start = 0
            bytes_left = resp.payload_bytes
            for i in range(1, len(codecs) + 1):
                if i < len(codecs) and codecs[i] == codecs[start] and \
                        not isinstance(codecs[start], Response):
                    continue
                if isinstance(codecs[start], Response):  # carved error
                    out.append(codecs[start])
                else:
                    out.append(Response(
                        ResponseType.ALLREDUCE,
                        tensor_names=resp.tensor_names[start:i],
                        tensor_dtype=resp.tensor_dtype,
                        # per-tensor bytes are unknown here; the batch
                        # total rides the FIRST non-error sub-batch so
                        # autotuner byte accounting stays conserved
                        # across the split
                        payload_bytes=bytes_left,
                        tensor_codec=codecs[start][0],
                        fused_apply=codecs[start][1]))
                    bytes_left = 0
                start = i
        return out

    def construct_response_list(self):
        from ..core.logging import LOG
        from ..ops.messages import ResponseList
        from .messages_adapter import parse_response_json

        if not self._dirty and not self._shutdown_latched and (
                self._stall_check_disable or
                time.monotonic() - self._last_ffi_pass
                < self._stall_warning_s):
            # Nothing added since the last construct and the stall-check
            # interval has not elapsed: the FFI call could only return an
            # empty list. stall_check=False is accurate — the check did
            # not run this cycle (the C++ core's own interval gate would
            # have declined it too).
            return ResponseList()
        self._dirty = False
        self._last_ffi_pass = time.monotonic()
        ptr = self._lib.htpu_negotiator_construct(self._handle)
        try:
            raw = ctypes.string_at(ptr).decode("utf-8")
        finally:
            self._lib.htpu_free(ptr)
        doc = json.loads(raw)
        for warning in doc.get("stall_warnings", []):
            LOG.warning("%s", warning)
        response_list = parse_response_json(doc)
        response_list.responses = self._stamp_codecs(
            response_list.responses)
        return response_list

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.htpu_negotiator_free(handle)
            self._handle = None


class NativeParameterManager:
    """GP/Bayesian autotuner over (fusion threshold, cycle time)."""

    def __init__(self, fusion_bytes: float, cycle_ms: float,
                 fusion_fixed: bool = False, cycle_fixed: bool = False) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native core unavailable: {_load_error}")
        self._lib = lib
        self._handle = lib.htpu_param_manager_new(
            fusion_bytes / (1024.0 * 1024.0), cycle_ms,
            1 if fusion_fixed else 0, 1 if cycle_fixed else 0)

    def update(self, bytes_processed: float, microseconds: float) -> bool:
        """Record a sample window; True when the knobs moved."""
        return bool(self._lib.htpu_param_manager_update(
            self._handle, bytes_processed, microseconds))

    @property
    def fusion_threshold_bytes(self) -> int:
        return int(self._lib.htpu_param_manager_fusion_bytes(self._handle))

    @property
    def cycle_time_ms(self) -> float:
        return self._lib.htpu_param_manager_cycle_ms(self._handle)

    @property
    def best(self) -> dict:
        return {
            "fusion_threshold_bytes": int(
                self._lib.htpu_param_manager_best_fusion_bytes(self._handle)),
            "cycle_time_ms":
                self._lib.htpu_param_manager_best_cycle_ms(self._handle),
            "score_bytes_per_us":
                self._lib.htpu_param_manager_best_score(self._handle),
        }

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.htpu_param_manager_free(handle)
            self._handle = None


class NativeTimelineWriter:
    """Background-thread trace writer; records are preformatted JSON."""

    def __init__(self, path: str) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native core unavailable: {_load_error}")
        self._lib = lib
        self._handle = lib.htpu_timeline_open(path.encode("utf-8"))
        if not self._handle:
            raise OSError(f"cannot open timeline file {path!r}")

    def write(self, record: str) -> None:
        self._lib.htpu_timeline_write(self._handle, record.encode("utf-8"))

    def close(self) -> None:
        if self._handle:
            self._lib.htpu_timeline_close(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
