"""Pure-Python online knob optimizer: the closed loop's "decide" half.

The reference ships a coordinator-resident Bayesian ``parameter_manager``
(``optim/bayesian_optimization``) tuning exactly two knobs — fusion
threshold and cycle time. This module generalizes that loop to every live
knob the repo has grown since (response-cache capacity, wire codec,
metrics interval) with a deliberately simpler optimizer: bounded
coordinate descent / hill climb over discrete knob ladders, scored by
median-of-window collective throughput (bytes/µs — the reference's own
objective, ``parameter_manager.cc:145-171``), with

* a **cooldown** after every move (a just-applied knob reaches the ranks
  one cycle response later, so the first post-move cycles mix
  configurations and must not score),
* a **revert guard**: any move whose measured window regresses past the
  tolerance rolls back to the best-known config — the property that makes
  online exploration safe on a production job, and
* **pinning**: knobs explicitly set via env never move (the reference's
  ``SetValue(..., fixed=true)`` semantics, ``parameter_manager.cc:329``).

Every decision is audited three ways (docs/autotune.md): knob gauges +
retune/revert counters on the obs registry, a JSONL decision log
(``HOROVOD_AUTOTUNE_DECISIONS``, rendered by ``tools/tune_report.py``),
and — applied by the engine — timeline metadata records.

Stdlib-only at module level (plus ``obs.registry``, itself stdlib-only):
the policy must be constructible in launcher/tool processes without jax.
"""

from __future__ import annotations

import statistics
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.registry import registry as _metrics

# Knob names are the shared vocabulary of the whole plane: the policy
# proposes them, the controller applies them, the decision log and the
# knob gauges report them.
KNOB_FUSION = "fusion_threshold_bytes"
KNOB_CYCLE = "cycle_time_ms"
KNOB_CACHE = "cache_capacity"
KNOB_INTERVAL = "metrics_interval_s"
KNOB_CODEC = "codec"
KNOB_SUBBUFFERS = "fusion_subbuffers"
KNOB_FUSED_APPLY = "fused_apply"
# Serving-plane knobs (docs/serving.md): tuned by the driver-resident
# ServingPlane's own policy instance, scored by batch payload throughput.
KNOB_SERVING_BATCH = "serving_batch_max"
KNOB_SERVING_EDGES = "serving_bucket_edges"
# Checkpoint-plane knob (docs/checkpoint.md): how many maybe_commit()
# calls between actual commits, tuned against commit-stall overhead.
KNOB_CKPT_INTERVAL = "ckpt_interval_steps"

# Prometheus gauges are numeric; the codec knob reports this id mapping
# (documented in docs/autotune.md).
CODEC_IDS = {"none": 0, "int8": 1, "fp8": 2, "topk": 3}

_RETUNES = _metrics().counter(
    "horovod_autotune_retunes_total",
    "Knob moves applied by the tuning plane", labels=("knob",))
_REVERTS = _metrics().counter(
    "horovod_autotune_reverts_total",
    "Moves rolled back to the best-known config by the revert guard",
    labels=("knob",))
_DISCARDS = _metrics().counter(
    "horovod_autotune_discards_total",
    "Tolerated-but-not-improving moves rolled back by the hill climb "
    "(strict acceptance: a kept move must improve)", labels=("knob",))
_KNOB_GAUGE = _metrics().gauge(
    "horovod_autotune_knob",
    "Current value of each tuned knob (codec reported as its id: "
    "none=0 int8=1 fp8=2 topk=3)", labels=("knob",))


@dataclass
class Knob:
    """One bounded knob: a discrete value ladder and a cursor on it.

    ``pinned`` knobs participate in the config map (so appliers, gauges,
    and logs always see a complete picture) but are never proposed."""

    name: str
    values: Tuple
    index: int
    pinned: bool = False

    @property
    def current(self):
        return self.values[self.index]

    def in_bounds(self, direction: int) -> bool:
        return 0 <= self.index + direction < len(self.values)


def _ladder(current, candidates: Sequence) -> Tuple[Tuple, int]:
    """Sorted numeric ladder with ``current`` spliced in — the policy must
    START at the live runtime value, or its first 'move' would silently
    change a knob nobody asked it to."""
    values = sorted(set(float(c) for c in candidates) | {float(current)})
    return tuple(values), values.index(float(current))


@dataclass
class Decision:
    """One applied knob change: a move ("retune"), the guard rolling a
    regressing move back ("revert"), or the hill climb dropping a
    tolerated-but-not-improving one ("discard").

    ``config`` is the COMPLETE knob→value map after the decision — the
    applier (controller service / engine) reads values from it without
    needing to know which knob moved."""

    action: str  # "retune" | "revert" | "discard"
    knob: str
    value: object
    score: float
    best_score: float
    config: Dict[str, object] = field(default_factory=dict)


def audit_decision(decision: Decision) -> None:
    """Registry half of the audit trail (shared by both backends): bump
    the retune/revert/discard counter and refresh every knob gauge."""
    fam = {"revert": _REVERTS, "discard": _DISCARDS}.get(
        decision.action, _RETUNES)
    fam.labels(knob=decision.knob).inc()
    for name, value in decision.config.items():
        if name == KNOB_CODEC:
            value = CODEC_IDS.get(str(value), -1)
        _KNOB_GAUGE.labels(knob=name).set(value)


def parse_fault(spec: str) -> Optional[Tuple[str, int]]:
    """``"regress@N"`` → ("regress", N); empty → None; typos fail loudly
    (the chaos-grammar loudness contract).

    The hook replaces REAL scores with a deterministic synthetic pair —
    a flat plateau until the Nth retune, a deep regression after it,
    the plateau again once the guard fired — so the certification is
    judged on the guard's logic, not on the noise floor of whatever
    box runs it (CPU-world scores swing 20x under scheduler load; a
    mere scale factor would let natural regressions fire extra
    reverts)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    kind, sep, arg = spec.partition("@")
    if kind != "regress" or not sep or not arg.isdigit():
        raise ValueError(
            f"bad HOROVOD_AUTOTUNE_FAULT spec {spec!r}; expected "
            f"'regress@N' (force a score regression after the Nth retune "
            f"so the revert guard must fire exactly once)")
    return ("regress", int(arg))


_FAULT_PLATEAU = 1000.0
_FAULT_REGRESSED = 200.0


class TuningPolicy:
    """Median-of-window coordinate descent with a revert guard.

    Drive it with one :meth:`observe` call per completed negotiation
    cycle; it returns a :class:`Decision` whenever the knobs change.
    State machine per scored window:

    1. Fold the window's per-cycle scores to a median.
    2. If the previous window's move regressed past ``tolerance`` vs the
       best-known score: roll back to the best-known config (revert).
    3. If it merely failed to improve: roll back too (discard) — strict
       hill-climb acceptance, because keeping tolerated-but-flat moves
       would let a knob with no measurable effect ping-pong forever,
       and every fusion/capacity/codec ping is a real change that bumps
       the response-cache generation.
    4. Otherwise adopt the config as best, and propose the next
       in-bounds, un-pinned, not-recently-rejected (knob, direction)
       move.
    5. Enter cooldown: the next ``cooldown`` samples are dropped.

    When every candidate move has been rejected the policy idles at the
    best-known config and re-opens exploration after a backoff that
    starts at ``reexplore_windows`` quiet windows and doubles (capped)
    for every exploration round that adopted nothing — online
    conditions drift, and a move that hurt an hour ago may win now, but
    a flat landscape must converge toward idle, not churn at a fixed
    cadence."""

    def __init__(self, knobs: Sequence[Knob], window: int = 5,
                 cooldown: int = 5, tolerance: float = 0.05,
                 decision_sink: Optional[Callable[[dict], None]] = None,
                 fault: str = "", reexplore_windows: int = 3,
                 propose_gate=None) -> None:
        if not knobs:
            raise ValueError("TuningPolicy needs at least one knob")
        # Evidence gate (docs/tensorwatch.md): a duck-typed object with
        # ``allows(knob, value) -> bool`` and ``evidence(knob, value) ->
        # dict|None``. Candidates a gate refuses are SKIPPED, not
        # rejected — the numerics observatory may certify them later and
        # the proposal then proceeds; admitted moves carry the gate's
        # evidence record into the JSONL decision log. None (the
        # default, and every world without the observatory) keeps the
        # pre-gate behavior byte-identically.
        self._propose_gate = propose_gate
        self._knobs: Dict[str, Knob] = {k.name: k for k in knobs}
        self._order = [k.name for k in knobs]
        self._window = max(int(window), 1)
        self._cooldown = max(int(cooldown), 0)
        self._tolerance = float(tolerance)
        self._sink = decision_sink
        self._fault = parse_fault(fault)
        self._fault_done = False
        self._reexplore = max(int(reexplore_windows), 1)
        self._samples: List[float] = []
        self._cooldown_left = 0
        self._best_score: Optional[float] = None
        self._best_config: Dict[str, int] = {}  # name -> ladder index
        self._last_move: Optional[Tuple[str, int]] = None  # (name, dir)
        self._rejected: set = set()  # {(name, dir)} since last improvement
        self._cursor = 0
        self._idle_windows = 0
        # Re-explore with exponential backoff: a fully-explored flat
        # landscape must converge toward idle (each exploration burst is
        # real knob churn — and cache-generation bumps), not repeat at a
        # fixed cadence forever. Any adopted improvement resets it.
        self._backoff = self._reexplore
        self._improved_since_explore = False
        self.retunes = 0
        self.reverts = 0
        self.discards = 0
        self._emit({"action": "init", "config": self.config(),
                    "window": self._window, "cooldown": self._cooldown,
                    "tolerance": self._tolerance})

    # -- introspection (the Autotuner facade's CSV columns) -------------------

    def config(self) -> Dict[str, object]:
        return {name: self._knobs[name].current for name in self._order}

    def value(self, name: str):
        return self._knobs[name].current

    @property
    def fusion_threshold_bytes(self) -> int:
        return int(self._knobs[KNOB_FUSION].current)

    @property
    def cycle_time_ms(self) -> float:
        return float(self._knobs[KNOB_CYCLE].current)

    @property
    def best(self) -> dict:
        best_cfg = {name: self._knobs[name].values[i]
                    for name, i in self._best_config.items()} \
            if self._best_config else self.config()
        return {"config": best_cfg,
                "score_bytes_per_us": self._best_score,
                "retunes": self.retunes, "reverts": self.reverts}

    # -- the loop --------------------------------------------------------------

    def observe(self, bytes_processed: float,
                microseconds: float) -> Optional[Decision]:
        if bytes_processed <= 0 or microseconds <= 0:
            return None
        score = bytes_processed / microseconds
        if self._fault is not None:
            # deterministic test hook (see parse_fault): a flat synthetic
            # plateau, regressed once after the Nth retune until the
            # guard fires — real (noisy) scores never reach the guard
            score = _FAULT_REGRESSED if (
                not self._fault_done and self.retunes >= self._fault[1]
            ) else _FAULT_PLATEAU
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return None
        self._samples.append(score)
        if len(self._samples) < self._window:
            return None
        median = statistics.median(self._samples)
        self._samples.clear()
        return self._decide(median)

    def _decide(self, score: float) -> Optional[Decision]:
        if self._best_score is None:
            # baseline window: the live config IS the best known so far
            self._best_score = score
            self._best_config = self._snapshot()
            return self._propose(score)
        if self._last_move is not None:
            if score < self._best_score * (1.0 - self._tolerance):
                return self._revert(score)
            if score <= self._best_score:
                # Strict acceptance: a kept move must IMPROVE. Keeping
                # tolerated-but-flat moves let a knob whose effect stays
                # inside the tolerance band ping-pong forever — and every
                # fusion/capacity/codec ping was a REAL change that
                # bumped the response-cache generation, perpetually
                # clearing the PR-3 warm bypass. Discard instead: restore
                # best-known, reject the direction, converge to idle.
                return self._discard(score)
        if score > self._best_score:
            self._best_score = score
            self._best_config = self._snapshot()
            self._rejected.clear()  # a better region re-opens exploration
            self._improved_since_explore = True
            self._backoff = self._reexplore
        elif self._last_move is None and \
                self._snapshot() == self._best_config:
            # Online drift re-anchor: the best-known config ITSELF scores
            # lower now (workload change, not a failed move — there is no
            # move to blame). Without this, every future move would be
            # judged against a stale, unreachable score and revert
            # forever, freezing the policy out of the new landscape.
            self._best_score = score
        self._last_move = None
        return self._propose(score)

    def _snapshot(self) -> Dict[str, int]:
        return {name: knob.index for name, knob in self._knobs.items()}

    def _rollback(self, score: float, action: str) -> Decision:
        """Restore the best-known config and reject the failed direction.
        ``action`` distinguishes the revert GUARD (the move regressed past
        tolerance) from a hill-climb discard (tolerated but flat) in every
        audit surface."""
        name, direction = self._last_move
        self._rejected.add((name, direction))
        for knob_name, index in self._best_config.items():
            self._knobs[knob_name].index = index
        self._last_move = None
        self._cooldown_left = self._cooldown
        decision = Decision(action=action, knob=name,
                            value=self._knobs[name].current, score=score,
                            best_score=self._best_score,
                            config=self.config())
        self._audit(decision)
        return decision

    def _revert(self, score: float) -> Decision:
        self.reverts += 1
        self._fault_done = True  # the hook proved the guard; plateau resumes
        return self._rollback(score, "revert")

    def _discard(self, score: float) -> Decision:
        self.discards += 1
        return self._rollback(score, "discard")

    def _propose(self, score: float) -> Optional[Decision]:
        candidates = []
        n = len(self._order)
        for step in range(n):
            name = self._order[(self._cursor + step) % n]
            knob = self._knobs[name]
            if knob.pinned:
                continue
            for direction in (1, -1):
                if not knob.in_bounds(direction) or \
                        (name, direction) in self._rejected:
                    continue
                if self._propose_gate is not None and \
                        not self._propose_gate.allows(
                            name, knob.values[knob.index + direction]):
                    # evidence-gated candidate (the lossy codec): not
                    # yet certified — skip without rejecting, so a
                    # later certification re-opens the move
                    continue
                candidates.append((name, direction))
            if candidates:
                break
        if not candidates:
            # fully explored from here: idle at best-known; re-open after
            # the backoff, doubling it whenever a whole exploration round
            # adopted nothing (capped — online drift still gets retried)
            self._idle_windows += 1
            if self._idle_windows >= self._backoff:
                self._idle_windows = 0
                if not self._improved_since_explore:
                    self._backoff = min(self._backoff * 2, 96)
                self._improved_since_explore = False
                self._rejected.clear()
            return None
        self._idle_windows = 0
        name, direction = candidates[0]
        self._cursor = (self._order.index(name) + 1) % n
        knob = self._knobs[name]
        knob.index += direction
        self._last_move = (name, direction)
        self._cooldown_left = self._cooldown
        self.retunes += 1
        decision = Decision(action="retune", knob=name, value=knob.current,
                            score=score, best_score=self._best_score,
                            config=self.config())
        evidence = None
        if self._propose_gate is not None:
            # an evidence-gated admit ships the measured record that
            # justified it into the decision log (docs/tensorwatch.md)
            evidence = self._propose_gate.evidence(name, knob.current)
        self._audit(decision, evidence=evidence)
        return decision

    def evidence_revert(self, name: str, value,
                        evidence: Optional[dict] = None
                        ) -> Optional[Decision]:
        """Forced revert on collapsed evidence (docs/tensorwatch.md):
        the numerics observatory measured an in-flight SNR collapse on
        an admitted lossy codec, so the move's justification no longer
        holds — roll the knob back to ``value`` through the same
        bookkeeping the best-known-config guard uses (best-config
        snapshot updated, the lossy direction rejected, cooldown
        entered), audited as a ``revert`` carrying the evidence record.
        No-op (None) when the knob is absent or already at ``value``."""
        knob = self._knobs.get(name)
        if knob is None or knob.current == value or \
                value not in knob.values:
            return None
        old_index = knob.index
        knob.index = knob.values.index(value)
        self._best_config[name] = knob.index
        self._rejected.add((name, 1 if old_index > knob.index else -1))
        self._last_move = None
        self._samples.clear()
        self._cooldown_left = self._cooldown
        self.reverts += 1
        score = self._best_score if self._best_score is not None else 0.0
        decision = Decision(action="revert", knob=name, value=value,
                            score=score, best_score=score,
                            config=self.config())
        self._audit(decision, evidence=evidence)
        return decision

    def _audit(self, decision: Decision,
               evidence: Optional[dict] = None) -> None:
        audit_decision(decision)
        record = {"action": decision.action, "knob": decision.knob,
                  "value": decision.value, "score": decision.score,
                  "best_score": decision.best_score,
                  "config": decision.config}
        if evidence is not None:
            record["evidence"] = evidence
        self._emit(record)

    def _emit(self, record: dict) -> None:
        if self._sink is not None:
            self._sink(record)


def default_knobs(cfg, extended: bool = False) -> List[Knob]:
    """The live knob set for a Config (docs/autotune.md knob table).

    The classic pair is always present (pinned when its env was set
    explicitly). ``extended`` adds the Python-controller-only knobs —
    response-cache capacity, codec, metrics interval — each gated on its
    subsystem actually being active and its own pin rules; the native
    controller wire cannot carry them (the cache-bit / metrics-RPC
    degrade pattern)."""
    knobs: List[Knob] = []
    mib = 1024 * 1024
    values, index = _ladder(cfg.fusion_threshold_bytes,
                            [m * mib for m in (1, 2, 4, 8, 16, 32, 64, 128)])
    knobs.append(Knob(KNOB_FUSION, values, index,
                      pinned=cfg.fusion_threshold_explicit))
    values, index = _ladder(cfg.cycle_time_ms,
                            [0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0])
    knobs.append(Knob(KNOB_CYCLE, values, index,
                      pinned=cfg.cycle_time_explicit))
    if extended and cfg.cache_capacity > 0:
        values, index = _ladder(cfg.cache_capacity,
                                [128, 256, 512, 1024, 2048, 4096])
        knobs.append(Knob(KNOB_CACHE, values, index,
                          pinned=cfg.cache_capacity_explicit))
    if extended:
        # Sub-buffer flush pipelining (docs/tensor-fusion.md): how many
        # generation-ordered sub-buffers each cycle tick cuts into — the
        # compute/collective overlap depth. Applied by the ENGINE off the
        # tuned_knobs piggyback (the metrics-interval pattern); ranks arm
        # the pipeline on the first >= 2 value. Numerics-neutral (every
        # tensor's reduction is unchanged, only the batching moves), so
        # no consent gate like the codec's.
        values, index = _ladder(cfg.fusion_subbuffers, [1, 2, 4, 8])
        knobs.append(Knob(KNOB_SUBBUFFERS, values, index,
                          pinned=cfg.fusion_subbuffers_explicit))
    if extended and cfg.fused_apply:
        # Fused reduce+apply execution strategy (docs/tensor-fusion.md
        # §fused apply): 1 = the single reduce+apply program, 0 = the
        # reduce-then-apply split. Present only when the operator armed
        # the plane (HOROVOD_FUSED_APPLY=1 — the env opts into the
        # PLANE, not the strategy, so the knob is never pinned by it).
        # Numerics-exact both ways — the two strategies share the
        # ApplyRule math bit-for-bit — so no consent gate like the
        # codec's; applied by the engine off the tuned_knobs piggyback.
        knobs.append(Knob(KNOB_FUSED_APPLY, (0, 1), 1, pinned=False))
    if extended and cfg.metrics_port > 0:
        # present (pinned) even when the interval was set explicitly, so
        # the config map / gauges / decision log can distinguish "pinned
        # at X" from "no metrics plane to manage"; absent entirely when
        # the exposition server is off — there is no knob to report
        values, index = _ladder(cfg.metrics_interval_s,
                                [0.5, 1.0, 2.0, 5.0, 10.0])
        knobs.append(Knob(KNOB_INTERVAL, values, index,
                          pinned=cfg.metrics_interval_explicit))
    if extended:
        # Lossy knob: pinned to the session default unless the operator
        # explicitly allowlisted candidates (HOROVOD_AUTOTUNE_CODECS) —
        # the tuner must never trade training numerics for wire bytes
        # without consent. Typos fail loudly (the chaos-grammar
        # contract): silently dropping "in8" would pin the knob while
        # the operator believes they consented to int8 exploration.
        unknown = [c for c in cfg.autotune_codecs if c not in CODEC_IDS]
        if unknown:
            raise ValueError(
                f"bad HOROVOD_AUTOTUNE_CODECS entry "
                f"{'/'.join(unknown)!r}; known codecs: "
                f"{'/'.join(sorted(CODEC_IDS))}")
        current = "none"
        ladder = [current] + [c for c in cfg.autotune_codecs
                              if c != current]
        knobs.append(Knob(KNOB_CODEC, tuple(ladder), 0,
                          pinned=len(ladder) == 1))
    return knobs


def serving_knobs(batch_max: int, edge_ratio: float,
                  batch_max_explicit: bool = False,
                  edges_explicit: bool = False) -> List[Knob]:
    """The serving plane's knob set (docs/serving.md): largest packed
    batch and the padding-bucket edge growth ratio. Both are
    numerics-neutral — padding rows are sliced off before any ticket
    completes and packing never changes a request's row values — so
    neither carries a consent gate like the codec's. The usual pin rule
    applies: a knob whose env (HOROVOD_SERVING_BATCH_MAX /
    HOROVOD_SERVING_BUCKET_EDGES) was set explicitly never moves."""
    knobs: List[Knob] = []
    values, index = _ladder(batch_max, [1, 2, 4, 8, 16, 32, 64, 128])
    knobs.append(Knob(KNOB_SERVING_BATCH, values, index,
                      pinned=batch_max_explicit))
    values, index = _ladder(edge_ratio, [2.0, 4.0])
    knobs.append(Knob(KNOB_SERVING_EDGES, values, index,
                      pinned=edges_explicit))
    return knobs


def ckpt_interval_knob(current: int, explicit: bool = False) -> Knob:
    """The checkpoint plane's commit cadence knob (docs/checkpoint.md):
    how many ``State.maybe_commit()`` calls elapse between actual
    commits. Numerics-neutral — skipping a commit changes durability
    (how much progress a relaunch replays), never training math — so no
    consent gate. The usual pin rule applies: an interval set explicitly
    via ``HOROVOD_CKPT_INTERVAL_STEPS`` never moves."""
    values, index = _ladder(current, [1, 2, 5, 10, 25, 50, 100])
    return Knob(KNOB_CKPT_INTERVAL, values, index, pinned=explicit)
