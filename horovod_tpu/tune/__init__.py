"""Closed-loop tuning plane: online knob optimization + straggler action.

The first subsystem that CLOSES the measure→decide→act loop the last four
PRs built the halves of (docs/autotune.md): the obs plane (PR 5/6)
measures — cycle-latency histograms, wire bytes, cache hit/miss, per-rank
arrival-spread blame; this package decides and acts:

* :mod:`.policy` — the pure-Python optimizer behind ``HOROVOD_AUTOTUNE=1``
  (``ops/autotuner.py`` keeps the native GP as an opt-in backend behind
  the same interface): bounded coordinate descent over fusion threshold,
  cycle time, response-cache capacity, codec, and metrics interval, with
  median-of-window scoring, per-move cooldown, and a best-known-config
  revert guard. Decisions ride the existing control wire (piggybacked on
  ``ResponseList``/``CacheHitAck``), fusion/codec retunes bump the
  response-cache generation (docs/response-cache.md), and every decision
  is audited (registry counters + knob gauges, JSONL decision log,
  timeline metadata).
* :mod:`.detector` — persistent-straggler mitigation: PR 6's per-cycle
  blame attribution folded over a sliding window with the same two-gated
  verdict; a persistent dominant rank becomes an eviction advisory to the
  elastic driver (``HOROVOD_STRAGGLER_EVICT=advisory|enforce|off``;
  enforce blacklists the slot and relaunches through the PR-2 path).
"""

from __future__ import annotations

from .detector import StragglerDetector, advise_elastic_driver  # noqa: F401
from .policy import (  # noqa: F401 - public surface (docs/autotune.md)
    CODEC_IDS,
    Decision,
    Knob,
    TuningPolicy,
    audit_decision,
    default_knobs,
    parse_fault,
)

__all__ = [
    "CODEC_IDS",
    "Decision",
    "Knob",
    "StragglerDetector",
    "TuningPolicy",
    "advise_elastic_driver",
    "audit_decision",
    "default_knobs",
    "parse_fault",
]
