"""Persistent-straggler detector: the closed loop's "act" half for ranks.

PR 6 built the diagnosis — the coordinator charges each cycle's arrival
spread to its last arriver (``horovod_straggler_blame_seconds_total``) and
``straggler_report`` folds a TWO-GATED dominant-rank verdict out of it.
Nothing acted on that verdict; the Horovod paper (1802.05799) names
straggler handling as the hardest operational problem precisely because a
persistent straggler silently taxes every healthy rank's step time.

This detector folds the same per-cycle attribution stream over a SLIDING
window and applies the same two gates *persistently*:

* the dominant rank must own more than ``blame_share`` (default 0.5) of
  the window's blame SECONDS (counts alone would let a rank late by
  microseconds every cycle outrank one late by 50 ms on a tenth of them —
  the PR 6 lesson), and
* the window's mean attributed spread must exceed ``min_spread_s``
  (below the floor the coordinator is measuring scheduler jitter, and
  naming a "straggler" would evict a healthy host), and
* at least ``min_cycles`` cycles were attributed inside the window (a
  handful of samples is noise, not persistence).

A verdict is surfaced as an EVICTION ADVISORY: counted on the obs
registry, logged, and pushed best-effort to the elastic driver's health
service (``("advise_evict", epoch, rank, info)``). The driver decides
what to do with it — record it (``HOROVOD_STRAGGLER_EVICT=advisory``) or
blacklist the slot and relaunch through the PR-2 elastic path
(``enforce``). Refire for the same rank is suppressed until a full window
has elapsed, so one slow patch produces one advisory, not a storm.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..core import config as _config
from ..core.logging import LOG
from ..obs.registry import registry as _metrics
from ..obs.tracing import DEFAULT_MIN_SPREAD_S

MODES = ("off", "advisory", "enforce")

_ADVISORIES = _metrics().counter(
    "horovod_straggler_eviction_advisories_total",
    "Persistent-straggler eviction advisories raised by the coordinator's "
    "detector", labels=("rank",))


class StragglerDetector:
    """Sliding-window fold of the coordinator's per-cycle attribution.

    Fed inline from the cycle bookkeeping point (``ControllerService``)
    — one ``observe_cycle(last_rank, spread_s)`` per fully-observed
    cycle; O(1) amortized, so the hot path pays a deque append and two
    running sums."""

    def __init__(self, size: int, mode: str = "advisory",
                 window_s: float = 30.0,
                 min_spread_s: float = DEFAULT_MIN_SPREAD_S,
                 min_cycles: int = 20,
                 blame_share: float = 0.5) -> None:
        if mode not in MODES:
            raise ValueError(
                f"bad HOROVOD_STRAGGLER_EVICT mode {mode!r}; expected one "
                f"of {'/'.join(MODES)}")
        self.mode = mode
        self._size = size
        self._window_s = max(float(window_s), 0.1)
        self._min_spread_s = float(min_spread_s)
        self._min_cycles = max(int(min_cycles), 1)
        self._blame_share = float(blame_share)
        self._events: Deque[Tuple[float, int, float]] = deque()
        self._blame: Dict[int, float] = {}
        self._spread_sum = 0.0
        self._last_fire: Dict[int, float] = {}  # rank -> monotonic ts
        self._fire_counts: Dict[int, int] = {}
        self.advisories: Dict[int, dict] = {}

    @classmethod
    def from_config(cls, cfg, size: int) -> "StragglerDetector":
        return cls(size, mode=cfg.straggler_evict,
                   window_s=cfg.straggler_window_s,
                   min_cycles=cfg.straggler_min_cycles)

    def _prune(self, now: float) -> None:
        horizon = now - self._window_s
        while self._events and self._events[0][0] < horizon:
            _, rank, spread = self._events.popleft()
            self._blame[rank] -= spread
            self._spread_sum -= spread

    def observe_cycle(self, last_rank: int,
                      spread_s: float) -> Optional[dict]:
        """Feed one attributed cycle; returns an advisory dict when the
        persistent verdict fires for a rank (rate-limited per window)."""
        now = time.monotonic()
        self._events.append((now, last_rank, spread_s))
        self._blame[last_rank] = self._blame.get(last_rank, 0.0) + spread_s
        self._spread_sum += spread_s
        self._prune(now)
        cycles = len(self._events)
        if cycles < self._min_cycles or self._spread_sum <= 0:
            return None
        mean_spread = self._spread_sum / cycles
        if mean_spread <= self._min_spread_s:
            return None  # gate 2: sub-floor spreads are scheduler jitter
        top_rank = max(self._blame, key=self._blame.get)
        share = self._blame[top_rank] / self._spread_sum
        if share <= self._blame_share:
            return None  # gate 1: no dominant owner of the blame seconds
        last = self._last_fire.get(top_rank)
        if last is not None and now - last < self._window_s:
            return None  # already advised for this window
        self._last_fire[top_rank] = now
        seq = self._fire_counts.get(top_rank, 0) + 1
        self._fire_counts[top_rank] = seq
        # seq distinguishes a REFIRE (the rank is still a straggler one
        # window later) from a redelivered copy: the elastic driver's
        # per-rank store overwrites, so without it a persistent straggler
        # would count exactly once per attempt no matter how long it lasts
        info = {"rank": int(top_rank), "seq": seq, "blame_share": share,
                "mean_spread_s": mean_spread, "cycles": cycles,
                "window_s": self._window_s, "mode": self.mode}
        self.advisories[int(top_rank)] = info
        _ADVISORIES.labels(rank=top_rank).inc()
        LOG.warning(
            "persistent straggler: rank %d owns %.0f%% of the blame "
            "seconds over the last %.1fs (%d cycles, mean spread %.1fms) "
            "— raising an eviction advisory (%s mode)", top_rank,
            100 * share, self._window_s, cycles, 1e3 * mean_spread,
            self.mode)
        advise_elastic_driver(info)
        return info


def advise_elastic_driver(info: dict) -> None:
    """Best-effort push of an eviction advisory to the elastic driver's
    health service, on a short-lived daemon thread — the advisory must
    never add wire latency to the cycle path that detected it, and a
    missing driver (plain ``runner.run``, no elastic plane) just means
    nobody can act; the registry counter and log line remain."""
    port = os.environ.get(_config.HOROVOD_ELASTIC_PORT)
    if not port:
        return
    addr = (os.environ.get(_config.HOROVOD_ELASTIC_ADDR, "127.0.0.1"),
            int(port))
    epoch = int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))

    def _push() -> None:
        from ..runner.network import BasicClient, default_secret

        client = None
        try:
            client = BasicClient(addr, secret=default_secret(),
                                 timeout_s=5.0, attempts=3)
            client.request(("advise_evict", epoch, info["rank"],
                            dict(info)))
        except Exception as exc:  # noqa: BLE001 - advisory only
            LOG.warning("eviction advisory for rank %s could not reach "
                        "the elastic driver: %s", info.get("rank"), exc)
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

    threading.Thread(target=_push, name="horovod-evict-advisory",
                     daemon=True).start()
