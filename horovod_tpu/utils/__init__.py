"""Auxiliary subsystems: timeline tracing, helpers (SURVEY §5)."""

from .timeline import Timeline

__all__ = ["Timeline"]
