"""Horovod Timeline: Chrome-tracing JSON of the eager collective lifecycle.

Rebuild of ``horovod/common/timeline.{h,cc}`` (SURVEY §5.1). Same artifact and
phase vocabulary: per-tensor NEGOTIATE_<OP> span while ranks agree, <OP>
top-level span while the collective runs, nested activity spans
(MEMCPY_IN_FUSION_BUFFER / EXECUTE / MEMCPY_OUT_FUSION_BUFFER), and optional
CYCLE_START instants (``HOROVOD_TIMELINE_MARK_CYCLES``). Same concurrency
design: the hot path only enqueues records; a dedicated writer thread owns
file I/O (the reference uses a boost lock-free SPSC queue feeding
``TimelineWriter``, ``timeline.h:45-73``; a ``queue.SimpleQueue`` plays that
role here). Written only where enabled — the engine enables it on rank 0,
as the reference does (``operations.cc:1825-1829``).

On-device time is not visible from the host path by design; for kernel-level
traces point ``jax.profiler.start_trace`` at the same run (SURVEY §5.1 TPU
note).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Optional

_PHASE_NEGOTIATE = "NEGOTIATE_"
CYCLE_NAME = "CYCLE_START"
# Distributed-tracing metadata records (docs/tracing.md): one
# TRACE_META per file identifies the rank/world the spans belong to;
# CLOCK_SYNC records carry the min-RTT-filtered offset-to-rank-0 the
# merge tool uses to fold per-rank files onto one corrected timebase.
TRACE_META = "horovod_trace_meta"
CLOCK_SYNC = "horovod_clock_sync"
# Closed-loop tuning plane (docs/autotune.md): one AUTOTUNE metadata
# record per applied knob change on each recording rank, so a trace
# shows WHEN the world's knobs moved next to the cycles they reshaped.
AUTOTUNE = "horovod_autotune"
# Data-plane integrity plane (docs/integrity.md): one INTEGRITY metadata
# record per sentry trip (step ordinal, policy, kind, tensors), so a
# trace shows exactly WHICH batch a skip/zero verdict neutralized.
INTEGRITY = "horovod_integrity"

# Observability plane (docs/metrics.md): events dropped after close().
# The drop always warned; counting it too makes a truncated trace
# visible on the registry / tools/metrics_summary.py instead of only in
# a log line nobody scrapes (docs/blackbox.md satellite).
FAMILY_DROPPED_EVENTS = "horovod_timeline_dropped_events_total"


def _dropped_counter():
    """Lazy registration (this module stays stdlib-first; the registry
    import is deferred exactly like obs/tracing's gauges)."""
    from ..obs.registry import registry as _metrics

    return _metrics().counter(
        FAMILY_DROPPED_EVENTS,
        "Timeline events that arrived after close() and were dropped "
        "(the written trace is truncated relative to the job)")


def rank_timeline_path(path: str, rank: int) -> str:
    """Per-rank artifact name under ``HOROVOD_TIMELINE_ALL_RANKS=1``:
    ``<base>.rank<N><ext>`` so ``tools/trace_merge.py`` can glob the
    world's files from the configured base path. Plain ``HOROVOD_TIMELINE``
    (rank 0 only) keeps the unsuffixed reference name."""
    if path.endswith(".json"):
        return f"{path[:-len('.json')]}.rank{rank}.json"
    return f"{path}.rank{rank}"


class Timeline:
    """Event producer + background writer. Thread-safe; cheap when disabled."""

    def __init__(self, path: str = "", mark_cycles: bool = False) -> None:
        self._path = path
        self._mark_cycles = mark_cycles
        self._queue: "queue.SimpleQueue[Optional[dict]]" = queue.SimpleQueue()
        self._tids: dict = {}
        self._lock = threading.Lock()
        self._writer: Optional[threading.Thread] = None
        self._closed = False
        self._drop_warned = False
        self._native = None
        if path:
            self._native = self._try_native(path)
            if self._native is None:
                self._writer = threading.Thread(
                    target=self._write_loop, name="horovod-timeline",
                    daemon=True)
                self._writer.start()

    @staticmethod
    def _try_native(path: str):
        """Prefer the C++ writer thread (``cc/timeline_writer.cc``), the
        direct analog of the reference's TimelineWriter; the Python thread
        below is the fallback when the native core isn't built."""
        import os

        from ..core.config import HOROVOD_NATIVE_CORE

        if os.environ.get(HOROVOD_NATIVE_CORE, "1") == "0":
            return None
        try:
            from ..cc import NativeTimelineWriter, available

            if available():
                return NativeTimelineWriter(path)
        except Exception:  # noqa: BLE001 - fall back to the Python writer
            return None
        return None

    @property
    def enabled(self) -> bool:
        return bool(self._path)

    # -- hot-path producers ---------------------------------------------------

    def _ts_us(self) -> float:
        return time.monotonic_ns() / 1e3

    def _emit(self, record: dict) -> None:
        if self._closed:
            # Dropped LOUDLY, never written: the file was terminated by
            # close() (and the native writer's handle freed — a late
            # write there is a use-after-free). Late emitters are bugs in
            # shutdown ordering (a finalizer or metrics bridge outliving
            # the engine), so say so once instead of corrupting the
            # artifact or silently queueing records nobody will drain —
            # and COUNT every drop, so a truncated trace shows on the
            # registry, not only in a log line nobody scrapes.
            if self._path:
                try:
                    _dropped_counter().inc()
                except Exception:  # noqa: BLE001 - audit must not raise
                    pass
                if not self._drop_warned:
                    self._drop_warned = True
                    import logging

                    logging.getLogger("horovod_tpu").warning(
                        "timeline event %r arrived after close(); "
                        "dropping it (and any later ones) instead of "
                        "writing to the closed trace",
                        record.get("name", record.get("ph")))
            return
        if self._native is not None:
            self._native.write(json.dumps(record))
        elif self._path:
            self._queue.put(record)

    def _tid(self, tensor_name: str) -> int:
        # The reference gives each tensor its own timeline "thread" row.
        with self._lock:
            tid = self._tids.get(tensor_name)
            if tid is None:
                tid = len(self._tids) + 1
                self._tids[tensor_name] = tid
                self._emit({
                    "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                    "args": {"name": tensor_name},
                })
            return tid

    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        """Tensor submitted; ranks not yet agreed (``timeline.cc:184-214``)."""
        self._emit({"name": _PHASE_NEGOTIATE + op_name.upper(), "ph": "B",
                    "pid": 0, "tid": self._tid(tensor_name),
                    "ts": self._ts_us()})

    def negotiate_end(self, tensor_name: str,
                      args: Optional[dict] = None) -> None:
        """``args`` (docs/tracing.md): the engine stamps the cycle ordinal
        and cache generation on the E record — every rank participates in
        every negotiation cycle exactly once and in order, so the ordinal
        correlates the same span across per-rank trace files without any
        shared clock (Chrome tracing merges E-record args into the span)."""
        record = {"ph": "E", "pid": 0, "tid": self._tid(tensor_name),
                  "ts": self._ts_us()}
        if args:
            record["args"] = dict(args)
        self._emit(record)

    def start(self, tensor_name: str, op_name: str,
              args: Optional[dict] = None) -> None:
        """Collective execution begins (top-level span, ``timeline.cc:230``).
        ``args``: cycle-correlation stamps, as on ``negotiate_end``."""
        record = {"name": op_name.upper(), "ph": "B", "pid": 0,
                  "tid": self._tid(tensor_name), "ts": self._ts_us()}
        if args:
            record["args"] = dict(args)
        self._emit(record)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        self._emit({"name": activity, "ph": "B", "pid": 0,
                    "tid": self._tid(tensor_name), "ts": self._ts_us()})

    def activity_end(self, tensor_name: str) -> None:
        self._emit({"ph": "E", "pid": 0, "tid": self._tid(tensor_name),
                    "ts": self._ts_us()})

    def end(self, tensor_name: str, shape: Optional[tuple] = None) -> None:
        args = {"shape": list(shape)} if shape is not None else {}
        self._emit({"ph": "E", "pid": 0, "tid": self._tid(tensor_name),
                    "ts": self._ts_us(), "args": args})

    def mark_cycle_start(self) -> None:
        """Optional cycle instants (``operations.cc:2042-2045``)."""
        if self._mark_cycles:
            self._emit({"name": CYCLE_NAME, "ph": "i", "pid": 0, "tid": 0,
                        "ts": self._ts_us(), "s": "g"})

    def counter(self, name: str, values: dict) -> None:
        """Chrome-tracing counter track (ph "C"): numeric series rendered
        as stacked area charts. The engine emits one per negotiation cycle
        for the response-cache bypass — hit/miss cycle totals and
        per-cycle negotiation wire bytes — so a bypass regression shows in
        the trace instead of silently re-inflating the control plane
        (docs/response-cache.md). The observability plane's
        ``obs.TimelineBridge`` emits every changed metrics-registry family
        through here as ``metrics/<family>`` tracks (docs/metrics.md).
        After ``close()`` counter events are dropped loudly, never written
        to the terminated file."""
        self._emit({"name": name, "ph": "C", "pid": 0, "tid": 0,
                    "ts": self._ts_us(), "args": dict(values)})

    def meta(self, name: str, args: dict) -> None:
        """File-scoped metadata record (Chrome ph "M"): the distributed-
        tracing plane writes one ``TRACE_META`` per file (rank, size,
        epoch) and a ``CLOCK_SYNC`` per alignment handshake (offset to
        rank 0, filter RTT), which is how ``tools/trace_merge.py`` knows
        which lane a file is and how to correct its timebase without any
        side-channel manifest (docs/tracing.md)."""
        self._emit({"name": name, "ph": "M", "pid": 0, "tid": 0,
                    "ts": self._ts_us(), "args": dict(args)})

    # -- writer ---------------------------------------------------------------

    def _write_loop(self) -> None:
        # Write the real file incrementally so it is inspectable mid-run,
        # like the reference writer; Chrome tracing tolerates a truncated
        # array, and close() terminates it properly.
        with open(self._path, "w", encoding="utf-8") as fh:
            fh.write("[\n")
            while True:
                record = self._queue.get()
                if record is None:
                    break
                fh.write(json.dumps(record) + ",\n")
                fh.flush()
            fh.write("{}]\n")

    def close(self) -> None:
        self._closed = True  # before the writer teardown: an emit racing
        # close must drop rather than enqueue behind the sentinel
        if self._native is not None:
            self._native.close()
            self._native = None
        if self._writer is not None:
            self._queue.put(None)
            self._writer.join(timeout=5.0)
            self._writer = None
