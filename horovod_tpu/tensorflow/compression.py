"""Gradient compression for the TensorFlow front-end.

Rebuild of ``horovod/tensorflow/compression.py`` (the 74-line none/fp16
pair): compression happens in TF land — cast down before the wire, cast
back after — so the engine only ever sees the compressed payload. bf16 is
added beyond the reference because it is the native TPU wire format.
"""

from __future__ import annotations


class NoneCompressor:
    """Default: no-op (``compression.py:20-33``)."""

    codec_name = "none"
    quantized = False

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    """Cast float tensors to fp16 for the wire (``compression.py:36-64``)."""

    _wire_dtype = "float16"
    codec_name = "fp16"
    quantized = False

    @classmethod
    def compress(cls, tensor):
        import tensorflow as tf

        ctx = tensor.dtype
        if tensor.dtype.is_floating:
            tensor = tf.cast(tensor, getattr(tf, cls._wire_dtype))
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        import tensorflow as tf

        if ctx is not None and ctx.is_floating and tensor.dtype != ctx:
            tensor = tf.cast(tensor, ctx)
        return tensor


class BF16Compressor(FP16Compressor):
    """bf16 wire format — same exponent range as f32, the TPU-native choice
    (extension beyond the reference's fp16)."""

    _wire_dtype = "bfloat16"
    codec_name = "bf16"


class Int8Compressor(NoneCompressor):
    """Block-quantized int8 wire (EQuARX): compression happens INSIDE the
    engine's fused collective — shared per-block scales need a cross-rank
    max exchange, impossible as a local pre-cast — so the TF-side hooks
    are identity and this class is the negotiation tag the ops layer
    forwards (``ops._submit`` reads ``codec_name``/``quantized``). The
    reduced result comes back in the original float dtype."""

    codec_name = "int8"
    quantized = True


class FP8Compressor(Int8Compressor):
    """fp8-e4m3 wire variant of the quantized codec (backend-gated)."""

    codec_name = "fp8"


class Compression:
    """Namespace matching the reference surface (``compression.py:67-74``;
    ``int8``/``fp8`` extend it with the EQuARX quantized wire)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor
