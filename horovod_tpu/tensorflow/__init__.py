"""TensorFlow (TF2) front-end on the TPU-native engine.

Rebuild of ``horovod/tensorflow/__init__.py`` (the reference's largest user
surface: ``allreduce`` :46-93, ``broadcast_global_variables`` :95,
``broadcast_variables`` :105, ``BroadcastGlobalVariablesHook`` :117-148,
``DistributedOptimizer`` :151-249, ``DistributedGradientTape`` :252-326)
without the custom-op ``.so``: eager tensors hand off to the shared
collective engine via numpy (zero-copy for CPU tensors), and code inside
``tf.function`` submits through ``tf.py_function`` with names bound at
TRACE time — the controller's named-tensor negotiation then tolerates any
runtime execution order, exactly the property the reference's coordinator
provides for its async custom ops.

Sparse gradients (``tf.IndexedSlices``) use the reference's
2×allgather construction (``tensorflow/__init__.py:72-83``).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .. import basics
from .. import ops as _ops
from ..basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from .compression import Compression

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "is_initialized", "mpi_threads_supported",
    "allreduce", "allgather", "broadcast",
    "broadcast_variables", "broadcast_global_variables",
    "BroadcastGlobalVariablesHook", "DistributedOptimizer",
    "DistributedGradientTape", "Compression",
]

_name_lock = threading.Lock()
_name_counter = 0

# The XLA compile fence, quoted verbatim by XLA's unsupported-op error
# (load-bearing three ways: the error message IS the remedy, the fence
# test asserts on its fragments, and docs/parity.md quotes it).
_XLA_FENCE_OP_NAME = (
    "hvd_host_collective__not_XLA_compilable__"
    "use_plain_tf_function_or_the_JAX_frontend__see_docs_parity_md")


def _auto_name(op: str) -> str:
    """Deterministic fallback names, assigned in Python call order — the
    analog of the reference keying on TF node names: identical programs on
    every rank produce identical sequences (same caveat as the reference:
    rank-divergent call order needs explicit names)."""
    global _name_counter
    with _name_lock:
        n = _name_counter
        _name_counter += 1
    return f"tf.{op}.{n}"


def _to_numpy(t):
    """TF tensor → numpy. bfloat16 is widened to f32 for the wire (numpy
    proper has no bf16); the caller narrows back."""
    import tensorflow as tf

    if t.dtype == tf.bfloat16:
        return tf.cast(t, tf.float32).numpy(), tf.bfloat16
    return t.numpy(), None


def _from_numpy(arr, narrow_to):
    import tensorflow as tf

    out = tf.convert_to_tensor(np.ascontiguousarray(arr))
    if narrow_to is not None:
        out = tf.cast(out, narrow_to)
    return out


def _eager_roundtrip(submit, t, keep_shape: bool = True):
    """submit(numpy) -> handle; waits and converts back, preserving bf16.

    ``keep_shape`` restores the input shape (the multi-process host plane
    returns 0-d scalars as shape-(1,); same defense as the torch
    front-end's ``reshape``) — allgather passes False since its first dim
    legitimately grows."""
    import tensorflow as tf

    arr, narrow = _to_numpy(t)
    out = _from_numpy(_ops.synchronize(submit(arr)), narrow)
    if keep_shape and out.shape != t.shape:
        out = tf.reshape(out, t.shape)
    return out


def _graph_op(fn, t, out_dtype, out_shape):
    """Wrap an engine roundtrip as a graph node. The python body runs at
    step time on the host; the name was fixed at trace time by the caller.

    Compile boundary: ``EagerPyFunc`` has no XLA kernel, so this node
    cannot live inside ``tf.function(jit_compile=True)`` / a TPU-compiled
    graph. That is undetectable at trace time (the ``_XlaMustCompile``
    attr is applied to the call op after tracing, and the FuncGraph
    carries no marker — verified empirically), so the fence is the op
    *name*: XLA's unsupported-op error quotes the node name verbatim,
    turning "No registered 'EagerPyFunc' OpKernel" into an actionable
    message pointing at docs/parity.md (which says: use plain
    ``tf.function``, or the JAX front-end for compiled TPU steps)."""
    import tensorflow as tf

    out = tf.py_function(fn, [t], Tout=out_dtype, name=_XLA_FENCE_OP_NAME)
    out.set_shape(out_shape)
    return out


def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none, device_dense: str = "",
              device_sparse: str = ""):
    """Allreduce a tf.Tensor/tf.Variable/tf.IndexedSlices across ranks.

    ``device_dense``/``device_sparse`` are accepted for API parity and
    ignored — placement is XLA's job on TPU (SURVEY §2.10)."""
    import tensorflow as tf

    if isinstance(tensor, tf.IndexedSlices):
        # 2×allgather sparse path (reference :72-83)
        values = allgather(tensor.values,
                           name=None if name is None else f"{name}.values")
        indices = allgather(tensor.indices,
                            name=None if name is None else f"{name}.indices")
        if average:
            values = tf.divide(values, tf.cast(size(), values.dtype))
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    @tf.custom_gradient
    def _op(x):
        y = _allreduce_dense(x, average, name, compression)

        def grad(dy):
            # reference mpi_ops.py:94-105: the gradient of a sum-over-ranks
            # is the same sum of the upstream gradients (the reference's
            # post-sum divide node supplies the /size; here ``average``
            # composes it directly). Via the public differentiable wrapper
            # so second-order tapes chain, as the reference's registered
            # ops do.
            return allreduce(dy, average=average)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def _allreduce_dense(tensor, average: bool, name: Optional[str],
                     compression):
    import tensorflow as tf

    name = name or _auto_name("allreduce")
    compressed, ctx = compression.compress(tf.convert_to_tensor(tensor))
    # Cast codecs already narrowed the tensor above; quantized codecs
    # compress inside the engine's collective, so their tag must ride the
    # submission (ops._submit reads codec_name off the object).
    kw = {"compression": compression} \
        if getattr(compression, "quantized", False) else {}
    if tf.executing_eagerly():
        out = _eager_roundtrip(
            lambda a: _ops.allreduce_async(a, average=average, name=name,
                                           **kw),
            compressed)
    else:
        def _run(t):
            arr, narrow = _to_numpy(t)
            h = _ops.allreduce_async(arr, average=average, name=name, **kw)
            res = np.asarray(_ops.synchronize(h)).reshape(arr.shape)
            return _from_numpy(res, narrow)

        out = _graph_op(_run, compressed, compressed.dtype, compressed.shape)
    return compression.decompress(out, ctx)


def allgather(tensor, name: Optional[str] = None):
    """Concatenate across ranks on dim 0; first dims may differ per rank.
    Differentiable: the upstream gradient is summed across ranks and each
    rank keeps its own block (reference ``mpi_ops.py:127-165``)."""
    import tensorflow as tf

    @tf.custom_gradient
    def _op(x):
        y = _allgather_impl(x, name)

        def grad(dy):
            # public wrappers so second-order tapes chain
            gsum = allreduce(dy, average=False)
            dim = tf.shape(x)[0]
            dims = _allgather_impl(tf.reshape(dim, [1]), None)
            offset = tf.reduce_sum(dims[:basics.rank()])
            return gsum[offset:offset + dim]

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def _allgather_impl(tensor, name: Optional[str]):
    import tensorflow as tf

    name = name or _auto_name("allgather")
    tensor = tf.convert_to_tensor(tensor)
    if tf.executing_eagerly():
        return _eager_roundtrip(
            lambda a: _ops.allgather_async(a, name=name), tensor,
            keep_shape=False)

    def _run(t):
        arr, narrow = _to_numpy(t)
        h = _ops.allgather_async(arr, name=name)
        return _from_numpy(_ops.synchronize(h), narrow)

    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    return _graph_op(_run, tensor, tensor.dtype, out_shape)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Differentiable: all gradient flows to the root, non-root inputs get
    zero (reference ``mpi_ops.py:168-183``)."""
    import tensorflow as tf

    @tf.custom_gradient
    def _op(x):
        y = _broadcast_impl(x, root_rank, name)

        def grad(dy):
            # public wrapper so second-order tapes chain
            gsum = allreduce(dy, average=False)
            if basics.rank() != root_rank:  # static per process
                gsum = gsum * 0
            return gsum

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def _broadcast_impl(tensor, root_rank: int, name: Optional[str]):
    import tensorflow as tf

    name = name or _auto_name("broadcast")
    tensor = tf.convert_to_tensor(tensor)
    if tf.executing_eagerly():
        return _eager_roundtrip(
            lambda a: _ops.broadcast_async(a, root_rank, name=name), tensor)

    def _run(t):
        arr, narrow = _to_numpy(t)
        h = _ops.broadcast_async(arr, root_rank, name=name)
        res = np.asarray(_ops.synchronize(h)).reshape(arr.shape)
        return _from_numpy(res, narrow)

    return _graph_op(_run, tensor, tensor.dtype, tensor.shape)


def broadcast_variables(variables, root_rank: int = 0):
    """Assign rank-``root_rank``'s values to ``variables`` on every rank
    (reference :105-114). Eager: in-place, batched through the engine so
    fusion applies. Graph: returns a grouped assign op."""
    import tensorflow as tf

    variables = list(variables)
    if basics.size() == 1:
        return tf.group() if not tf.executing_eagerly() else None
    if tf.executing_eagerly():
        handles = []
        for i, var in enumerate(variables):
            arr, narrow = _to_numpy(tf.convert_to_tensor(var))
            h = _ops.broadcast_async(
                arr, root_rank, name=f"broadcast_variables.{i}.{var.name}")
            handles.append((var, narrow, h))
        for var, narrow, h in handles:
            out = _from_numpy(_ops.synchronize(h), narrow)
            var.assign(tf.reshape(out, var.shape))
        return None
    return tf.group(*[
        var.assign(tf.reshape(
            broadcast(tf.convert_to_tensor(var), root_rank,
                      name=f"broadcast_variables.{i}.{var.name}"),
            var.shape))
        for i, var in enumerate(variables)])


def broadcast_global_variables(root_rank: int = 0):
    """TF1-compat surface (reference :95-102): broadcasts
    ``tf.compat.v1.global_variables()``. In TF2 eager there are no global
    variables — use :func:`broadcast_variables` on your model/optimizer
    variables instead."""
    import tensorflow as tf

    if tf.executing_eagerly():
        raise RuntimeError(
            "broadcast_global_variables() does not support eager execution. "
            "Please use `broadcast_variables(<model/optimizer variables>)` "
            "instead.")
    return broadcast_variables(tf.compat.v1.global_variables(), root_rank)


def _make_broadcast_global_variables_hook():
    import tensorflow as tf

    class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
        """SessionRunHook broadcasting global variables once after session
        creation (reference :117-148)."""

        def __init__(self, root_rank: int, device: str = "") -> None:
            super().__init__()
            self.root_rank = root_rank
            self.bcast_op = None
            self.device = device  # parity; placement is XLA's job

        def begin(self):
            if not self.bcast_op or \
                    self.bcast_op.graph != tf.compat.v1.get_default_graph():
                self.bcast_op = broadcast_global_variables(self.root_rank)

        def after_create_session(self, session, coord):
            session.run(self.bcast_op)

    return BroadcastGlobalVariablesHook


def __getattr__(attr):
    # BroadcastGlobalVariablesHook subclasses a tf.compat.v1 class, so its
    # definition must not force `import tensorflow` at package import.
    if attr == "BroadcastGlobalVariablesHook":
        cls = _make_broadcast_global_variables_hook()
        globals()[attr] = cls
        return cls
    raise AttributeError(f"module {__name__!r} has no attribute {attr!r}")


def _allreduce_grads(grads, compression, sparse_as_dense: bool,
                     name_prefix: str):
    """Allreduce a gradient list. Inside tf.function, all dense gradients go
    through ONE py_function — one host hop, submitted async together so the
    engine's fusion buffer packs them (the reference relies on its fusion
    cycle for the same effect); eager submissions are likewise batched."""
    import tensorflow as tf

    if sparse_as_dense:
        grads = [tf.convert_to_tensor(g)
                 if g is not None and isinstance(g, tf.IndexedSlices) else g
                 for g in grads]
    names = [f"{name_prefix}.{i}" for i in range(len(grads))]
    dense_idx = [i for i, g in enumerate(grads)
                 if g is not None and not isinstance(g, tf.IndexedSlices)]
    out = list(grads)
    for i, g in enumerate(grads):
        if g is not None and isinstance(g, tf.IndexedSlices):
            out[i] = allreduce(g, average=True, name=names[i])
    if not dense_idx:
        return out

    dense = [tf.convert_to_tensor(grads[i]) for i in dense_idx]
    compressed, ctxs = zip(*[compression.compress(g) for g in dense])
    dense_names = [names[i] for i in dense_idx]

    def _run(*tensors):
        submitted = []
        for t, n in zip(tensors, dense_names):
            arr, narrow = _to_numpy(t)
            submitted.append(
                (_ops.allreduce_async(arr, average=True, name=n), narrow,
                 arr.shape))
        return [_from_numpy(np.asarray(_ops.synchronize(h)).reshape(shape),
                            narrow)
                for h, narrow, shape in submitted]

    if tf.executing_eagerly():
        reduced = _run(*compressed)
    else:
        reduced = tf.py_function(
            _run, list(compressed), Tout=[t.dtype for t in compressed],
            name=_XLA_FENCE_OP_NAME)
        if not isinstance(reduced, (list, tuple)):
            reduced = [reduced]
        for r, t in zip(reduced, compressed):
            r.set_shape(t.shape)
    for slot, r, ctx in zip(dense_idx, reduced, ctxs):
        out[slot] = compression.decompress(r, ctx)
    return out


class DistributedOptimizer:
    """Wrap a ``tf.compat.v1.train.Optimizer`` so ``compute_gradients``
    returns world-averaged gradients (reference :151-249 — delegation, not
    subclassing: ``apply_gradients``/slots forward to the inner optimizer).

    For Keras 3 / ``tf.keras`` optimizers use
    ``horovod_tpu.tensorflow.keras.DistributedOptimizer``."""

    def __init__(self, optimizer, name: Optional[str] = None,
                 use_locking: bool = False, device_dense: str = "",
                 device_sparse: str = "", compression=Compression.none,
                 sparse_as_dense: bool = False) -> None:
        self._optimizer = optimizer
        self._name = name or f"Distributed{type(optimizer).__name__}"
        self._use_locking = use_locking
        self._device_dense = device_dense
        self._device_sparse = device_sparse
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense

    def compute_gradients(self, *args, **kwargs):
        grads_and_vars = self._optimizer.compute_gradients(*args, **kwargs)
        if basics.size() == 1:
            return grads_and_vars
        grads, variables = zip(*grads_and_vars)
        avg = _allreduce_grads(list(grads), self._compression,
                               self._sparse_as_dense,
                               name_prefix=f"{self._name}_Allreduce")
        return list(zip(avg, variables))

    def minimize(self, loss, **kwargs):
        var_list = kwargs.pop("var_list", None)
        global_step = kwargs.pop("global_step", None)
        grads_and_vars = self.compute_gradients(loss, var_list=var_list)
        return self.apply_gradients(grads_and_vars, global_step=global_step)

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


def DistributedGradientTape(gradtape, device_dense: str = "",
                            device_sparse: str = "",
                            compression=Compression.none,
                            sparse_as_dense: bool = False):
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns world-averaged
    gradients (reference :252-326: dynamic subclass of the tape's class
    keeping the original tape's recorded state)."""
    import tensorflow as tf

    class _DistributedGradientTape(tf.GradientTape):
        def gradient(self, target, sources, output_gradients=None):
            grads = super(self.__class__, self).gradient(
                target, sources, output_gradients)
            if basics.size() == 1:
                return grads
            flat = tf.nest.flatten(grads)
            avg = _allreduce_grads(flat, self._hvd_compression,
                                   self._hvd_sparse_as_dense,
                                   name_prefix=self._hvd_name)
            return tf.nest.pack_sequence_as(grads, avg)

    donor = {k: v for k, v in _DistributedGradientTape.__dict__.items()
             if k not in ("__dict__", "__weakref__")}
    cls = type(gradtape.__class__.__name__, (gradtape.__class__,), donor)
    # Rebind the live tape: its pushed-tape state must survive the wrap, so
    # mutate __class__ rather than re-running __init__ (the reference copies
    # the private _tape pointer; swapping the class is the TF2-safe form).
    gradtape.__class__ = cls
    gradtape._hvd_compression = compression
    gradtape._hvd_sparse_as_dense = sparse_as_dense
    gradtape._hvd_name = "DistributedGradientTape_Allreduce"
    return gradtape
