"""Keras callbacks for the tf.keras front-end.

Rebuild of ``horovod/_keras/callbacks.py`` + the ``tensorflow/keras``
binding (reference: ``BroadcastGlobalVariablesCallbackImpl`` :20-30,
``MetricAverageCallbackImpl`` :33-67, ``LearningRateScheduleCallbackImpl``
:70-147 with momentum correction, ``LearningRateWarmupCallbackImpl``
:149-168 — the Goyal et al. gradual warmup) for Keras 3, where there is no
session/backend object: metric averaging goes straight through the eager
engine and LR mutation targets ``model.optimizer.learning_rate``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import keras

from ... import basics
from ... import ops as _ops

__all__ = [
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
]


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast rank-0 model + optimizer state at training start
    (reference ``_keras/callbacks.py:20-30``).

    Keras 3 creates optimizer slot variables lazily on the first
    ``apply``, so the broadcast runs after the first batch — rank 0's
    values overwrite whatever the divergent batch 0 computed, which is the
    same consistency guarantee the reference's graph-mode broadcast gives
    (every rank starts epoch-identical from rank 0's state)."""

    def __init__(self, root_rank: int = 0, device: str = "") -> None:
        super().__init__()
        self.root_rank = root_rank
        self.device = device  # parity; placement is XLA's job on TPU
        self.broadcast_done = False

    def _broadcast(self) -> None:
        from .. import broadcast_variables

        variables = list(self.model.variables)
        if getattr(self.model, "optimizer", None) is not None:
            variables += list(self.model.optimizer.variables)
        broadcast_variables(variables, self.root_rank)

    def on_train_batch_end(self, batch, logs=None) -> None:
        if self.broadcast_done or basics.size() == 1:
            return
        self._broadcast()
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics across ranks so rank 0's logs (and any
    downstream callbacks: checkpointing, early stopping) see world metrics
    (reference ``_keras/callbacks.py:33-67``)."""

    def __init__(self, device: str = "") -> None:
        super().__init__()
        self.device = device

    def on_epoch_end(self, epoch, logs=None) -> None:
        if not logs or basics.size() == 1:
            return
        for metric in sorted(logs):
            value = np.asarray(float(logs[metric]), dtype=np.float64)
            avg = _ops.allreduce(value, average=True,
                                 name=f"metric.{metric}.epoch{epoch}")
            logs[metric] = float(np.asarray(avg))


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """LR = initial_lr * multiplier(epoch) within [start_epoch, end_epoch),
    staircase or smoothly interpolated per batch, with momentum correction
    (reference ``_keras/callbacks.py:70-147``)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None) -> None:
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = None
        self.restore_momentum = None
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.staircase = True
            self.multiplier = lambda epoch: multiplier

    def _autodetect_steps_per_epoch(self) -> int:
        if self.params.get("steps"):
            return self.params["steps"]
        raise ValueError(
            f"Could not autodetect the number of steps per epoch. Please "
            f"specify the steps_per_epoch parameter to the "
            f"{self.__class__.__name__}() or upgrade to the latest version "
            f"of Keras.")

    def _get_lr(self) -> float:
        return float(
            keras.ops.convert_to_numpy(self.model.optimizer.learning_rate))

    def _set_lr(self, lr: float) -> None:
        self.model.optimizer.learning_rate = lr

    def _adjust_learning_rate(self, epoch: float) -> None:
        old_lr = self._get_lr()
        new_lr = self.initial_lr * self.multiplier(epoch)
        self._set_lr(new_lr)
        opt = self.model.optimizer
        if self.momentum_correction and \
                getattr(opt, "momentum", None) not in (None, 0):
            # momentum correction (Goyal et al.): scale momentum by the LR
            # ratio for the step where LR changes, restore afterwards
            self.restore_momentum = float(opt.momentum)
            opt.momentum = self.restore_momentum * new_lr / old_lr

    def _restore_momentum_if_needed(self) -> None:
        if self.restore_momentum:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    def on_train_begin(self, logs=None) -> None:
        self.initial_lr = self._get_lr()
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self._autodetect_steps_per_epoch()

    def on_epoch_begin(self, epoch, logs=None) -> None:
        self.current_epoch = epoch

    def on_train_batch_begin(self, batch, logs=None) -> None:
        if self.current_epoch < self.start_epoch or \
                (self.end_epoch is not None and
                 self.current_epoch >= self.end_epoch):
            return
        if self.staircase and batch == 0:
            self._adjust_learning_rate(self.current_epoch)
        elif not self.staircase:
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            self._adjust_learning_rate(epoch)

    def on_train_batch_end(self, batch, logs=None) -> None:
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None) -> None:
        if logs is not None:
            logs["lr"] = self._get_lr()


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from a 1-worker LR to the size()-scaled LR over
    ``warmup_epochs`` (reference ``_keras/callbacks.py:149-168``)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 verbose: int = 0) -> None:
        def multiplier(epoch: float) -> float:
            # shifted so epoch boundaries land on round LR values, as the
            # reference notes for TensorBoard readability
            epoch += 1.0 / self.steps_per_epoch
            return 1.0 / basics.size() * (
                epoch * (basics.size() - 1) / warmup_epochs + 1)

        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose

    def on_epoch_end(self, epoch, logs=None) -> None:
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._get_lr():g}.")
