"""tf.keras front-end (Keras 3) on the TPU-native engine.

Rebuild of ``horovod/tensorflow/keras/__init__.py`` (:40-155) +
``horovod/_keras/__init__.py``. In Keras 3 the gradient seam moved: there
is no ``get_gradients`` (reference ``_keras/__init__.py:34-61``); every
path — ``model.fit``'s compiled train step and manual
``optimizer.apply_gradients`` — funnels through ``Optimizer.apply``, so the
dynamic subclass overrides ``apply`` to allreduce first. Inside
``model.fit``'s ``tf.function``, all dense gradients ride ONE
``tf.py_function`` into the engine's fusion buffer (see
``.._allreduce_grads``).
"""

from __future__ import annotations

from typing import Optional

import keras

from ... import basics
from ...basics import (  # noqa: F401  (re-exported API surface)
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from .. import _allreduce_grads, allgather as _tf_allgather, \
    allreduce as _tf_allreduce, broadcast as _tf_broadcast, \
    broadcast_variables
from ..compression import Compression
from . import callbacks  # noqa: F401

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "is_initialized", "mpi_threads_supported",
    "DistributedOptimizer", "Compression", "broadcast_variables",
    "allreduce", "allgather", "broadcast", "load_model", "callbacks",
]


class _DistributedOptimizer:
    """Method donor for the dynamic subclass (reference
    ``_keras/__init__.py:22-61`` pattern, re-seamed onto ``apply``)."""

    def apply(self, grads, trainable_variables=None):
        if basics.size() > 1:
            grads = _allreduce_grads(
                list(grads),
                getattr(self, "_hvd_compression", Compression.none),
                getattr(self, "_hvd_sparse_as_dense", False),
                name_prefix=getattr(self, "_hvd_name",
                                    "DistributedOptimizer_Allreduce"))
        return super(self.__class__, self).apply(grads, trainable_variables)


def _make_distributed_class(base_cls, name: Optional[str] = None,
                            compression=Compression.none,
                            sparse_as_dense: bool = False):
    """Dynamic subclass of ``base_cls`` with the allreducing ``apply``.

    Keeps the wrapped optimizer's class name so a model saved with it
    reloads without horovod_tpu installed (the reference's stated reason
    for the ``type(...)`` construction), and so keras's deserializer —
    which requires a CLASS with ``from_config`` in ``custom_objects`` —
    can construct it directly during ``load_model``."""
    # __dict__/__weakref__ descriptors belong to the donor class and would
    # shadow the real ones on the subclass (breaking keras's save walker)
    donor = {k: v for k, v in _DistributedOptimizer.__dict__.items()
             if k not in ("__dict__", "__weakref__")}
    donor["_hvd_compression"] = compression
    donor["_hvd_sparse_as_dense"] = sparse_as_dense
    donor["_hvd_name"] = (name or f"Distributed{base_cls.__name__}"
                          ) + "_Allreduce"
    return type(base_cls.__name__, (base_cls,), donor)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         device_dense: str = "", device_sparse: str = "",
                         compression=Compression.none,
                         sparse_as_dense: bool = False):
    """Wrap a keras optimizer so gradients are world-averaged before the
    update (reference ``tensorflow/keras/__init__.py:40-66``).

    ``device_dense``/``device_sparse`` are accepted for API parity and
    ignored — placement is XLA's job on TPU."""
    cls = _make_distributed_class(optimizer.__class__, name=name,
                                  compression=compression,
                                  sparse_as_dense=sparse_as_dense)
    return cls.from_config(optimizer.get_config())


def broadcast_global_variables(model, root_rank: int = 0) -> None:
    """Broadcast a model's (+ its optimizer's) variables from root_rank.

    The reference signature takes no model (TF1 global-variable
    collection, ``tensorflow/keras/__init__.py:68-76``); Keras 3 has no
    such collection, so the model is explicit here."""
    variables = list(model.variables)
    if getattr(model, "optimizer", None) is not None:
        variables += list(model.optimizer.variables)
    broadcast_variables(variables, root_rank)


def allreduce(value, name: Optional[str] = None, average: bool = True):
    """Allreduce a tensor-compatible value, returned as numpy
    (reference ``tensorflow/keras/__init__.py:78-90`` semantics)."""
    import numpy as np
    import tensorflow as tf

    out = _tf_allreduce(tf.convert_to_tensor(value), average=average,
                        name=name)
    return np.asarray(out)


def allgather(value, name: Optional[str] = None):
    import numpy as np
    import tensorflow as tf

    return np.asarray(_tf_allgather(tf.convert_to_tensor(value), name=name))


def broadcast(value, root_rank: int, name: Optional[str] = None):
    import numpy as np
    import tensorflow as tf

    return np.asarray(
        _tf_broadcast(tf.convert_to_tensor(value), root_rank, name=name))


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a saved keras model with its optimizer re-wrapped as a
    DistributedOptimizer, preserving restored optimizer state
    (reference ``tensorflow/keras/__init__.py:121-155``)."""

    def wrap_optimizer(cls):
        # keras 3 deserialization requires a class (constructed via
        # from_config), not a factory function
        return _make_distributed_class(cls, compression=compression)

    horovod_objects = {
        subclass.__name__: wrap_optimizer(subclass)
        for subclass in vars(keras.optimizers).values()
        if isinstance(subclass, type) and
        issubclass(subclass, keras.optimizers.Optimizer) and
        subclass is not keras.optimizers.Optimizer
    }
    if custom_optimizers is not None:
        horovod_objects.update({
            cls.__name__: wrap_optimizer(cls) for cls in custom_optimizers})
    if custom_objects is not None:
        horovod_objects.update(custom_objects)
    return keras.models.load_model(filepath, custom_objects=horovod_objects)
