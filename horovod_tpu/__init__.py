"""horovod_tpu: a TPU-native distributed training framework.

A ground-up rebuild of Horovod 0.16 (reference: SinestroEdmonce/horovod) for
TPU: same user surface — ``init()/rank()/size()``, named async
allreduce/allgather/broadcast with tensor fusion, ``DistributedOptimizer``,
parameter/optimizer-state broadcast, compression, timeline, autotune,
launcher — with the data plane rebuilt on XLA collectives over an ICI/DCN
device mesh instead of MPI/NCCL, and the SPMD compiler replacing the
coordinator for jit-compiled training steps (see SURVEY.md §7).

Typical use, mirroring the reference README:

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.num_devices()),
                                   axis_name="data")
    # ... shard_map/pjit train step over the mesh; psum rides ICI ...
    params = hvd.broadcast_parameters(params, root_rank=0)
"""

import os as _os

# Bridge JAX API drift (jax.shard_map / check_vma / lax.axis_size on
# older pinned releases) before anything — including test modules that do
# `from jax import shard_map` after importing this package — touches jax.
from .core import jax_compat as _jax_compat

_jax_compat.install()
del _jax_compat

# HOROVOD_PLATFORM: pin the JAX platform before ANY backend starts (the
# env var JAX_PLATFORMS alone is insufficient on TPU images whose plugin
# prepends itself to the list). Applied at import so launcher-spawned
# workers — which import this package before their first device query —
# are steered without code changes; see docs/running.md.
from .core import config as _config

_platform = _os.environ.get(_config.HOROVOD_PLATFORM)
if _platform:
    import jax as _jax

    _jax.config.update("jax_platforms", _platform)
    try:  # diagnose the one case the pin cannot fix: a live backend.
        # backends_are_initialized() is the purpose-built passive query
        # (jax.config itself uses it to validate late config changes);
        # there is no fully-public equivalent that doesn't itself
        # initialize a backend.
        _live = _jax._src.xla_bridge.backends_are_initialized()
    except Exception as _exc:  # noqa: BLE001 - probe moved in a future JAX
        from .core.logging import LOG as _LOG

        _LOG.debug("HOROVOD_PLATFORM late-backend probe unavailable "
                   f"({_exc!r}); cannot warn if the pin came too late")
        del _LOG
        _live = False
    if _live:
        import warnings as _warnings

        _warnings.warn(
            f"HOROVOD_PLATFORM={_platform!r} was applied AFTER a JAX "
            f"backend initialized; existing computations stay on the old "
            f"platform. Import horovod_tpu (or set the env var) before "
            f"any jax device use.", RuntimeWarning, stacklevel=2)
        del _warnings
    del _jax, _live
del _os, _platform

from . import (
    callbacks,
    checkpoint,
    elastic,
    integrity,
    obs,
    parallel,
    runner,
    serving,
    tune,
)
from .obs import (
    health_report,
    metrics_snapshot,
    straggler_report,
    tensor_report,
)
from .basics import (
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_device_count,
    local_rank,
    local_size,
    mpi_threads_supported,
    num_devices,
    rank,
    shutdown,
    size,
)
from .core.status import (
    ConsensusError,
    HorovodInternalError,
    NonFiniteGradError,
    NotInitializedError,
    RanksAbortedError,
)
from .ops import (
    Compression,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    broadcast,
    broadcast_async,
    poll,
    release,
    spmd,
    synchronize,
)
from .ops.fused_apply import (
    adam as fused_adam,
    momentum as fused_momentum,
    sgd as fused_sgd,
)
from .ops.pallas_attention import flash_attention
from .ops.sparse import IndexedSlices, allreduce_sparse
from .optimizers import DistributedOptimizer, allreduce_gradients, apply_step
from .state_bcast import (
    broadcast_global_variables,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Framework front-ends are optional (like the torch front-end): flax and
    # haiku are extras, so they must not break `import horovod_tpu` when
    # absent.
    if name in ("flax", "haiku"):
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "__version__",
    "init", "shutdown", "is_initialized",
    "rank", "size", "local_rank", "local_size", "cross_rank", "cross_size",
    "local_device_count", "num_devices", "mpi_threads_supported",
    "allreduce", "allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "poll", "synchronize", "release",
    "Compression", "spmd", "parallel", "callbacks", "checkpoint",
    "elastic", "obs", "tune", "metrics_snapshot", "straggler_report",
    "health_report", "tensor_report",
    "IndexedSlices", "allreduce_sparse", "flash_attention",
    "DistributedOptimizer", "allreduce_gradients", "apply_step",
    "fused_sgd", "fused_momentum", "fused_adam",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_global_variables", "broadcast_object",
    "HorovodInternalError", "NotInitializedError", "RanksAbortedError",
    "ConsensusError", "NonFiniteGradError", "integrity",
]
