"""DEPRECATED location — the checkpoint plane owns checkpoint I/O now.

This module is a compatibility shim: the rank-0 orbax storage +
broadcast-consistent restore helpers moved verbatim to
``horovod_tpu/ckpt/files.py`` when the checkpoint plane landed
(docs/checkpoint.md), so there is exactly one checkpoint implementation.
``save``/``restore`` keep working from here unchanged; new code should
import :mod:`horovod_tpu.ckpt` — which also carries what this module
never had: the async in-training commit pipeline, digest-sealed epochs,
and the train-to-serve hot-swap path.
"""

from __future__ import annotations

from .ckpt.files import restore, save  # noqa: F401

__all__ = ["save", "restore"]
