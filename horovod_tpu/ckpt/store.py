"""Driver-resident checkpoint store: seal ledger + ticket journal.

The :class:`SealLedger` is the coordinator half of the checkpoint plane
(docs/checkpoint.md). It lives inside the elastic driver's
``ElasticService`` — the process that survives world relaunches — and
ingests the chunked commit streams the per-rank
:class:`~horovod_tpu.ckpt.committer.AsyncCommitter` ships over its
dedicated connection.

Sealing semantics (the whole point): checkpoint commit N is **sealed**
only when

* every rank of the committing world announced N (``ckpt_begin``),
* every rank's shard digest arrived (``ckpt_end``) and all digests
  AGREE (PR-8 consensus bar: a sealed epoch is a verified epoch), and
* rank 0's payload arrived complete (all ``n_chunks`` chunk frames).

A kill mid-commit therefore leaves N unsealed — partial chunk state is
dropped at the next ``begin_epoch`` — and restore always lands on the
last *sealed* commit, bit-exactly. Seals are monotonic: a late or
replayed stream for an already-superseded commit number is ignored.

The :class:`TicketJournal` shares the store: the serving gateway
journals in-flight request envelopes through it so a driver restart
(``HOROVOD_CKPT_DIR`` set) resumes them instead of losing them to a
world abort.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, Optional, Tuple

from ..core.logging import LOG
from ..integrity.consensus import digest_bytes
from ..obs.registry import registry as _metrics

_SEALS = _metrics().counter(
    "horovod_ckpt_seals_total",
    "Checkpoint commits sealed by the driver ledger (every rank's shard "
    "digest arrived and agreed, rank-0 payload complete)")
_SEALED_NO = _metrics().gauge(
    "horovod_ckpt_sealed_commit",
    "Highest sealed checkpoint commit number (-1 until the first seal)")
_DIGEST_MISMATCHES = _metrics().counter(
    "horovod_ckpt_digest_mismatches_total",
    "Checkpoint commits REFUSED a seal because per-rank shard digests "
    "diverged (the commit stays unsealed; restore keeps the previous "
    "sealed epoch)")
_JOURNAL_ENTRIES = _metrics().gauge(
    "horovod_ckpt_journal_entries",
    "Live entries in the gateway ticket journal")

# File names under HOROVOD_CKPT_DIR. The payload and its sidecar meta
# are written first, the SEALED pointer last — a torn driver death
# between the two leaves the pointer at the previous sealed commit,
# which is exactly the restore contract.
_SEALED_POINTER = "SEALED"
_JOURNAL_FILE = "journal.json"


class _Partial:
    """One in-flight (unsealed) commit: chunk assembly + digest votes."""

    __slots__ = ("meta", "world", "digests", "chunks", "n_chunks",
                 "shard_digests", "shard_world")

    def __init__(self) -> None:
        self.meta: dict = {}
        self.world: int = 0
        self.digests: Dict[int, str] = {}
        self.chunks: Dict[int, bytes] = {}
        self.n_chunks: int = -1
        # ZeRO-1 partition manifest (docs/sharding.md): per-rank digests
        # of the RESIDENT shard bytes, folded into the seal meta so the
        # partition that produced a sealed commit is on the record
        self.shard_digests: Dict[int, str] = {}
        self.shard_world: int = 0

    def complete(self) -> bool:
        if self.world <= 0 or len(self.digests) < self.world:
            return False
        if self.n_chunks < 0 or len(self.chunks) < self.n_chunks:
            return False
        return True


class SealLedger:
    """Epoch-fenced ingest of chunked commit streams; seal on agreement.

    ``dir`` (``HOROVOD_CKPT_DIR``) is optional: unset keeps the ledger
    in driver memory (survives world relaunches, not a driver restart);
    set, every seal is spilled to disk and a fresh ledger reloads the
    last sealed commit, refusing a payload whose bytes digest does not
    match its sidecar (a torn spill restores the previous epoch instead
    of garbage).
    """

    def __init__(self, dir: Optional[str] = None,
                 on_seal: Optional[Callable[[int, dict, bytes], None]] = None
                 ) -> None:
        self._lock = threading.Lock()
        self._epoch = 0
        self._partials: Dict[int, _Partial] = {}
        self._sealed_no = -1
        self._sealed_meta: dict = {}
        self._sealed_payload: Optional[bytes] = None
        self._dir = dir or None
        self.on_seal = on_seal
        self.journal = TicketJournal(dir=self._dir)
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)
            self._load_sealed()
        _SEALED_NO.set(self._sealed_no)

    @property
    def sealed_no(self) -> int:
        """Current sealed watermark (-1 = nothing sealed yet). The elastic
        driver reads this cheaply per epoch to decide whether an attempt
        made checkpoint progress (backoff-ladder reset, docs/recovery.md)
        without paying ``fetch_sealed``'s payload copy."""
        with self._lock:
            return self._sealed_no

    # -- epoch fence -----------------------------------------------------------

    def begin_epoch(self, epoch: int) -> None:
        """New world attempt: drop partial streams (a kill mid-commit
        leaves its commit unsealed forever), KEEP sealed state and the
        ticket journal — they are exactly what the relaunch restores."""
        with self._lock:
            self._epoch = int(epoch)
            self._partials.clear()

    # -- ingest (ElasticService handler thread) --------------------------------

    def ingest_begin(self, epoch: int, ckpt_no: int, rank: int,
                     meta: dict) -> None:
        with self._lock:
            if not self._admit_locked(epoch, ckpt_no):
                return
            part = self._partials.setdefault(int(ckpt_no), _Partial())
            if not part.meta:
                part.meta = dict(meta or {})
            part.world = max(part.world, int(meta.get("world", 0) or 0))

    def ingest_chunk(self, epoch: int, ckpt_no: int, rank: int, seq: int,
                     payload: bytes) -> None:
        with self._lock:
            if not self._admit_locked(epoch, ckpt_no):
                return
            part = self._partials.setdefault(int(ckpt_no), _Partial())
            part.chunks[int(seq)] = bytes(payload)

    def ingest_end(self, epoch: int, ckpt_no: int, rank: int, n_chunks: int,
                   digest: str) -> int:
        """Digest vote; returns the current sealed commit number (the
        seal ack the committer checks to learn whether ITS commit
        landed)."""
        callback = None
        with self._lock:
            if self._admit_locked(epoch, ckpt_no):
                part = self._partials.setdefault(int(ckpt_no), _Partial())
                part.digests[int(rank)] = str(digest)
                if rank == 0:
                    part.n_chunks = int(n_chunks)
                callback = self._maybe_seal_locked(int(ckpt_no))
            sealed_no = self._sealed_no
        if callback is not None:
            callback()
        return sealed_no

    def ingest_shard_manifest(self, epoch: int, ckpt_no: int, rank: int,
                              world: int, digest: str) -> None:
        """ZeRO-1 partition manifest vote (docs/sharding.md): each rank
        of a sharded world digests the shard bytes it OWNS for this
        commit. The votes are folded (``consensus.fold_digest``) into
        the seal meta — the partition provenance a resharding restore
        can audit — without joining the seal condition itself: the
        sealed payload is the CANONICAL expanded tree, whose whole-tree
        digest votes already gate the seal, so a replicated run (which
        never sends manifests) seals exactly as before."""
        with self._lock:
            if not self._admit_locked(epoch, ckpt_no):
                return
            part = self._partials.setdefault(int(ckpt_no), _Partial())
            part.shard_digests[int(rank)] = str(digest)
            part.shard_world = max(part.shard_world, int(world))

    def _admit_locked(self, epoch: int, ckpt_no: int) -> bool:
        # Epoch fence (the beat discipline): a stream from a previous
        # world attempt is a ghost — acknowledged, ignored. Monotonic
        # seal: a commit at or below the sealed watermark is history.
        return int(epoch) == self._epoch and int(ckpt_no) > self._sealed_no

    def _maybe_seal_locked(self, ckpt_no: int) -> Optional[Callable]:
        part = self._partials.get(ckpt_no)
        if part is None or not part.complete():
            return None
        votes = set(part.digests.values())
        if len(votes) != 1:
            _DIGEST_MISMATCHES.inc()
            LOG.warning(
                "ckpt: commit %d digest disagreement across ranks (%s) — "
                "NOT sealed; restore keeps commit %d",
                ckpt_no, sorted(votes), self._sealed_no)
            del self._partials[ckpt_no]
            return None
        payload = b"".join(part.chunks[i] for i in range(part.n_chunks))
        meta = dict(part.meta)
        meta["digest"] = next(iter(votes))
        meta["world"] = part.world
        if part.shard_digests:
            from ..integrity.consensus import fold_digest

            meta["shard_digest"] = fold_digest(part.shard_digests)
            meta["shard_world"] = part.shard_world
        del self._partials[ckpt_no]
        self._sealed_no = ckpt_no
        self._sealed_meta = meta
        self._sealed_payload = payload
        _SEALS.inc()
        _SEALED_NO.set(ckpt_no)
        if self._dir:
            self._spill_locked(ckpt_no, meta, payload)
        cb = self.on_seal
        if cb is None:
            return None
        # fire outside the lock (the hook may publish to a serving plane
        # that takes its own locks)
        return lambda: cb(ckpt_no, meta, payload)

    # -- restore side ----------------------------------------------------------

    def fetch_sealed(self) -> Tuple[int, dict, Optional[bytes]]:
        with self._lock:
            return self._sealed_no, dict(self._sealed_meta), \
                self._sealed_payload

    def stats(self) -> dict:
        with self._lock:
            return {
                "sealed_no": self._sealed_no,
                "partials": sorted(self._partials),
                "epoch": self._epoch,
            }

    # -- disk spill / reload ---------------------------------------------------

    def _spill_locked(self, ckpt_no: int, meta: dict, payload: bytes) -> None:
        try:
            base = os.path.join(self._dir, "ckpt-%d" % ckpt_no)
            with open(base + ".bin", "wb") as f:
                f.write(payload)
            sidecar = dict(meta)
            sidecar["bytes_digest"] = digest_bytes(payload)
            with open(base + ".json", "w") as f:
                json.dump(sidecar, f)
            pointer = os.path.join(self._dir, _SEALED_POINTER)
            with open(pointer + ".tmp", "w") as f:
                json.dump({"sealed_no": ckpt_no}, f)
            os.replace(pointer + ".tmp", pointer)  # atomic pointer flip
        except OSError as exc:  # pragma: no cover - disk-full etc.
            LOG.warning("ckpt: spill of commit %d failed: %s", ckpt_no, exc)

    def _load_sealed(self) -> None:
        pointer = os.path.join(self._dir, _SEALED_POINTER)
        try:
            with open(pointer) as f:
                ckpt_no = int(json.load(f)["sealed_no"])
            base = os.path.join(self._dir, "ckpt-%d" % ckpt_no)
            with open(base + ".json") as f:
                meta = json.load(f)
            with open(base + ".bin", "rb") as f:
                payload = f.read()
        except (OSError, ValueError, KeyError):
            return  # no sealed state on disk: fresh ledger
        if digest_bytes(payload) != meta.get("bytes_digest"):
            LOG.warning(
                "ckpt: on-disk commit %d fails its bytes digest — refusing "
                "the torn spill, starting unsealed", ckpt_no)
            return
        self._sealed_no = ckpt_no
        self._sealed_meta = meta
        self._sealed_payload = payload
        LOG.info("ckpt: reloaded sealed commit %d from %s (digest ok)",
                 ckpt_no, self._dir)


class TicketJournal:
    """Crash-durable journal of in-flight gateway requests.

    Entries are small JSON-serializable envelopes keyed by the client's
    ``X-Request-Id``. In-memory by default; with ``dir`` set every
    mutation rewrites ``journal.json`` (entries are request-sized, the
    journal is capped, and a rewrite is atomic via ``os.replace`` — the
    boring durable choice over an append log that needs compaction).
    """

    def __init__(self, dir: Optional[str] = None,
                 max_entries: int = 1024,
                 filename: str = _JOURNAL_FILE) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._max = max(int(max_entries), 1)
        self._file = filename
        self._dir = dir or None
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)
            self._load()
        _JOURNAL_ENTRIES.set(len(self._entries))

    def put(self, key: str, entry: dict) -> None:
        with self._lock:
            self._entries[str(key)] = dict(entry)
            while len(self._entries) > self._max:  # drop-oldest cap
                self._entries.pop(next(iter(self._entries)))
            self._persist_locked()
            _JOURNAL_ENTRIES.set(len(self._entries))

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            entry = self._entries.get(str(key))
            return dict(entry) if entry is not None else None

    def delete(self, key: str) -> None:
        with self._lock:
            self._entries.pop(str(key), None)
            self._persist_locked()
            _JOURNAL_ENTRIES.set(len(self._entries))

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def _persist_locked(self) -> None:
        if not self._dir:
            return
        path = os.path.join(self._dir, self._file)
        try:
            with open(path + ".tmp", "w") as f:
                json.dump(self._entries, f)
            os.replace(path + ".tmp", path)
        except (OSError, TypeError, ValueError) as exc:
            LOG.warning("ckpt: journal persist failed: %s", exc)

    def _load(self) -> None:
        path = os.path.join(self._dir, self._file)
        try:
            with open(path) as f:
                loaded = json.load(f)
        except (OSError, ValueError):
            return
        if isinstance(loaded, dict):
            self._entries = {str(k): dict(v) for k, v in loaded.items()
                             if isinstance(v, dict)}
