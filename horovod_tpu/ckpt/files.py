"""File-format checkpoint I/O: rank-0 orbax storage + broadcast restore.

The filesystem leg of the checkpoint plane (docs/checkpoint.md). This is
the former top-level ``horovod_tpu/checkpoint.py`` relocated verbatim —
the plane owns every checkpoint implementation now, and the legacy
module is a re-export shim — carrying the reference's consistency
contract (SURVEY §5.4): save only on rank 0 (README Usage step 6;
``examples/tensorflow_mnist.py`` passes checkpoint_dir=None off rank 0)
and push rank-0 state to every rank after restore
(``BroadcastGlobalVariablesHook`` / ``broadcast_parameters``). Storage
is orbax — the JAX-native checkpointer — wrapped so both halves of that
contract are one call.

The reference repo's Keras ``ModelCheckpoint``-callback era hooks map
here (docs/api-mapping.md): ``save`` is the rank-0-gated write, and the
async in-training path those callbacks never had is
``elastic.State.commit()`` over the :mod:`~horovod_tpu.ckpt.committer`
pipeline.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .. import basics
from ..state_bcast import broadcast_parameters


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, force: bool = True) -> None:
    """Write ``state`` (any pytree) from rank 0 only; other ranks no-op
    (the reference's checkpoint_dir=None convention)."""
    if basics.rank() != 0:
        return
    _checkpointer().save(os.path.abspath(os.path.expanduser(path)), state,
                         force=force)


def restore(path: str, template: Optional[Any] = None,
            root_rank: int = 0, broadcast: bool = True) -> Any:
    """Restore on every rank and broadcast root's copy so all ranks start
    identical even if their filesystems disagree (rank-0 truth, exactly the
    post-restore broadcast the reference prescribes)."""
    restored = _checkpointer().restore(
        os.path.abspath(os.path.expanduser(path)), item=template)
    if broadcast and basics.size() > 1:
        restored = broadcast_parameters(
            restored, root_rank=root_rank, name_prefix="checkpoint_restore")
    return restored
