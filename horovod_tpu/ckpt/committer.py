"""Rank-side async commit pipeline: snapshot → background chunk stream.

The :class:`AsyncCommitter` is the per-rank half of the checkpoint plane
(docs/checkpoint.md). ``State.commit()`` hands it the already-snapshotted
host tree and RETURNS — the stall the training loop pays is O(snapshot),
independent of state size — while a daemon streaming thread pickles the
tree, digests it, and ships ``ckpt_begin`` / ``ckpt_chunk`` / ``ckpt_end``
frames to the driver's :class:`~horovod_tpu.ckpt.store.SealLedger`.

The stream rides its OWN identified ``BasicClient`` connection — the
PR-9 second-connection pattern: a parked multi-megabyte commit stream
must never hold the wire the negotiation cycle (or the heartbeat) is
waiting on.

Supersession is latest-wins: the pending slot holds ONE tree, and a new
``submit`` while the thread is still streaming the previous commit
replaces it — under backpressure the plane ships the freshest state
instead of queueing a convoy (each skip is counted). Rank 0 streams the
payload; every other rank ships only begin + digest vote, which is what
lets the ledger seal = verify across the world (PR-8 bar) without
shipping the model N times.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Optional, Tuple

from ..core import config as _config
from ..core.config import _env_float, _env_int
from ..basics import world_epoch
from ..core.logging import LOG
from ..integrity.consensus import tree_digest
from ..obs.registry import registry as _metrics
from ..runner.network import BasicClient, default_secret

_COMMITS = _metrics().counter(
    "horovod_ckpt_commits_total",
    "Async checkpoint commits submitted to the streaming thread")
_SKIPPED = _metrics().counter(
    "horovod_ckpt_skipped_total",
    "Pending commits superseded before their stream started (latest-wins "
    "backpressure: the plane ships the freshest state, never a convoy)")
_CHUNKS = _metrics().counter(
    "horovod_ckpt_chunks_total",
    "Checkpoint payload chunk frames streamed to the driver ledger")
_BYTES = _metrics().counter(
    "horovod_ckpt_bytes_total",
    "Checkpoint payload bytes streamed to the driver ledger")
_STREAM_S = _metrics().histogram(
    "horovod_ckpt_stream_seconds",
    "Wall time of one background commit stream (pickle + digest + frames)")
_STALL_S = _metrics().histogram(
    "horovod_ckpt_commit_stall_seconds",
    "Commit-path stall the TRAINING LOOP paid per State.commit() — the "
    "bench headline: ~flat vs state size when async, linear when "
    "synchronous")


def parse_ckpt_fault(spec: str) -> Optional[Tuple[int, int, int]]:
    """``"rank:ckpt[:chunk]"`` → ``(rank, ckpt_no, chunk_seq)`` or None.

    The kill-between-chunks twin of ``elastic.state.parse_fault_spec``:
    the victim rank dies with ``os._exit`` in its STREAMING thread right
    before sending chunk ``chunk_seq`` (0-based, default 0) of commit
    ``ckpt``, leaving that commit unsealed at the ledger. Malformed
    specs parse to None, like the elastic twin.
    """
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        return None
    try:
        rank = int(parts[0])
        ckpt_no = int(parts[1])
        chunk = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        return None
    return rank, ckpt_no, chunk


def _maybe_inject_ckpt_fault(rank: int, ckpt_no: int, chunk_seq: int) -> None:
    """Kill-between-chunks drill (HOROVOD_CKPT_FAULT): epoch-0 only so
    the fault never re-fires after the relaunch restores."""
    fault = parse_ckpt_fault(os.environ.get(_config.HOROVOD_CKPT_FAULT, ""))
    if fault is None or world_epoch() != 0:
        return
    f_rank, f_ckpt, f_chunk = fault
    if rank == f_rank and ckpt_no == f_ckpt and chunk_seq == f_chunk:
        LOG.warning(
            "HOROVOD_CKPT_FAULT firing: rank %d dying before chunk %d of "
            "commit %d (the commit stays unsealed)", rank, chunk_seq, ckpt_no)
        os._exit(13)


class AsyncCommitter:
    """One background streaming thread + one dedicated wire per rank."""

    def __init__(self, addr: Tuple[str, int], rank: int, world: int,
                 secret: Optional[bytes] = None,
                 chunk_bytes: Optional[int] = None,
                 timeout_s: Optional[float] = None) -> None:
        self._addr = addr
        self._rank = int(rank)
        self._world = int(world)
        self._secret = secret if secret is not None else default_secret()
        self._chunk_bytes = max(int(
            chunk_bytes if chunk_bytes is not None else
            _env_int(_config.HOROVOD_CKPT_CHUNK_BYTES, 1 << 20)), 1)
        self._timeout_s = float(
            timeout_s if timeout_s is not None else
            _env_float(_config.HOROVOD_CKPT_PUSH_TIMEOUT_S, 60.0))
        self._client: Optional[BasicClient] = None
        self._cond = threading.Condition()
        # latest-wins pending slot: (ckpt_no, tree, epoch) or None
        self._pending: Optional[Tuple[int, object, int]] = None
        self._streaming = False
        self._closed = False
        self.last_sealed = -1  # last seal ack observed on the wire
        self._thread = threading.Thread(
            target=self._run, name="ckpt-committer", daemon=True)
        self._thread.start()

    # -- training-loop side (the O(snapshot) path) -----------------------------

    def submit(self, ckpt_no: int, tree, epoch: int) -> None:
        """Hand a snapshotted host tree to the stream; returns at once."""
        with self._cond:
            if self._closed:
                return
            if self._pending is not None:
                _SKIPPED.inc()
            self._pending = (int(ckpt_no), tree, int(epoch))
            _COMMITS.inc()
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float = 30.0) -> bool:
        """Block until the pending slot drained AND the stream finished
        (tests and clean shutdowns; the training loop never calls this)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._pending is not None or self._streaming:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=min(left, 0.2))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._drop_client()

    # -- streaming thread ------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait(timeout=0.2)
                if self._closed and self._pending is None:
                    return
                ckpt_no, tree, epoch = self._pending
                self._pending = None
                self._streaming = True
            try:
                self._stream(ckpt_no, tree, epoch)
            except Exception as exc:  # noqa: BLE001 - stream is best-effort
                LOG.warning(
                    "ckpt: async stream of commit %d failed: %s (the commit "
                    "stays unsealed; recovery restores the previous sealed "
                    "epoch)", ckpt_no, exc)
                self._drop_client()
            finally:
                with self._cond:
                    self._streaming = False
                    self._cond.notify_all()

    def _stream(self, ckpt_no: int, tree, epoch: int) -> None:
        t0 = time.monotonic()
        payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
        digest = tree_digest(tree)
        meta = {"commit_no": ckpt_no, "world": self._world}
        client = self._client_or_dial()
        resp = client.request(("ckpt_begin", epoch, ckpt_no, self._rank,
                               meta))
        assert resp and resp[0] == "ok", resp
        n_chunks = 0
        if self._rank == 0:
            # only the root ships bytes; the other ranks' digest votes
            # are what turns the seal into a verification
            step = self._chunk_bytes
            n_chunks = max((len(payload) + step - 1) // step, 1)
            for seq in range(n_chunks):
                _maybe_inject_ckpt_fault(self._rank, ckpt_no, seq)
                chunk = payload[seq * step:(seq + 1) * step]
                resp = client.request(
                    ("ckpt_chunk", epoch, ckpt_no, self._rank, seq, chunk))
                assert resp and resp[0] == "ok", resp
                _CHUNKS.inc()
                _BYTES.inc(len(chunk))
        resp = client.request(
            ("ckpt_end", epoch, ckpt_no, self._rank, n_chunks, digest))
        assert resp and resp[0] == "ok", resp
        sealed_no = int(resp[1])
        self.last_sealed = sealed_no
        _STREAM_S.observe(time.monotonic() - t0)
        if sealed_no >= ckpt_no:
            from ..obs import flightrec
            flightrec.record(flightrec.EV_CKPT_SEAL, ordinal=sealed_no)

    def _client_or_dial(self) -> BasicClient:
        if self._client is None:
            self._client = BasicClient(
                self._addr, secret=self._secret, attempts=3,
                timeout_s=self._timeout_s)
        return self._client

    def _drop_client(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass


def observe_commit_stall(seconds: float) -> None:
    """State.commit() reports the stall the training loop actually paid
    (both paths — the bench compares the two histograms)."""
    _STALL_S.observe(seconds)
