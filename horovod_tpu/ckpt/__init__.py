"""Checkpoint plane (docs/checkpoint.md): async sharded commits,
digest-sealed epochs, live train-to-serve weight swaps.

Three coordinated pieces, all on the existing control-plane machinery:

* :mod:`~horovod_tpu.ckpt.committer` — the rank-side
  :class:`AsyncCommitter`: ``State.commit()`` stalls for O(snapshot)
  and a background thread streams the chunked tree over its own
  identified connection (the PR-9 second-connection pattern).
* :mod:`~horovod_tpu.ckpt.store` — the driver-side :class:`SealLedger`
  (a commit is *sealed* only when every rank's shard digest arrived and
  agrees and the payload is complete; restore always lands on the last
  sealed commit, bit-exactly) and the gateway :class:`TicketJournal`.
* :mod:`~horovod_tpu.ckpt.files` — the filesystem leg (rank-0 orbax
  save + broadcast-consistent restore), relocated from the legacy
  top-level ``horovod_tpu/checkpoint.py``.
"""

from .committer import (AsyncCommitter, observe_commit_stall,  # noqa: F401
                        parse_ckpt_fault)
from .files import restore, save  # noqa: F401
from .store import SealLedger, TicketJournal  # noqa: F401
