"""Flight recorder + cross-rank black-box incident dumps (docs/blackbox.md).

The structured escalations this repo grew (``RanksAbortedError``,
``ConsensusError``, ``NonFiniteGradError``, ``StallEscalation``,
``ServingAbortedError``) name *who* failed but discard the event history
that explains *how*. PR 6's tracing is steady-state and file-based —
opt-in, unbounded, dead when the process dies mid-write. This module is
the aircraft-flight-recorder shape production needs instead: an
always-on (``HOROVOD_FLIGHTREC=0`` to disable), fixed-capacity ring
buffer on every rank recording the lifecycle of every control- and
data-plane transition with its aligning ordinals — cycle ordinals the
way 1810.11112 correlates per-rank collective timing, flush ordinals
(PR 9), sentry batch ordinals (PR 8), consensus window ordinals — and,
on any world escalation, a best-effort, time-bounded cross-rank
collection into one ``blackbox-<world>-<epoch>.json`` incident file that
``tools/blackbox_report.py`` merges and classifies.

Layering, matching ``obs/tracing.py``: the module level is deliberately
STDLIB-ONLY (package imports live inside the functions that need them),
so ``tools/blackbox_report.py`` can load this file directly on
workstations where importing the package would pull in jax — the
classifier half is pure dict math over a saved incident document. The
wire-tag regexes below are deliberate small copies of the
``core/status.py`` format contract (pinned against it by
``tests/test_zzflightrec.py``): the classifier must run with nothing but
the incident file in hand.

Event record layout (one preallocated slot each, mutated in place —
O(1) append under a lock, allocation-free on the hot path like the PR 5
registry): ``[ts_us, kind, ordinal, aux, detail]`` where ``ts_us`` is
the local monotonic clock in microseconds (the same clock every
``Timeline`` span carries; the dump stamps the rank's PR 6 ``ClockSync``
offset beside the tail so merged incidents share one timebase),
``ordinal`` the kind's aligning ordinal (cycle / flush / sentry batch /
consensus window / chaos / epoch; -1 when none), ``aux`` a secondary
integer (cache generation, in-flight depth), and ``detail`` a short
string (tensor name, fault kind, reason prefix).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

# -- metric family names (docs/metrics.md "flight recorder" section) -----------
FAMILY_EVENTS = "horovod_flightrec_events_total"
FAMILY_DROPPED = "horovod_flightrec_dropped_events_total"
FAMILY_DUMPS = "horovod_flightrec_dumps_total"
FAMILY_DUMP_FAILURES = "horovod_flightrec_dump_failures_total"

# -- event-kind vocabulary (docs/blackbox.md event schema) ---------------------
EV_ENQUEUE = "enqueue"            # ordinal=-1, detail=tensor name
EV_NEGOTIATE = "negotiate"        # ordinal=cycle ordinal (submit)
EV_RESPONSE = "response"          # ordinal=cycle ordinal, aux=cache gen
EV_CACHE_HIT = "cache_hit"        # ordinal=cycle ordinal, aux=cache gen
EV_FLUSH_START = "flush_start"    # ordinal=flush cycle, aux=in-flight depth
EV_FLUSH_END = "flush_end"        # ordinal=flush cycle
EV_SENTRY = "sentry"              # ordinal=batch ordinal, detail=policy:kind
EV_CONSENSUS_SEAL = "consensus_seal"  # ordinal=window ordinal
EV_RECONNECT = "reconnect"        # aux=attempt number
EV_RECONNECT_HEALED = "reconnect_healed"
EV_CHAOS = "chaos"                # ordinal=injector ordinal, detail=kind
EV_EPOCH = "epoch"                # ordinal=elastic world epoch
EV_COMMIT = "commit"              # ordinal=elastic commit number
EV_ELASTIC_FAIL = "elastic_fail"  # ordinal=epoch, detail=exception type
EV_ELASTIC_RELAUNCH = "elastic_relaunch"  # ordinal=new epoch
EV_SERVING_BATCH = "serving_batch"      # ordinal=batch ordinal
EV_SERVING_DIGEST = "serving_digest"    # ordinal=batch ordinal
EV_SERVING_DISPATCH = "serving_dispatch"  # ordinal=batch ordinal (driver)
EV_CKPT_SUBMIT = "ckpt_submit"    # ordinal=ckpt commit number (async submit)
EV_CKPT_SEAL = "ckpt_seal"        # ordinal=sealed commit number
EV_CKPT_RESTORE = "ckpt_restore"  # ordinal=restored commit number,
#                                   detail=sealed/legacy source
EV_SERVING_SWAP = "serving_swap"  # ordinal=weights version (hot swap)
EV_FUSED_APPLY = "fused_apply"    # ordinal=cycle, detail=fused/split
EV_TENSORWATCH = "tensorwatch"    # ordinal=batch, detail=codec:SNRdb —
#                                   a sampled decode SNR near or below
#                                   the evidence floor (docs/tensorwatch.md)
EV_ESCALATE = "escalate"          # coordinator escalation, detail=reason
EV_ABORT = "abort"                # rank-side abort, detail=reason
# surgical recovery plane (docs/recovery.md)
EV_RECOVER_PARK = "recover_park"  # ordinal=failed epoch (survivor parks)
EV_RECOVER_WARM = "recover_warm"  # ordinal=new epoch (warm re-entry)
EV_SUCCESSION = "succession"      # ordinal=island id (standby activates)

# Cycle-ordinal-bearing kinds: the classifier's cross-rank alignment
# ground truth (every rank joins every cycle exactly once and in order).
CYCLE_KINDS = (EV_NEGOTIATE, EV_RESPONSE, EV_CACHE_HIT)

# Data-plane chaos kinds — a deliberate small copy of
# ``chaos.DATA_KINDS`` (pinned against it by tests, like the wire-tag
# regexes below): a non-finite verdict may only blame a rank whose
# stream recorded a DATA injection. A co-occurring wire fault (delay /
# drop / close / refuse) on a lower rank is harmless to the numerics and
# must not steal the attribution.
DATA_CHAOS_KINDS = ("nan", "flipbits")

# Wire-tag patterns — small deliberate copies of the core/status.py
# format contract (format_aborted_ranks / format_consensus /
# format_nonfinite), pinned by tests so they cannot drift: the classifier
# runs on jax-less boxes from nothing but the incident file.
_ABORTED_RE = re.compile(r"\[aborted ranks: ([0-9][0-9,\s]*)\]")
_CONSENSUS_RE = re.compile(r"\[consensus mismatch: ranks ([0-9][0-9,\s]*)\]")
_NONFINITE_RE = re.compile(r"\[non-finite grad: step (\d+)\]")
_EXITED_RE = re.compile(r"rank (\d+) (?:exited mid-job|disconnected)")
# hierarchical negotiation tree (docs/hierarchy.md): island-scoped abort
# texts — a sub-coordinator death names the island's whole member roster,
# an inter-level desync or digest-fold mismatch names the island, and the
# postmortem verdict must surface that scope instead of a single rank
_ISLAND_DEAD_RE = re.compile(
    r"island (\d+) sub-coordinator \(rank (\d+)\) exited")
_ISLAND_DESYNC_RE = re.compile(r"desync between islands: island (\d+)")
_ISLAND_FOLD_RE = re.compile(r"island (\d+) consensus digest fold mismatch")

DEFAULT_CAPACITY = 4096
DEFAULT_DUMP_TIMEOUT_S = 5.0


class FlightRecorder:
    """Fixed-capacity event ring. Slots are preallocated lists mutated in
    place; ``record`` is one lock acquire plus five item writes — never
    an allocation — so it can sit beside every hot-path transition. When
    ``enabled`` is False every producer call returns after one attribute
    check (the zero-overhead contract of ``HOROVOD_FLIGHTREC=0``)."""

    __slots__ = ("enabled", "capacity", "_slots", "_next", "_lock",
                 "recorded", "_c_events", "_c_dropped")

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True, counters=None) -> None:
        self.capacity = max(int(capacity), 1)
        self.enabled = bool(enabled)
        self._slots: List[list] = (
            [[0, "", -1, -1, ""] for _ in range(self.capacity)]
            if self.enabled else [])
        self._next = 0
        self.recorded = 0
        self._lock = threading.Lock()
        self._c_events = counters[0] if counters else None
        self._c_dropped = counters[1] if counters else None

    def record(self, kind: str, ordinal: int = -1, aux: int = -1,
               detail: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            slot = self._slots[self._next]
            slot[0] = time.monotonic_ns() // 1000
            slot[1] = kind
            slot[2] = ordinal
            slot[3] = aux
            slot[4] = detail
            self._next = (self._next + 1) % self.capacity
            self.recorded += 1
            dropped = self.recorded > self.capacity
        # counters inc OUTSIDE the ring lock: no nested lock acquisition
        # on the hot path (the lock-order discipline of docs/analysis.md)
        if self._c_events is not None:
            self._c_events.inc()
            if dropped:
                self._c_dropped.inc()

    def tail(self) -> List[list]:
        """The retained events, oldest first (copies — safe to mutate)."""
        with self._lock:
            n = min(self.recorded, self.capacity)
            start = (self._next - n) % self.capacity
            return [list(self._slots[(start + i) % self.capacity])
                    for i in range(n)]

    @property
    def dropped(self) -> int:
        return max(0, self.recorded - self.capacity)

    def stats(self) -> dict:
        return {"enabled": self.enabled, "capacity": self.capacity,
                "recorded": self.recorded, "dropped": self.dropped}


# -- process-global recorder ---------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def _counters():
    """The one registration site for the flight-recorder families
    (package import kept function-level; see module docstring)."""
    from .registry import registry as _metrics

    reg = _metrics()
    return (
        reg.counter(FAMILY_EVENTS,
                    "Events recorded into this rank's flight-recorder "
                    "ring (all kinds)"),
        reg.counter(FAMILY_DROPPED,
                    "Flight-recorder events overwritten by ring wrap "
                    "before any dump could retain them"),
        reg.counter(FAMILY_DUMPS,
                    "Black-box incident files written by this process "
                    "(coordinator cross-rank dumps and rank-local "
                    "degrades both count)"),
        reg.counter(FAMILY_DUMP_FAILURES,
                    "Incident pushes or file writes that failed "
                    "(best-effort by contract: the failure is counted "
                    "and logged, never raised into the abort path)"),
    )


def _build_recorder() -> FlightRecorder:
    from ..core.config import HOROVOD_FLIGHTREC, HOROVOD_FLIGHTREC_EVENTS

    enabled = os.environ.get(HOROVOD_FLIGHTREC, "1").strip().lower() \
        not in ("0", "false", "off")
    capacity = DEFAULT_CAPACITY
    raw = os.environ.get(HOROVOD_FLIGHTREC_EVENTS, "")
    if raw:
        try:
            capacity = int(raw)
        except ValueError:
            capacity = DEFAULT_CAPACITY
    counters = _counters()[:2] if enabled else None
    return FlightRecorder(capacity, enabled, counters=counters)


def recorder() -> FlightRecorder:
    """The process-global flight recorder (built from env on first use)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            rec = _recorder
            if rec is None:
                rec = _recorder = _build_recorder()
    return rec


def record(kind: str, ordinal: int = -1, aux: int = -1,
           detail: str = "") -> None:
    """Module-level hot-path producer: one global read, one enabled
    check, then the ring append. Disabled recorders return immediately
    with zero allocation."""
    rec = _recorder
    if rec is None:
        rec = recorder()
    if rec.enabled:
        rec.record(kind, ordinal, aux, detail)


def reset_for_tests() -> None:
    """Rebuild the recorder from the current env (tests flip the knob
    in-process; production processes build exactly one)."""
    global _recorder, _dump_fired, _push_ctx, _local_warned
    with _recorder_lock:
        _recorder = None
    with _dump_lock:
        _dump_fired = False
        _push_ctx = None
        _local_warned = False


# -- dump plumbing (rank side) -------------------------------------------------

_push_ctx: Optional[dict] = None
_dump_lock = threading.Lock()
_dump_fired = False
_local_warned = False


def dump_timeout_s() -> float:
    from ..core.config import HOROVOD_FLIGHTREC_DUMP_TIMEOUT

    raw = os.environ.get(HOROVOD_FLIGHTREC_DUMP_TIMEOUT, "")
    try:
        return float(raw) if raw else DEFAULT_DUMP_TIMEOUT_S
    except ValueError:
        return DEFAULT_DUMP_TIMEOUT_S


def launch_grace_s() -> float:
    """How long the launcher should let surviving ranks drain after a
    rank dies hard (nonzero exit) before terminating them. A rank that
    dies by ``os._exit``/``SIGKILL`` makes the launcher's fail-fast
    teardown SIGTERM the survivors within milliseconds — destroying the
    coordinator's incident collector AFTER the dying world's tails were
    pushed, so no dump survives. The grace bounds a drain window on the
    FAILURE path only: survivors that exit on their own (they abort once
    the coordinator declares the rank dead, then their interpreter exit
    joins the non-daemon collector) end it early; clean worlds never
    enter it. 0 when the recorder is disabled or the knob says 0."""
    if not recorder().enabled:
        return 0.0
    from ..core.config import (
        HOROVOD_FLIGHTREC_LAUNCH_GRACE,
        HOROVOD_RECONNECT_WINDOW,
    )

    raw = os.environ.get(HOROVOD_FLIGHTREC_LAUNCH_GRACE, "")
    if raw:
        try:
            return max(0.0, float(raw))
        except ValueError:
            pass
    window = 5.0
    try:
        window = float(os.environ.get(HOROVOD_RECONNECT_WINDOW, "") or 5.0)
    except ValueError:
        pass
    return min(window + dump_timeout_s() + 1.0, 15.0)


def dump_dir() -> str:
    """Incident-file directory: ``HOROVOD_FLIGHTREC_DIR``, else beside
    the timeline artifact, else the working directory."""
    from ..core.config import HOROVOD_FLIGHTREC_DIR, HOROVOD_TIMELINE

    explicit = os.environ.get(HOROVOD_FLIGHTREC_DIR, "")
    if explicit:
        return explicit
    timeline = os.environ.get(HOROVOD_TIMELINE, "")
    if timeline:
        return os.path.dirname(os.path.abspath(timeline)) or "."
    return "."


def incident_filename(world_id, epoch, rank: Optional[int] = None) -> str:
    wid = re.sub(r"[^A-Za-z0-9]+", "-", str(world_id) or "world")
    wid = wid.strip("-") or "world"
    base = f"blackbox-{wid}-{epoch}"
    if rank is not None:
        base += f".rank{rank}"
    return base + ".json"


def metrics_values() -> Dict[str, float]:
    """Compact counter/gauge map of the local registry — the "metrics
    deltas" section of an incident file (histograms are omitted: the
    incident story lives in counters, and the full distributions remain
    on the metrics plane)."""
    from .registry import registry as _metrics

    out: Dict[str, float] = {}
    for name, fam in _metrics().snapshot().items():
        if fam.get("type") == "histogram":
            continue
        total = 0.0
        for sample in fam.get("samples", []):
            total += sample.get("value", 0) or 0
        out[name] = total
    return out


def rank_payload(reason: str,
                 snapshot_fn: Optional[Callable[[], dict]] = None) -> dict:
    """What one rank ships on abort: its event tail, the engine state
    snapshot (the same one ``hvd.health_report()`` serves — one
    definition), its registry values, and its PR 6 clock offset so the
    merge tool can fold tails onto one timebase."""
    values = {}
    try:
        values = metrics_values()
    except Exception:  # noqa: BLE001 - best-effort by contract
        pass
    payload = {
        "events": recorder().tail(),
        "stats": recorder().stats(),
        "error": str(reason)[:1000] if reason else "",
        "clock_offset_us": values.get("horovod_clock_offset_us"),
        "metrics": values,
    }
    if snapshot_fn is not None:
        try:
            payload["snapshot"] = snapshot_fn()
        except Exception as exc:  # noqa: BLE001 - snapshot is best-effort
            payload["snapshot"] = {"error": str(exc)}
    return payload


def arm_push(addr, secret, world_id: str, rank: int, epoch: int,
             snapshot_fn: Optional[Callable[[], dict]] = None,
             local_only: bool = False) -> None:
    """Register this rank's dump context (the engine calls this at init).
    ``local_only`` is the native-controller degrade: the binary wire
    predates the ``flightrec`` RPC, so the dump is written rank-locally
    instead of collected by the coordinator (warned once at dump time,
    the established degrade pattern)."""
    global _push_ctx, _dump_fired
    with _dump_lock:
        _push_ctx = {"addr": addr, "secret": secret, "world_id": world_id,
                     "rank": rank, "epoch": epoch,
                     "snapshot_fn": snapshot_fn, "local_only": local_only}
        _dump_fired = False
    record(EV_EPOCH, ordinal=int(epoch))


def disarm_push() -> None:
    global _push_ctx
    with _dump_lock:
        _push_ctx = None


def on_structured_error(reason: str) -> None:
    """``Status.raise_if_error`` hook: a structured world escalation
    (RanksAborted / Consensus / NonFiniteGrad) is about to raise — ship
    this rank's tail before the exception unwinds. Idempotent and
    unarmed-safe (tests constructing structured errors directly trigger
    nothing)."""
    try:
        trigger_dump(reason)
    except Exception:  # noqa: BLE001 - never worsen the failure path
        pass


def trigger_dump(reason: str) -> Optional[str]:
    """Best-effort rank-side incident shipment, once per armed world:
    push the event tail to the coordinator's ``flightrec`` store (the
    coordinator's collector folds every rank's into one incident file),
    or write a rank-local file on the native-controller degrade / when
    the coordinator is already gone. Returns the local path when one was
    written."""
    global _dump_fired, _local_warned
    rec = recorder()
    if not rec.enabled:
        return None
    with _dump_lock:
        ctx = _push_ctx
        if ctx is None or _dump_fired:
            return None
        _dump_fired = True
    record(EV_ABORT, detail=str(reason)[:200])
    payload = rank_payload(reason, ctx.get("snapshot_fn"))
    if ctx["local_only"]:
        from ..core.logging import LOG

        if not _local_warned:
            _local_warned = True
            LOG.warning(
                "flight recorder: this controller wire predates the "
                "flightrec collection RPC; writing a rank-local incident "
                "dump (set HOROVOD_NATIVE_CONTROLLER=0 for one merged "
                "cross-rank file).")
        return _write_local(ctx, reason, payload)
    try:
        from ..runner.network import BasicClient

        client = BasicClient(ctx["addr"], secret=ctx["secret"],
                             timeout_s=min(dump_timeout_s(), 5.0),
                             attempts=1)
        try:
            client.request(("flightrec", ctx["rank"], payload,
                            ctx["world_id"]))
        finally:
            client.close()
        return None
    except Exception as exc:  # noqa: BLE001 - coordinator gone: degrade
        from ..core.logging import LOG

        _counters()[3].inc()
        LOG.warning(
            "flight recorder: incident push to the coordinator failed "
            "(%s); writing a rank-local dump instead", exc)
        return _write_local(ctx, reason, payload)


def _write_local(ctx: dict, reason: str, payload: dict) -> Optional[str]:
    doc = {
        "format": 1,
        "world_id": ctx["world_id"],
        "epoch": ctx["epoch"],
        "size": None,
        "reason": str(reason)[:1000],
        "written_by": f"rank-local:{ctx['rank']}",
        "written_at_unix": time.time(),
        "ranks": {str(ctx["rank"]): payload},
        "coordinator": None,
    }
    return write_incident(doc, rank=ctx["rank"])


def write_incident(doc: dict, rank: Optional[int] = None) -> Optional[str]:
    """Atomic incident-file write (tmp + rename); counted, never raised."""
    from ..core.logging import LOG

    try:
        directory = dump_dir()
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, incident_filename(
            doc.get("world_id"), doc.get("epoch"), rank))
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    except Exception as exc:  # noqa: BLE001 - best-effort by contract
        _counters()[3].inc()
        LOG.warning("flight recorder: incident dump failed: %s", exc)
        return None
    _counters()[2].inc()
    LOG.warning("flight recorder: black-box incident dump written to %s",
                path)
    return path


def coordinator_collect(reason: str, size: int, world_id: str, epoch: int,
                        store_get: Callable[[], Dict[int, dict]],
                        snapshot_fn: Optional[Callable[[], dict]] = None
                        ) -> Optional[threading.Thread]:
    """Coordinator-side incident collection: a bounded NON-daemon thread
    (interpreter exit joins it, so the dump lands even when the
    coordinator process dies right after the abort) waits up to
    ``HOROVOD_FLIGHTREC_DUMP_TIMEOUT_S`` for per-rank tails in
    ``store_get()`` — exiting early once all ``size`` ranks pushed, or
    once pushes stop arriving (a dead rank never pushes; waiting its full
    timeout on every abort would tax every escalation test) — then writes
    one merged incident file."""
    if not recorder().enabled:
        return None
    timeout = dump_timeout_s()
    settle = min(1.0, timeout / 2.0)

    def _run() -> None:
        deadline = time.monotonic() + timeout
        last_n = -1
        last_change = time.monotonic()
        while time.monotonic() < deadline:
            n = len(store_get())
            if n >= size:
                break
            now = time.monotonic()
            if n != last_n:
                last_n, last_change = n, now
            elif n > 0 and now - last_change > settle:
                break
            time.sleep(0.05)
        ranks = store_get()
        coord = {"events": recorder().tail()}
        try:
            coord["metrics"] = metrics_values()
        except Exception:  # noqa: BLE001
            pass
        if snapshot_fn is not None:
            try:
                coord["snapshot"] = snapshot_fn()
            except Exception as exc:  # noqa: BLE001
                coord["snapshot"] = {"error": str(exc)}
        doc = {
            "format": 1,
            "world_id": world_id,
            "epoch": epoch,
            "size": size,
            "reason": str(reason)[:1000],
            "written_by": "coordinator",
            "written_at_unix": time.time(),
            "ranks": {str(r): p for r, p in sorted(ranks.items())},
            "coordinator": coord,
        }
        write_incident(doc)

    thread = threading.Thread(target=_run, name="horovod-flightrec-dump",
                              daemon=False)
    thread.start()
    return thread


# -- incident classification (stdlib-only: runs from the file alone) -----------


def merge_incidents(docs: List[dict]) -> dict:
    """Fold one or more incident documents (a coordinator dump, or the
    per-rank files of the rank-local degrade) into one: ranks union
    (first writer wins per rank), first non-empty reason wins, first
    coordinator section wins."""
    merged: dict = {"format": 1, "world_id": None, "epoch": None,
                    "size": None, "reason": "", "ranks": {},
                    "coordinator": None, "written_by": []}
    for doc in docs:
        for key in ("world_id", "epoch", "size"):
            if merged[key] is None and doc.get(key) is not None:
                merged[key] = doc[key]
        if not merged["reason"] and doc.get("reason"):
            merged["reason"] = doc["reason"]
        for rank, payload in (doc.get("ranks") or {}).items():
            merged["ranks"].setdefault(str(rank), payload)
        if merged["coordinator"] is None and doc.get("coordinator"):
            merged["coordinator"] = doc["coordinator"]
        merged["written_by"].append(doc.get("written_by", "?"))
    return merged


def _parse_int_list(text: str) -> List[int]:
    return sorted({int(tok) for tok in text.replace(",", " ").split()})


def classify_incident(doc: dict) -> dict:
    """Classify one (merged) incident document: the last cycle ordinal
    every rank agrees on, the first diverging rank and the event where
    its stream forks, the parked-rendezvous table, and a one-line
    verdict (``stall@rank2 cycle 417``, ``consensus-fork@rank1 window
    12``, ``desync: flush_ordinal``, ``dead@rank1 cycle 9``,
    ``nonfinite@rank1 step 3``)."""
    ranks = {int(r): p or {} for r, p in (doc.get("ranks") or {}).items()}
    last_cycle: Dict[int, int] = {}
    for rank, payload in ranks.items():
        cycles = [e[2] for e in payload.get("events", [])
                  if len(e) >= 3 and e[1] in CYCLE_KINDS]
        if cycles:
            last_cycle[rank] = max(cycles)
    agreed = min(last_cycle.values()) if last_cycle else None
    diverging = None
    fork_event = None
    if len(last_cycle) >= 2 and len(set(last_cycle.values())) > 1:
        diverging = min(sorted(last_cycle), key=lambda r: last_cycle[r])
        events = ranks[diverging].get("events", [])
        fork_event = list(events[-1]) if events else None
    reason = doc.get("reason") or ""
    if not reason:
        for rank in sorted(ranks):
            if ranks[rank].get("error"):
                reason = ranks[rank]["error"]
                break
    # The coordinator's dump reason can be the generic "rank N exited"
    # while the SPECIFIC structured tag (consensus / non-finite / desync)
    # only survives in a rank's error field: the tag search spans both.
    search = "\n".join([reason] + [str(ranks[r].get("error") or "")
                                   for r in sorted(ranks)])
    coord = doc.get("coordinator") or {}
    parked = (coord.get("snapshot") or {}).get("pending_rendezvous")
    # Ranks whose streams carry fault injections: under chaos, the
    # injected rank is the one that RECORDED the injection — a NaN
    # propagates through the sum, so post-combine evidence (sentry kinds)
    # implicates every rank equally; the injection event does not.
    chaos_ranks = sorted(
        rank for rank in ranks
        if any(len(e) >= 2 and e[1] == EV_CHAOS
               for e in ranks[rank].get("events", [])))

    def seal_ordinals(rank: int) -> List[int]:
        return [e[2] for e in ranks[rank].get("events", [])
                if len(e) >= 3 and e[1] == EV_CONSENSUS_SEAL]

    cycle_s = "?" if agreed is None else str(agreed)
    verdict = f"abort cycle {cycle_s}"
    m = _CONSENSUS_RE.search(search)
    if m is not None:
        bad = _parse_int_list(m.group(1))
        seals = [max(seal_ordinals(r), default=0) for r in sorted(ranks)]
        window = min(seals) if seals else 0
        verdict = (f"consensus-fork@rank{bad[0]} window {window}"
                   if bad else f"consensus-fork window {window}")
    elif _NONFINITE_RE.search(search) is not None:
        step = int(_NONFINITE_RE.search(search).group(1))
        data_chaos = sorted(
            rank for rank in ranks
            if any(len(e) >= 5 and e[1] == EV_CHAOS and
                   str(e[4]) in DATA_CHAOS_KINDS
                   for e in ranks[rank].get("events", [])))
        culprit = data_chaos[0] if data_chaos else None
        if culprit is None:
            # no injection evidence: a genuinely non-finite gradient —
            # name a rank only when exactly one saw a LOCAL (non-peer)
            # fault; a sum-propagated NaN implicates everyone equally
            local = [rank for rank in sorted(ranks)
                     if any(len(e) >= 5 and e[1] == EV_SENTRY and
                            e[2] == step and
                            not str(e[4]).endswith(":peer")
                            for e in ranks[rank].get("events", []))]
            if len(local) == 1:
                culprit = local[0]
        verdict = (f"nonfinite@rank{culprit} step {step}"
                   if culprit is not None else f"nonfinite step {step}")
    elif _ISLAND_FOLD_RE.search(search) is not None:
        island = _ISLAND_FOLD_RE.search(search).group(1)
        verdict = f"consensus-fold@island{island}"
    elif _ISLAND_DESYNC_RE.search(search) is not None:
        island = _ISLAND_DESYNC_RE.search(search).group(1)
        verdict = f"desync: island{island} flush_ordinal"
    elif "cycle stream desync" in search or "flush_ordinal" in search:
        verdict = "desync: flush_ordinal"
    elif "stalled past" in reason or "Stalled ops" in reason:
        named = _ABORTED_RE.search(reason)
        stalled = _parse_int_list(named.group(1)) if named else []
        who = f"rank{stalled[0]}" if stalled else "rank?"
        verdict = f"stall@{who} cycle {cycle_s}"
    else:
        m_isl = _ISLAND_DEAD_RE.search(search)
        named = _ABORTED_RE.search(reason)
        if named is None:
            named = _EXITED_RE.search(reason)
            dead = [int(named.group(1))] if named else []
        else:
            dead = _parse_int_list(named.group(1))
        if m_isl is not None:
            # checked before the rank verdicts: the sub-coordinator text
            # also matches _EXITED_RE, and the postmortem must lead with
            # the TREE scope (a whole island's members went unreachable)
            verdict = (f"island-dead@island{m_isl.group(1)} "
                       f"cycle {cycle_s}")
        elif dead:
            verdict = f"dead@rank{dead[0]} cycle {cycle_s}"
    return {
        "verdict": verdict,
        "reason": reason,
        "world_id": doc.get("world_id"),
        "epoch": doc.get("epoch"),
        "ranks_present": sorted(ranks),
        "chaos_ranks": chaos_ranks,
        "last_agreed_cycle": agreed,
        "per_rank_last_cycle": {str(r): c for r, c in
                                sorted(last_cycle.items())},
        "first_diverging_rank": diverging,
        "fork_event": fork_event,
        "parked_rendezvous": parked,
    }
