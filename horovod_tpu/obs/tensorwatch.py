"""Gradient numerics observatory (docs/tensorwatch.md).

PRs 5/6/14 made the control plane, wire, and failure paths observable;
this module is the missing layer for the *numerical content* of the data
plane. On sampled steps (``HOROVOD_TENSORWATCH_INTERVAL_STEPS``, 0 =
off) the engine hands each reduced allreduce batch to a
:class:`TensorWatch`, which measures per tensor:

* ``norm²``, ``max|g|``, nonzero count — the basic gradient-health
  scalars;
* a coarse log₂-magnitude occupancy histogram (which exponent decades
  the mass lives in — the dynamic-range picture a quantized wire cares
  about);
* the top-k mass-coverage curve — fraction of ``‖g‖²`` held by the top
  0.1 / 1 / 10 % entries, the sparse-readiness statistic deep-gradient-
  compression work (DGC-style top-k, see PAPERS.md) assumes you already
  have when sizing k;
* for every quantized codec *in play* (active on the batch, or
  consented via ``HOROVOD_AUTOTUNE_CODECS``): the decode-error SNR of
  this rank's LOCAL contribution — one encode→decode leg through the
  exact EQuARX block math (``Compression.*.roundtrip_error`` /
  ``ops.spmd.codec_roundtrip``, one definition pinned by tests), so
  wire error is measured where it happens, before any collective.

Results land three ways (docs/metrics.md "numerics observatory"):
bounded-cardinality registry families (only the K worst tensors carry
labels — ``HOROVOD_TENSORWATCH_WORST_K``), the FULL table via
``hvd.tensor_report()`` / ``GET /v1/tensors`` on the shared httpd, and
cross-rank via the existing metrics-publisher fold, where the per-rank
``horovod_tensor_prenorm2`` gauges double as a data-skew detector (a
rank whose local gradient norm persistently dwarfs its peers' is
feeding skewed data).

The loop closes through the **evidence gate**: the autotuner's lossy
codec knob (PR 7's ``HOROVOD_AUTOTUNE_CODECS`` consent) is no longer
operator faith — a lossy retune is only *proposed* once
``HOROVOD_TENSORWATCH_SNR_WINDOW`` consecutive sampled SNRs certify
above ``HOROVOD_TENSORWATCH_SNR_FLOOR_DB``, and an in-flight SNR
collapse reverts the codec through the policy's best-known-config
guard, decision-log audited with the evidence record.

Layering, matching ``obs/tracing.py``/``obs/flightrec.py``: the module
level is deliberately STDLIB-ONLY (numpy/package imports live inside
the functions that need them), so ``tools/tensorwatch_report.py`` can
load this file directly on jax-less workstations — the report fold
(:func:`build_tensor_report`) is pure dict math over a saved
``/metrics.json`` document.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# -- metric family names (docs/metrics.md "numerics observatory") --------------
FAMILY_SAMPLES = "horovod_tensorwatch_samples_total"
FAMILY_TENSORS = "horovod_tensorwatch_tensors"
FAMILY_NONFINITE = "horovod_tensorwatch_nonfinite_skips_total"
FAMILY_FLOOR_MISSES = "horovod_tensorwatch_snr_floor_misses_total"
FAMILY_CODEC_SNR = "horovod_codec_snr_db"
FAMILY_TOPK = "horovod_tensorwatch_topk_mass"
FAMILY_TENSOR_NORM2 = "horovod_tensor_norm2"
FAMILY_TENSOR_PRENORM2 = "horovod_tensor_prenorm2"
FAMILY_TENSOR_SNR = "horovod_tensor_snr_db"

# Sharding-plane families (registered in ``sharding/zero1.py`` — a
# deliberate small copy so this module's exec-fallback load never
# imports the package). When a metrics document carries
# ``horovod_shard_ranks`` the run is ZeRO-1 sharded, and the per-rank
# prenorm spread above doubles as a SHARD-IMBALANCE detector: under
# ZeRO-1 every rank both feeds its own data shard and owns a slice of
# the optimizer state, so a rank whose pre-reduce norms persistently
# dwarf its peers' is the rank whose partition is doing outsized work.
FAMILY_SHARD_RANKS = "horovod_shard_ranks"
FAMILY_SHARD_IMBALANCE = "horovod_shard_imbalance_ratio"

# The knob name the evidence gate guards on the autotune ladder — a
# deliberate small copy of ``tune.policy.KNOB_CODEC`` (cross-pinned by
# test), so this module's exec-fallback load never imports the package.
CODEC_KNOB = "codec"

# The quantized (lossy, SNR-measurable) codec tags — a deliberate small
# copy of the ``Compression.int8/fp8`` quantized set (cross-pinned by
# test): the observatory measures decode SNR only where a decode exists.
QUANTIZED_CODECS = ("int8", "fp8")

# The sparse (top-k) codec tags — a deliberate small copy of the
# ``Compression.topk`` sparse set (cross-pinned by test). Their
# "decode error" is the SELECTION error — the energy the top-k wire
# drops — so the measured SNR is exactly ``-10·log₁₀(1 - coverage)``
# of the topk-mass curve at the configured k, and the evidence gate's
# per-codec floor for them derives from the coverage floor
# (``coverage_floor_db``), not the quantized dB floor.
SPARSE_CODECS = ("topk",)

# Top-k mass-coverage curve points: fraction of ‖g‖² in the top q of
# entries (the ROADMAP sparse-wire item's k ∈ {0.1%, 1%, 10%} design
# points). Keys are the label values of FAMILY_TOPK.
TOPK_FRACTIONS = (("0.1", 0.001), ("1", 0.01), ("10", 0.1))

# Coarse log₂-magnitude occupancy histogram geometry: bin i counts
# elements with floor(log2|g|) == LOG2_HIST_MIN + i (clamped at both
# ends); zeros are excluded (size - nnz recovers them).
LOG2_HIST_MIN = -24
LOG2_HIST_BINS = 32

# Lossless measurements (zero error power) report this instead of +Inf:
# Infinity is not an RFC JSON token and would break the tools' one-line
# JSON contract (the PR 6 histogram-quantile lesson).
SNR_CAP_DB = 200.0

# SNRs within this many dB above the floor record a flightrec near-miss
# event (docs/blackbox.md EV_TENSORWATCH) — the postmortem breadcrumb
# for "the codec was one bad batch away from a revert".
NEAR_MISS_MARGIN_DB = 3.0

# Evidence-gate defaults — the single definition shared by the lazy
# env-built gate and core/config's resolved knobs (HOROVOD_TENSORWATCH_
# SNR_FLOOR_DB / _SNR_WINDOW must certify and revert against the same
# floor the observatory's floor-miss counter uses).
DEFAULT_SNR_FLOOR_DB = 20.0
DEFAULT_SNR_WINDOW = 5


def snr_db(signal_power: float, error_power: float) -> float:
    """THE single accounting definition of measured decode SNR (the
    ``Compression.wire_cost`` precedent): ``10·log₁₀(Σx² / Σe²)``,
    capped at :data:`SNR_CAP_DB` for lossless measurements and floored
    at 0-signal. A NON-FINITE power (a NaN gradient reached the sampled
    measurement — the observatory is pre-sentry by design, or an f32
    accumulator overflowed) reports 0 dB: conservative for the evidence
    gate (never certifies, de-certifies an applied codec) and keeps
    NaN/Infinity out of the gauges and the RFC-JSON surfaces (the PR 6
    lesson). Shared by the observatory, the compression bench's
    measured-SNR column, and the tests' NumPy reference."""
    signal_power = float(signal_power)
    error_power = float(error_power)
    if not (math.isfinite(signal_power) and math.isfinite(error_power)):
        return 0.0
    if signal_power <= 0.0:
        return 0.0
    if error_power <= 0.0:
        return SNR_CAP_DB
    return min(10.0 * math.log10(signal_power / error_power), SNR_CAP_DB)


def coverage_floor_db(coverage: float) -> float:
    """Topk-mass coverage floor (fraction of gradient energy the top-k
    selection must keep, ``HOROVOD_SPARSE_COVERAGE_FLOOR``) → the
    equivalent selection-SNR floor in dB: dropping ``1 - c`` of the
    energy is an SNR of ``-10·log₁₀(1 - c)``, so the sparse codec rides
    the SAME evidence-gate machinery as the quantized ones, with its
    floor derived from coverage instead of the quantized dB knob."""
    c = min(max(float(coverage), 0.0), 1.0)
    return snr_db(1.0, 1.0 - c)


def watch_codecs(cfg) -> Tuple[str, ...]:
    """The lossy codecs the observatory measures for a Config: the
    active ``HOROVOD_COMPRESSION`` codec when it is quantized or sparse,
    plus every ``HOROVOD_AUTOTUNE_CODECS`` consent candidate — measured
    BEFORE the tuner may apply them, which is what the evidence gate
    certifies on."""
    lossy = QUANTIZED_CODECS + SPARSE_CODECS
    out: List[str] = []
    active = getattr(cfg, "compression", "none")
    if active in lossy:
        out.append(active)
    for codec in getattr(cfg, "autotune_codecs", ()) or ():
        if codec in lossy and codec not in out:
            out.append(codec)
    return tuple(out)


# -- numpy measurement kernels (package-level callers only) --------------------


def _np_tensor_stats(arr) -> dict:
    """Per-tensor stats of one reduced gradient (host path). Float64
    accumulation: norm² of an fp16-ish tensor must not overflow the
    measurement. Read-only by construction — the observatory must be
    bit-exactness-neutral on the training result."""
    import numpy as np

    flat = np.asarray(arr).reshape(-1)
    n = int(flat.size)
    if n == 0 or not np.issubdtype(flat.dtype, np.floating):
        flat = np.asarray(flat, np.float64).reshape(-1)
    a = np.abs(flat.astype(np.float64, copy=False))
    a2 = a * a
    norm2 = float(a2.sum())
    absmax = float(a.max()) if n else 0.0
    nnz = int(np.count_nonzero(a))
    if nnz:
        nz = a[a > 0]
        e = np.clip(np.floor(np.log2(nz)), LOG2_HIST_MIN,
                    LOG2_HIST_MIN + LOG2_HIST_BINS - 1)
        hist = np.bincount((e - LOG2_HIST_MIN).astype(np.int64),
                           minlength=LOG2_HIST_BINS)
    else:
        hist = np.zeros(LOG2_HIST_BINS, np.int64)
    topk: Dict[str, float] = {}
    total = max(norm2, 1e-300)
    for key, q in TOPK_FRACTIONS:
        k = max(1, int(math.ceil(q * n))) if n else 1
        if n == 0:
            topk[key] = 0.0
        elif k >= n:
            topk[key] = 1.0
        else:
            topk[key] = float(np.partition(a2, n - k)[n - k:].sum() / total)
    return {"elems": n, "norm2": norm2, "absmax": absmax, "nnz": nnz,
            "log2_hist": [int(c) for c in hist], "topk": topk}


def _np_norm2(arr) -> float:
    """Norm² alone (host path) — the pre-reduce local contribution only
    needs this one scalar (the skew detector's input), so the sampled
    step must not pay the full stats program (sort/cumsum/histogram)
    twice per tensor."""
    import numpy as np

    flat = np.asarray(arr).reshape(-1)
    if flat.size == 0:
        return 0.0
    a = flat.astype(np.float64, copy=False)
    return float((a * a).sum())


def _np_codec_snr(arr, codec_name: str, size: int) -> Optional[float]:
    """Decode-error SNR of one local contribution through ``codec_name``
    (host path): ``Compression.*.roundtrip_error`` is the single
    definition of the encode→decode leg (docs/compression.md)."""
    import numpy as np

    from ..ops.compression import Compression

    codec = Compression.lookup(codec_name)
    if not (getattr(codec, "quantized", False)
            or getattr(codec, "sparse", False)):
        return None
    flat = np.asarray(arr).reshape(-1)
    if not np.issubdtype(flat.dtype, np.floating) or flat.size == 0:
        return None
    sp, ep = codec.roundtrip_error(flat.astype(np.float32, copy=False),
                                   size)
    return snr_db(sp, ep)


# -- evidence gate -------------------------------------------------------------


class EvidenceGate:
    """Measured-SNR consent gate for the autotuner's lossy codec knob
    (docs/tensorwatch.md): a codec is *certified* once ``window``
    consecutive sampled SNRs land at or above ``floor_db``; a sample
    below the floor de-certifies it, and — when the drop happened while
    certified — latches an in-flight *collapse* that the tuning plane
    consumes as a forced revert through the best-known-config guard.
    Collapse latches clear on re-certification, so a dip observed while
    the codec was never applied can't force a spurious revert later."""

    def __init__(self, floor_db: float, window: int) -> None:
        self.floor_db = float(floor_db)
        self.window = max(int(window), 1)
        self._lock = threading.Lock()
        self._history: Dict[str, object] = {}
        self._certified: Dict[str, bool] = {}
        self._certified_at: Dict[str, int] = {}
        self._collapsed: Dict[str, bool] = {}
        self._floors: Dict[str, float] = {}
        self.samples = 0
        self.floor_misses = 0

    def set_floor(self, codec: str, floor_db: float) -> None:
        """Per-codec floor override: the sparse codec certifies against
        its coverage-derived floor (``coverage_floor_db``) on the same
        gate the quantized codecs use the dB knob for."""
        with self._lock:
            self._floors[codec] = float(floor_db)

    def floor_for(self, codec: str) -> float:
        with self._lock:
            return self._floors.get(codec, self.floor_db)

    def observe(self, codec: str, value_db: float) -> None:
        with self._lock:
            floor = self._floors.get(codec, self.floor_db)
            self.samples += 1
            hist = self._history.get(codec)
            if hist is None:
                hist = self._history[codec] = deque(maxlen=self.window)
            hist.append(float(value_db))
            if value_db < floor:
                self.floor_misses += 1
                if self._certified.get(codec):
                    # in-flight collapse: the evidence that admitted the
                    # codec no longer holds — the tuning plane reverts
                    self._collapsed[codec] = True
                self._certified[codec] = False
            elif not self._certified.get(codec) and \
                    len(hist) == self.window and \
                    all(v >= floor for v in hist):
                self._certified[codec] = True
                self._certified_at[codec] = self.samples
                self._collapsed.pop(codec, None)

    def allows(self, codec: str) -> bool:
        with self._lock:
            return bool(self._certified.get(codec))

    def take_collapse(self, codec: str) -> bool:
        """Consume a latched in-flight collapse (the forced-revert
        trigger fires exactly once per collapse)."""
        with self._lock:
            return bool(self._collapsed.pop(codec, False))

    def evidence_record(self, codec: str) -> dict:
        """The audited evidence behind an admit/revert decision — rides
        the JSONL decision log (docs/autotune.md)."""
        with self._lock:
            hist = self._history.get(codec)
            return {
                "codec": codec,
                "floor_db": self._floors.get(codec, self.floor_db),
                "window": self.window,
                "snr_db_window": [round(v, 3) for v in hist] if hist
                else [],
                "certified": bool(self._certified.get(codec)),
                "certified_at_sample": self._certified_at.get(codec),
                "samples": self.samples,
                "floor_misses": self.floor_misses,
            }

    def state(self) -> dict:
        with self._lock:
            return {
                "floor_db": self.floor_db,
                "window": self.window,
                "samples": self.samples,
                "floor_misses": self.floor_misses,
                "certified": {c: bool(v) for c, v in
                              self._certified.items()},
                "collapsed": sorted(c for c, v in self._collapsed.items()
                                    if v),
            }


class PolicyGate:
    """Duck-typed adapter the :class:`tune.policy.TuningPolicy` consults
    (``propose_gate=``): ``allows``/``evidence`` guard the codec knob's
    proposals, ``maybe_revert`` converts a latched SNR collapse into the
    policy's evidence-audited revert. Non-codec knobs pass through."""

    def __init__(self, gate: EvidenceGate) -> None:
        self._gate = gate

    def allows(self, knob: str, value) -> bool:
        if knob != CODEC_KNOB or value in (None, "none"):
            return True
        return self._gate.allows(str(value))

    def evidence(self, knob: str, value) -> Optional[dict]:
        if knob != CODEC_KNOB or value in (None, "none"):
            return None
        return self._gate.evidence_record(str(value))

    def maybe_revert(self, policy):
        """Forced revert on in-flight collapse: when the policy's live
        codec is lossy and its gate evidence collapsed, roll the knob
        back to "none" through ``TuningPolicy.evidence_revert`` (the
        best-known-config guard's bookkeeping, decision-log audited).
        Returns the Decision, or None when nothing collapsed."""
        current = policy.config().get(CODEC_KNOB)
        if current in (None, "none"):
            return None
        codec = str(current)
        if not self._gate.take_collapse(codec):
            return None
        return policy.evidence_revert(
            CODEC_KNOB, "none", evidence=self._gate.evidence_record(codec))


_gate: Optional[EvidenceGate] = None
_gate_built = False
_gate_lock = threading.Lock()


def evidence_gate() -> Optional[EvidenceGate]:
    """The process-global evidence gate, built from env on first use —
    present iff the observatory is armed (interval > 0), so a world
    without tensorwatch keeps the PR 7 consent-only behavior
    byte-identically."""
    global _gate, _gate_built
    with _gate_lock:
        if not _gate_built:
            from ..core.config import (
                HOROVOD_TENSORWATCH_INTERVAL,
                HOROVOD_TENSORWATCH_SNR_FLOOR,
                HOROVOD_TENSORWATCH_SNR_WINDOW,
                _env_float,
                _env_int,
            )

            interval = max(_env_int(HOROVOD_TENSORWATCH_INTERVAL, 0), 0)
            if interval > 0:
                from ..core.config import HOROVOD_SPARSE_COVERAGE_FLOOR

                _gate = EvidenceGate(
                    _env_float(HOROVOD_TENSORWATCH_SNR_FLOOR,
                               DEFAULT_SNR_FLOOR_DB),
                    max(_env_int(HOROVOD_TENSORWATCH_SNR_WINDOW,
                                 DEFAULT_SNR_WINDOW), 1))
                # Sparse codecs certify against their coverage floor
                # (selection SNR, dB-equivalent) on the same gate.
                cov = _env_float(HOROVOD_SPARSE_COVERAGE_FLOOR, 0.95)
                for c in SPARSE_CODECS:
                    _gate.set_floor(c, coverage_floor_db(cov))
            _gate_built = True
        return _gate


def ensure_gate(floor_db: float, window: int) -> EvidenceGate:
    """Build (or return) the process-global gate with RESOLVED knob
    values — ``from_config`` routes the engine's ``Config`` here so the
    gate certifies/reverts against the same floor the observatory's
    floor-miss counter and near-miss events use, even for Configs
    constructed programmatically rather than from env. First build
    wins; in production both paths resolve the same env."""
    global _gate, _gate_built
    with _gate_lock:
        if _gate is None:
            _gate = EvidenceGate(floor_db, window)
            _gate_built = True
        return _gate


def policy_gate(cfg=None) -> Optional[PolicyGate]:
    """The autotuner's gate hook (``ops.autotuner``): None when the
    observatory is disarmed — the codec knob then behaves exactly as
    before this plane existed. With a resolved ``Config`` the gate is
    built from ITS knob values (``ensure_gate``): the Autotuner is
    constructed before the engine's observatory in the same
    ``Engine.__init__``, so a programmatic Config (env unset) must not
    latch the env-lazy singleton to None and silently run consent-only
    while the observatory feeds a gate nobody consults."""
    if cfg is not None:
        if getattr(cfg, "tensorwatch_interval_steps", 0) <= 0:
            return None
        return PolicyGate(ensure_gate(cfg.tensorwatch_snr_floor_db,
                                      cfg.tensorwatch_snr_window))
    gate = evidence_gate()
    return PolicyGate(gate) if gate is not None else None


def reset_for_tests() -> None:
    """Rebuild the gate from the current env (tests flip the knobs
    in-process; production processes build exactly one)."""
    global _gate, _gate_built
    with _gate_lock:
        _gate = None
        _gate_built = False


# -- the observatory -----------------------------------------------------------


def _families():
    """The one registration site for the observatory's metric families
    (package import kept function-level; see module docstring).
    Cardinality contract: the ``tensor`` label only ever carries the
    worst-K set (plus retired members pinned to 0), never one child per
    model tensor."""
    from .registry import registry as _metrics

    reg = _metrics()
    return {
        "samples": reg.counter(
            FAMILY_SAMPLES,
            "Allreduce batches the numerics observatory sampled"),
        "tensors": reg.gauge(
            FAMILY_TENSORS,
            "Distinct tensors in the live per-tensor numerics table "
            "(full table: hvd.tensor_report() / GET /v1/tensors)"),
        "nonfinite": reg.counter(
            FAMILY_NONFINITE,
            "Sampled tensors skipped because their measurement was "
            "non-finite (NaN gradients reach the observatory pre-"
            "sentry by design; the sentry is the diagnosis plane, "
            "these gauges must stay RFC-JSON-finite)"),
        "floor_misses": reg.counter(
            FAMILY_FLOOR_MISSES,
            "Sampled decode SNRs below HOROVOD_TENSORWATCH_SNR_FLOOR_DB",
            labels=("codec",)),
        "codec_snr": reg.gauge(
            FAMILY_CODEC_SNR,
            "Worst per-tensor decode-error SNR (dB) of the last sampled "
            "batch, by quantized codec (local encode->decode leg; "
            "lossless caps at 200)", labels=("codec",)),
        "topk": reg.gauge(
            FAMILY_TOPK,
            "Fraction of the sampled batch's gradient energy in the "
            "top k% entries (the sparse-readiness curve)",
            labels=("k",)),
        "norm2": reg.gauge(
            FAMILY_TENSOR_NORM2,
            "Post-reduce gradient norm-squared of the current worst-K "
            "tensors (0 = tensor left the worst set)",
            labels=("tensor",)),
        "prenorm2": reg.gauge(
            FAMILY_TENSOR_PRENORM2,
            "This rank's PRE-reduce local contribution norm-squared for "
            "the worst-K tensors — per-rank spread across the "
            "/metrics.json rank sections is the data-skew detector",
            labels=("tensor",)),
        "snr": reg.gauge(
            FAMILY_TENSOR_SNR,
            "Per-tensor decode SNR (dB, min across watched codecs) for "
            "the worst-K tensors", labels=("tensor",)),
    }


class TensorWatch:
    """Sampled per-tensor gradient telemetry for one engine.

    ``begin_batch`` advances the batch ordinal — batches execute in
    negotiated order, so ordinal N names the SAME batch on every rank
    and the sampling decision (``ordinal % interval == 0``) is
    rank-identical by construction, like the sentry's ordinals. The
    non-sampled path is integer arithmetic only (zero-allocation,
    pinned by the tracemalloc test); the disabled plane is no
    ``TensorWatch`` object at all (engine holds ``None``).

    ``probe``/``snr_probe`` are the XLA plane's compiled collective-free
    measurement programs (``XlaDataPlane.tensorwatch_stats`` /
    ``codec_snr``) — device-resident batches sync a handful of scalars
    instead of pulling buffers to host (the PR 8 two-scalar census
    pattern); numpy batches measure host-side."""

    def __init__(self, interval: int, size: int = 1, rank: int = 0,
                 snr_floor_db: float = 20.0, worst_k: int = 8,
                 codecs: Sequence[str] = (),
                 probe: Optional[Callable] = None,
                 snr_probe: Optional[Callable] = None,
                 norm2_probe: Optional[Callable] = None,
                 timeline=None,
                 gate: Optional[EvidenceGate] = None) -> None:
        self.interval = max(int(interval), 1)
        self.size = max(int(size), 1)
        self.rank = int(rank)
        self.snr_floor_db = float(snr_floor_db)
        self.worst_k = max(int(worst_k), 1)
        self.codecs = tuple(
            c for c in codecs
            if c in QUANTIZED_CODECS or c in SPARSE_CODECS)
        self._probe = probe
        self._snr_probe = snr_probe
        self._norm2_probe = norm2_probe
        self._timeline = timeline
        self._gate = gate if gate is not None else evidence_gate()
        self.ordinal = 0
        self.sampling = False
        self.samples = 0
        self._lock = threading.Lock()
        self._table: Dict[str, dict] = {}
        self._labeled: set = set()
        self._fams = None
        self._warned = False

    # -- hot path (every allreduce batch) -------------------------------------

    def begin_batch(self) -> None:
        """Advance the batch ordinal and decide whether this batch is
        sampled. Integer arithmetic only — the per-batch cost of an
        armed-but-idle observatory."""
        self.ordinal += 1
        self.sampling = self.ordinal % self.interval == 0

    # -- sampled path ---------------------------------------------------------

    def observe_batch(self, names: Sequence[str], locals_: Sequence,
                      results: Sequence, codec: str = "none") -> None:
        """Measure one sampled reduced batch: ``locals_`` are this
        rank's pre-reduce contributions (the SNR reference and the skew
        detector's input), ``results`` the reduced values as received
        (pre-sentry, like consensus). Strictly read-only; a measurement
        failure is counted-and-logged, never raised into the batch."""
        try:
            self._observe(list(names), list(locals_), list(results),
                          codec)
        except Exception as exc:  # noqa: BLE001 - observability must
            # never kill a batch it watches
            if not self._warned:
                self._warned = True
                from ..core.logging import LOG

                LOG.warning(
                    "tensorwatch: sampled measurement failed (%s); "
                    "telemetry for this batch is dropped", exc)

    def _measure_stats(self, arr) -> dict:
        import numpy as np

        if self._probe is not None and not isinstance(arr, np.ndarray):
            return self._probe(arr)
        return _np_tensor_stats(arr)

    def _measure_norm2(self, arr) -> float:
        import numpy as np

        if self._norm2_probe is not None and \
                not isinstance(arr, np.ndarray):
            return self._norm2_probe(arr)
        return _np_norm2(arr)

    def _measure_snr(self, arr, codec: str) -> Optional[float]:
        import numpy as np

        if self._snr_probe is not None and \
                not isinstance(arr, np.ndarray):
            sp, ep = self._snr_probe(arr, codec)
            return snr_db(sp, ep)
        return _np_codec_snr(arr, codec, self.size)

    def _observe(self, names: List[str], locals_: List, results: List,
                 codec: str) -> None:
        if self._fams is None:
            self._fams = _families()
        fams = self._fams
        self.samples += 1
        fams["samples"].inc()
        measured = []
        if codec in QUANTIZED_CODECS or codec in SPARSE_CODECS:
            measured.append(codec)
        for cand in self.codecs:
            if cand not in measured:
                measured.append(cand)
        rows: Dict[str, dict] = {}
        batch_norm2 = 0.0
        batch_topk = {key: 0.0 for key, _ in TOPK_FRACTIONS}
        batch_min_snr: Dict[str, float] = {}
        for name, local, result in zip(names, locals_, results):
            stats = self._measure_stats(result)
            # pre-reduce side: one scalar only (the skew detector's
            # input), never the full stats program a second time
            pre_norm2 = self._measure_norm2(local)
            if not (math.isfinite(stats["norm2"])
                    and math.isfinite(stats["absmax"])
                    and math.isfinite(pre_norm2)):
                # a NaN/Inf gradient reached the sampled measurement —
                # the observatory is PRE-sentry by design, so this is
                # expected under chaos/real nonfinite worlds; the
                # sentry diagnoses it, these gauges and the JSON
                # surfaces must stay finite (the PR 6 RFC lesson)
                fams["nonfinite"].inc()
                continue
            snrs: Dict[str, float] = {}
            for c in measured:
                value = self._measure_snr(local, c)
                if value is None:
                    continue
                snrs[c] = value
                prev = batch_min_snr.get(c)
                batch_min_snr[c] = value if prev is None \
                    else min(prev, value)
            row = dict(stats)
            row["prenorm2"] = pre_norm2
            row["snr_db"] = snrs
            row["sample_ordinal"] = self.ordinal
            row["codec"] = codec
            rows[name] = row
            batch_norm2 += stats["norm2"]
            for key, _ in TOPK_FRACTIONS:
                # energy-weighted fold of the per-tensor coverages: the
                # whole-batch curve without a cross-tensor sort
                batch_topk[key] += stats["topk"][key] * stats["norm2"]
        with self._lock:
            for name, row in rows.items():
                prev = self._table.get(name)
                if prev is not None:
                    row["batches_sampled"] = prev.get(
                        "batches_sampled", 0) + 1
                else:
                    row["batches_sampled"] = 1
                self._table[name] = row
            n_tensors = len(self._table)
            worst = self._worst_tensors()
        fams["tensors"].set(n_tensors)
        if batch_norm2 > 0:
            for key, _ in TOPK_FRACTIONS:
                fams["topk"].labels(k=key).set(
                    round(batch_topk[key] / batch_norm2, 6))
        for c, value in batch_min_snr.items():
            fams["codec_snr"].labels(codec=c).set(round(value, 3))
            floor = self.snr_floor_db
            if self._gate is not None:
                self._gate.observe(c, value)
                # the sparse codec's floor is its coverage bound in dB
                floor = self._gate.floor_for(c)
            if value < floor:
                fams["floor_misses"].labels(codec=c).inc()
            if value < floor + NEAR_MISS_MARGIN_DB:
                from . import flightrec as _flightrec

                _flightrec.record(_flightrec.EV_TENSORWATCH,
                                  self.ordinal,
                                  detail=f"{c}:{value:.1f}db")
        self._update_labels(worst)
        if self._timeline is not None and \
                getattr(self._timeline, "enabled", False):
            track = {"samples": self.samples, "tensors": n_tensors}
            if batch_min_snr:
                track["min_snr_db_x100"] = int(
                    min(batch_min_snr.values()) * 100)
            try:
                self._timeline.counter("tensorwatch", track)
            except Exception:  # noqa: BLE001 - audit never kills a batch
                pass

    def _worst_tensors(self) -> List[str]:
        """Caller holds ``_lock``. Worst-first order: lowest SNR first
        where SNR exists (the codec-risk view), largest norm² otherwise
        (the wire-sizing view)."""
        def key(item):
            name, row = item
            snrs = row.get("snr_db") or {}
            worst_snr = min(snrs.values()) if snrs else None
            return (0, worst_snr, -row["norm2"]) if worst_snr is not None \
                else (1, 0.0, -row["norm2"])

        ordered = sorted(self._table.items(), key=key)
        return [name for name, _ in ordered[:self.worst_k]]

    def _update_labels(self, worst: List[str]) -> None:
        """Refresh the bounded labeled families: current worst-K tensors
        carry live values, retired members pin to 0 (documented: 0 =
        "left the worst set"), and label admission hard-caps at 4*K over
        the process lifetime so a churning worst set can never grow the
        registry unboundedly — the full table stays in
        ``tensor_report()``."""
        fams = self._fams
        admitted = []
        for name in worst:
            if name not in self._labeled and \
                    len(self._labeled) >= 4 * self.worst_k:
                continue
            self._labeled.add(name)
            admitted.append(name)
        with self._lock:
            for name in self._labeled:
                row = self._table.get(name)
                if name in admitted and row is not None:
                    snrs = row.get("snr_db") or {}
                    fams["norm2"].labels(tensor=name).set(
                        round(row["norm2"], 6))
                    fams["prenorm2"].labels(tensor=name).set(
                        round(row["prenorm2"], 6))
                    if snrs:
                        fams["snr"].labels(tensor=name).set(
                            round(min(snrs.values()), 3))
                else:
                    fams["norm2"].labels(tensor=name).set(0)
                    fams["prenorm2"].labels(tensor=name).set(0)
                    fams["snr"].labels(tensor=name).set(0)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"interval": self.interval, "batches": self.ordinal,
                    "samples": self.samples,
                    "tensors": len(self._table),
                    "codecs": list(self.codecs),
                    "labeled": len(self._labeled)}

    def report(self) -> dict:
        """The FULL per-tensor table (no cardinality cap — this is the
        ``hvd.tensor_report()`` / ``GET /v1/tensors`` payload)."""
        with self._lock:
            table = {name: dict(row) for name, row in
                     self._table.items()}
            worst = self._worst_tensors()
        return {"enabled": True, "interval": self.interval,
                "batches": self.ordinal, "samples": self.samples,
                "codecs": list(self.codecs), "worst": worst,
                "tensors": table}


def from_config(cfg, size: int = 1, rank: int = 0, probe=None,
                snr_probe=None, norm2_probe=None,
                timeline=None) -> Optional[TensorWatch]:
    """Engine-side constructor: None when the interval knob is 0 — the
    disabled plane is no object at all, so the hot path pays one
    ``is not None`` check (the flightrec zero-overhead bar)."""
    interval = getattr(cfg, "tensorwatch_interval_steps", 0)
    if interval <= 0:
        return None
    codecs = watch_codecs(cfg)
    gate = ensure_gate(cfg.tensorwatch_snr_floor_db,
                       cfg.tensorwatch_snr_window)
    # The sparse codec's admit/revert floor is its coverage knob mapped
    # to dB (selection SNR = -10*log10(1-coverage)) — same gate, same
    # window, its own floor.
    cov = getattr(cfg, "sparse_coverage_floor", 0.95)
    for c in SPARSE_CODECS:
        gate.set_floor(c, coverage_floor_db(cov))
    return TensorWatch(
        interval, size=size, rank=rank,
        snr_floor_db=cfg.tensorwatch_snr_floor_db,
        worst_k=cfg.tensorwatch_worst_k,
        codecs=codecs, probe=probe, snr_probe=snr_probe,
        norm2_probe=norm2_probe, timeline=timeline, gate=gate)


def tensor_report() -> dict:
    """The live observatory table + gate state of this process
    (docs/tensorwatch.md): served as ``hvd.tensor_report()`` and
    ``GET /v1/tensors`` on the shared httpd routes. Safe to call any
    time; a disarmed world reports ``enabled: False``."""
    report: dict = {"enabled": False, "interval": 0, "batches": 0,
                    "samples": 0, "tensors": {}, "worst": [],
                    "codecs": [], "gate": None}
    watch = None
    try:
        from ..ops import engine as _engine_mod

        eng = _engine_mod._engine
        watch = getattr(eng, "_tensorwatch", None) \
            if eng is not None else None
    except Exception:  # noqa: BLE001 - pre-init callers get the shell
        watch = None
    if watch is not None:
        report.update(watch.report())
    gate = _gate
    if gate is not None:
        report["gate"] = gate.state()
    return report


# -- report fold (stdlib-only: runs from a /metrics.json file alone) -----------


def _labeled_values(families: dict, family: str, label: str
                    ) -> Dict[str, float]:
    fam = (families or {}).get(family)
    out: Dict[str, float] = {}
    for sample in (fam or {}).get("samples", []):
        key = (sample.get("labels") or {}).get(label)
        if key is not None:
            out[key] = sample.get("value", 0)
    return out


def build_tensor_report(ranks: Dict[int, dict], top: int = 20) -> dict:
    """Fold the per-rank ``horovod_tensor_*`` families of a
    ``/metrics.json`` document into the worst-SNR / highest-spread
    tensor table (``tools/tensorwatch_report.py``). Pure dict math —
    loadable without the package (the straggler_report precedent).

    Gauge value 0 means "tensor left the worst-K set" by the labeling
    contract, so zero-valued labels are skipped. ``spread`` is the
    max/min ratio of per-rank PRE-reduce norms — a persistent ratio far
    from 1 is the data-skew signal (one rank's shard feeds much larger
    gradients than its peers'). When the document carries the
    sharding-plane families the same spread is relabeled as the
    shard-imbalance detector (``shard_imbalance`` section)."""
    rows: Dict[str, dict] = {}
    codec_snr: Dict[str, float] = {}
    topk: Dict[str, float] = {}
    shard_ratios: Dict[str, float] = {}
    sharded = False
    samples = 0.0
    present = False
    for rank in sorted(ranks):
        fams = ranks[rank] or {}
        if (fams.get(FAMILY_SHARD_RANKS) or {}).get("samples"):
            sharded = True
        for sample in (fams.get(FAMILY_SHARD_IMBALANCE) or
                       {}).get("samples", []):
            value = sample.get("value", 0)
            if value > 0:
                shard_ratios[str(rank)] = value
        sample_fam = fams.get(FAMILY_SAMPLES)
        if sample_fam:
            present = True
            for s in sample_fam.get("samples", []):
                samples += s.get("value", 0)
        for name, value in _labeled_values(fams, FAMILY_TENSOR_NORM2,
                                           "tensor").items():
            if value == 0:
                continue
            row = rows.setdefault(name, {"tensor": name, "norm2": 0.0,
                                         "prenorm2": {}, "snr_db": {}})
            row["norm2"] = max(row["norm2"], value)
        for name, value in _labeled_values(fams, FAMILY_TENSOR_PRENORM2,
                                           "tensor").items():
            if value == 0:
                continue
            row = rows.setdefault(name, {"tensor": name, "norm2": 0.0,
                                         "prenorm2": {}, "snr_db": {}})
            row["prenorm2"][str(rank)] = value
        for name, value in _labeled_values(fams, FAMILY_TENSOR_SNR,
                                           "tensor").items():
            if value == 0:
                continue
            row = rows.setdefault(name, {"tensor": name, "norm2": 0.0,
                                         "prenorm2": {}, "snr_db": {}})
            row["snr_db"][str(rank)] = value
        for codec, value in _labeled_values(fams, FAMILY_CODEC_SNR,
                                            "codec").items():
            codec_snr[codec] = value if codec not in codec_snr \
                else min(codec_snr[codec], value)
        for k, value in _labeled_values(fams, FAMILY_TOPK, "k").items():
            topk[k] = max(topk.get(k, 0.0), value)
    table = []
    for name, row in rows.items():
        pres = [v for v in row["prenorm2"].values() if v > 0]
        row["spread"] = (max(pres) / min(pres)) if len(pres) >= 2 \
            else None
        row["worst_snr_db"] = min(row["snr_db"].values()) \
            if row["snr_db"] else None
        table.append(row)

    def order(row):
        snr = row["worst_snr_db"]
        spread = row["spread"] or 1.0
        return (0, snr, -spread) if snr is not None \
            else (1, -spread, -row["norm2"])

    table.sort(key=order)
    return {
        "degraded": not present,
        "samples": samples,
        "tensors": table[:max(int(top), 1)],
        "tensor_count": len(table),
        "codec_snr_db": codec_snr,
        "topk_mass": topk,
        # The prenorm spread, relabeled: in a ZeRO-1 world each rank's
        # pre-reduce norm is its partition's contribution, so the same
        # ratio that reads "data skew" replicated reads "shard
        # imbalance" sharded. ``worst`` is the highest per-rank
        # contribution ratio (1.0 = balanced).
        "shard_imbalance": {
            "sharded": sharded,
            "per_rank": shard_ratios,
            "worst": max(shard_ratios.values()) if shard_ratios else None,
        },
    }
