"""Shared stdlib loopback HTTP machinery (docs/metrics.md, docs/serving.md).

Both HTTP surfaces this repo exposes — the rank-0 metrics endpoint
(``obs.exposition``) and the rank-0 inference gateway
(``serving.gateway``) — are the same machine: a loopback-bound
``ThreadingHTTPServer`` on a daemon thread, an exact-path route table,
content-type handling, and a close that shuts the serve loop down BEFORE
releasing the socket. This module is that machine, factored out while
there was still one caller so the two planes cannot drift: the metrics
endpoint is two GET routes, the gateway is those two plus its own.

The helper also owns the shutdown-ordering fix the old in-module server
needed: ``close()`` stops the serve loop (``shutdown()`` blocks until the
loop exits), only then closes the listening socket, then joins the
thread — and it is idempotent, so a server that is both globally
registered and owned by a caller can be closed from either side without
a second close racing a half-torn-down loop.

Stdlib-only, like everything on the obs plane: importable in launcher
and tooling processes that never load jax.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Tuple
from urllib.parse import parse_qs


@dataclass
class HttpResponse:
    """One handler's answer. ``headers`` are extras (Content-Type and
    Content-Length are emitted from the dedicated fields)."""

    status: int = 200
    content_type: str = "text/plain; charset=utf-8"
    body: bytes = b""
    headers: Dict[str, str] = field(default_factory=dict)


class HttpError(Exception):
    """Structured non-200 a route raises on purpose (admission rejects,
    malformed requests). ``headers`` carry e.g. ``Retry-After``; the body
    is rendered by the route's error convention (the gateway sends JSON),
    or falls back to the plain message."""

    def __init__(self, status: int, message: str,
                 headers: Dict[str, str] | None = None,
                 content_type: str = "text/plain; charset=utf-8",
                 body: bytes | None = None) -> None:
        super().__init__(message)
        self.status = int(status)
        self.message = message
        self.headers = dict(headers or {})
        self.content_type = content_type
        self.body = body

    def to_response(self) -> HttpResponse:
        body = self.body if self.body is not None \
            else (self.message + "\n").encode()
        return HttpResponse(self.status, self.content_type, body,
                            dict(self.headers))


# route: (method, exact path) -> handler(query, headers, body) -> HttpResponse
RouteHandler = Callable[[Dict[str, list], Dict[str, str], bytes],
                        HttpResponse]


class LoopbackHTTPD:
    """Exact-path routed loopback HTTP server on a daemon thread.

    ``routes`` maps ``(method, path)`` to a handler; the path is matched
    with the query string stripped and the parsed query passed through.
    Unknown paths get a 404 listing the served routes; a handler raising
    ``HttpError`` answers with its structured status/headers; any other
    exception answers 500 with the message (surface, never hang the
    scraper/client). Request logging is silenced — scrapes and serving
    traffic are not news."""

    def __init__(self, name: str, port: int,
                 routes: Dict[Tuple[str, str], RouteHandler],
                 bind_host: str = "127.0.0.1") -> None:
        outer = self
        self._routes = dict(routes)
        known = sorted({p for _, p in self._routes})

        class _Handler(BaseHTTPRequestHandler):
            # one keep-alive connection serves many requests (the bench's
            # closed-loop clients reuse theirs)
            protocol_version = "HTTP/1.1"

            def _dispatch(self, method: str) -> None:
                path, _, query_s = self.path.partition("?")
                handler = outer._routes.get((method, path))
                if handler is None:
                    self._answer(HttpResponse(
                        404, body=(f"no route for {method} {path}; "
                                   f"try {', '.join(known)}\n").encode()))
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0) or 0)
                    body = self.rfile.read(length) if length else b""
                    resp = handler(parse_qs(query_s),
                                   dict(self.headers.items()), body)
                except HttpError as exc:
                    resp = exc.to_response()
                except Exception as exc:  # noqa: BLE001 - surface, not hang
                    resp = HttpResponse(
                        500, body=f"handler failed: {exc}\n".encode())
                self._answer(resp)

            def _answer(self, resp: HttpResponse) -> None:
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(resp.body)))
                for key, value in resp.headers.items():
                    self.send_header(key, str(value))
                self.end_headers()
                self.wfile.write(resp.body)

            def do_GET(self) -> None:  # noqa: N802 - stdlib handler names
                self._dispatch("GET")

            def do_POST(self) -> None:  # noqa: N802
                self._dispatch("POST")

            def log_message(self, *args) -> None:
                pass

            # Track live connections: under HTTP/1.1 keep-alive each
            # handler thread loops independently of serve_forever, so a
            # close() that only stopped the accept loop would leave
            # already-connected clients being answered by a torn-down
            # server (stale provider state) indefinitely.
            def setup(self) -> None:
                super().setup()
                with outer._conns_lock:
                    outer._conns.add(self.connection)

            def finish(self) -> None:
                try:
                    super().finish()
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.connection)

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # A closed-loop client fleet (the serving bench) dials many
            # connections at once; the stdlib default backlog of 5
            # overflows and the kernel drops SYNs, adding 1 s retransmit
            # spikes to p99 — the same fix BasicService carries.
            request_queue_size = 128

        self.name = name
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._server = _Server((bind_host, port), _Handler)
        self.port = self._server.server_address[1]
        self._closed = False
        self._close_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"{name}-http",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Ordered, idempotent teardown: stop the serve loop first
        (``shutdown()`` blocks until the loop exits), release the listen
        socket, then cut every live keep-alive connection so their
        handler threads exit too — a closed server must stop ANSWERING,
        not just stop accepting (re-registration on a fixed port would
        otherwise leave old clients pinned to the torn-down instance)."""
        import socket as _socket

        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)
