"""Registry → Timeline bridge: metric deltas as Chrome counter tracks.

The timeline (``utils.timeline``) predates the registry and its tooling
is established (chrome://tracing, the response-cache counter assertions
in tests); this bridge keeps that surface alive by emitting, once per
engine cycle, every registry family that CHANGED since the last emit as
a ``Timeline.counter`` record named ``metrics/<family>``. Counters and
histogram counts emit their per-interval DELTA (a rate, the useful
trace shape); gauges emit their absolute value. Families that did not
move emit nothing, so an idle metric costs no trace bytes.

Cheap when the timeline is disabled (one attribute check), and safe
after ``Timeline.close()`` — the timeline itself drops late events
loudly instead of writing to a closed file (see ``utils.timeline``).
"""

from __future__ import annotations

from typing import Dict, Tuple

from .registry import Registry


class TimelineBridge:
    """One per engine; ``emit()`` is called from the engine loop thread
    only, so the delta state needs no lock."""

    def __init__(self, registry: Registry, timeline) -> None:
        self._registry = registry
        self._timeline = timeline
        self._last: Dict[Tuple[str, str], float] = {}

    @staticmethod
    def _series_key(labels: Dict[str, str]) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))

    def emit(self) -> None:
        if not self._timeline.enabled:
            return
        snapshot = self._registry.snapshot()
        for name, fam in snapshot.items():
            track: Dict[str, float] = {}
            for sample in fam["samples"]:
                key = self._series_key(sample.get("labels", {}))
                if fam["type"] == "gauge":
                    cur = sample["value"]
                    if self._last.get((name, key)) != cur:
                        self._last[(name, key)] = cur
                        track[key or "value"] = cur
                    continue
                if fam["type"] == "histogram":
                    series = ((key + "," if key else "") + "count",
                              sample["count"])
                else:
                    series = (key or "value", sample["value"])
                skey, cur = series
                prev = self._last.get((name, skey), 0)
                if cur != prev:
                    self._last[(name, skey)] = cur
                    track[skey] = cur - prev
            if track:
                self._timeline.counter("metrics/" + name, track)
