"""Observability plane: unified metrics registry + cross-rank aggregation.

The subsystem docs live in docs/metrics.md; the pieces:

* :mod:`.registry` — process-local counters/gauges/mergeable histograms
  plus ``merge_snapshots`` (the pointwise world fold);
* :mod:`.httpd` — the shared stdlib loopback HTTP machinery (server
  thread lifecycle, route table, content-type handling) the metrics
  endpoint and the serving gateway both ride;
* :mod:`.exposition` — Prometheus text + JSON rendering, the loopback
  HTTP server (``HOROVOD_METRICS_PORT``) as a route set on it, and the
  ``parse_prometheus`` format-lint helper;
* :mod:`.bridge` — registry deltas as ``Timeline.counter`` tracks so the
  existing Chrome-tracing tooling keeps working;
* :mod:`.tracing` — the distributed-tracing half (docs/tracing.md):
  NTP-style clock alignment over the control wire and the coordinator's
  straggler attribution folded into :func:`straggler_report`;
* :func:`metrics_snapshot` — the Python API: this process's families, or
  the world-aggregated view rank 0's coordinator assembled from the
  per-rank pushes riding the HMAC control wire.
"""

from __future__ import annotations

from typing import Dict, Optional

from .registry import (  # noqa: F401 - public surface
    Counter,
    Gauge,
    Histogram,
    Registry,
    merge_snapshots,
    registry,
)
from .bridge import TimelineBridge  # noqa: F401
from . import exposition  # noqa: F401
from . import flightrec  # noqa: F401 - public surface (docs/blackbox.md)
from . import tensorwatch  # noqa: F401 - public surface (docs/tensorwatch.md)
from .tensorwatch import tensor_report  # noqa: F401
from .tracing import (  # noqa: F401 - public surface (docs/tracing.md)
    ClockSync,
    build_straggler_report,
    straggler_report,
)


def _pull_world_store(client) -> Dict[int, dict]:
    """Fetch the coordinator's per-rank snapshot store over a transient
    ANONYMOUS control-wire connection — never the engine's cycle client,
    whose request lock a pull would contend with mid-negotiation (the
    "metrics must not perturb the cycle" contract)."""
    from ..runner.network import BasicClient

    pull = None
    try:
        pull = BasicClient(client._addr, secret=client._secret,
                           timeout_s=5.0, attempts=3)
        kind, store = pull.request(
            ("metrics_pull", getattr(client, "_world_id", "")))
        assert kind == "metrics", kind
        return dict(store)
    finally:
        if pull is not None:
            pull.close()


def metrics_snapshot(world: bool = False):
    """Live metrics of this job (docs/metrics.md).

    ``world=False``: this process's registry families, as a plain dict.

    ``world=True``: ``{"world": merged_families, "ranks": {rank:
    families}}`` — the merged view plus the per-rank snapshots it was
    folded from. On the rank hosting the Python controller service the
    per-rank section is the coordinator's live push store; other ranks
    pull that store over a transient control-wire connection. This
    process's own entry is always refreshed from its live registry, so
    local families are exact while remote ones are as fresh as the last
    publisher push (``HOROVOD_METRICS_INTERVAL_S``; publishers run only
    when the plane is opted into — port or interval set — so an
    un-opted-in job's world view carries this rank alone). Size-1 worlds
    and the native (C++) controller — whose fixed binary wire predates
    the metrics RPC — degrade to a world of this rank alone too."""
    local = registry().snapshot()
    if not world:
        return local
    rank = 0
    engine = None
    try:
        from .. import basics
        from ..ops import engine as _engine_mod

        if basics.is_initialized():
            rank = basics.rank()
        engine = _engine_mod._engine
    except Exception:  # noqa: BLE001 - pre-init callers get local-only
        pass
    store: Dict[int, dict] = {}
    if engine is not None and not getattr(engine, "_native_controller",
                                          False):
        service = getattr(engine, "_service", None)
        client = getattr(engine, "_client", None)
        if service is not None and hasattr(service, "metrics_store"):
            store = service.metrics_store()
        elif client is not None and hasattr(client, "_addr"):
            try:
                store = _pull_world_store(client)
            except Exception:  # noqa: BLE001 - degraded view, not a crash
                store = {}
    ranks = dict(store)
    ranks[rank] = local
    return {"world": merge_snapshots(ranks.values()), "ranks": ranks}


def health_report() -> dict:
    """One-shot fold of the live engine/controller state (docs/blackbox.md):
    the SAME snapshots a black-box incident dump embeds — one definition
    — served live, so a slow-but-alive world can be poked without
    killing it. Exposed over HTTP as ``GET /v1/introspect`` on rank 0's
    exposition server and on the serving gateway's co-hosted metrics
    routes (the PR 11 httpd)."""
    report: dict = {
        "initialized": False,
        "engine": None,
        "controller": None,
        "flightrec": flightrec.recorder().stats(),
    }
    engine = None
    try:
        from .. import basics
        from ..ops import engine as _engine_mod

        if basics.is_initialized():
            report.update(initialized=True, rank=basics.rank(),
                          size=basics.size(),
                          epoch=basics.world_epoch())
        engine = _engine_mod._engine
    except Exception:  # noqa: BLE001 - pre-init callers get the shell
        pass
    if engine is not None:
        try:
            report["engine"] = engine.state_snapshot()
        except Exception as exc:  # noqa: BLE001 - live poke, best-effort
            report["engine"] = {"error": str(exc)}
        service = getattr(engine, "_service", None)
        if service is not None and hasattr(service, "state_snapshot"):
            try:
                report["controller"] = service.state_snapshot()
            except Exception as exc:  # noqa: BLE001
                report["controller"] = {"error": str(exc)}
    return report


def world_snapshot_provider():
    """The exposition server's provider (``basics.init`` wires it up)."""
    return metrics_snapshot(world=True)


def metrics_port() -> Optional[int]:
    """Port of the live HTTP exposition server, or None when disabled."""
    return exposition.metrics_port()
