"""Metrics exposition: Prometheus text format, JSON snapshots, HTTP server.

Three consumers share the same snapshot shape (docs/metrics.md):

* ``GET /metrics`` — Prometheus text exposition (format 0.0.4) of the
  WORLD-merged families, scraped by any Prometheus-compatible collector;
* ``GET /metrics.json`` — the full structured snapshot: the merged world
  view plus the raw per-rank snapshots it was folded from (the per-rank
  section is what makes "world bucket sums == sum of per-rank sums"
  checkable from one scrape, and what ``tools/metrics_summary.py``
  pretty-prints);
* ``horovod_tpu.metrics_snapshot(world=True)`` — the same dict, in
  Python.

The server is stdlib-only (``http.server``), loopback-bound, started by
``hvd.init()`` on rank 0 when ``HOROVOD_METRICS_PORT`` names a port —
0/unset means no server, no thread, no socket (the exposition plane is
strictly opt-in). It never blocks the hot path: scrapes run on the HTTP
thread and only take per-metric locks long enough to copy values.

``parse_prometheus`` is the format-lint helper the tests and the
``dryrun_metrics`` certification share: a tiny validating parser for the
subset of the exposition format we emit, so "Prometheus-parseable" is an
executable claim, not a hope.
"""

from __future__ import annotations

import json
import re
from typing import Callable, Dict, Optional

from .httpd import HttpResponse, LoopbackHTTPD

# -- Prometheus text rendering -------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def render_prometheus(families: Dict[str, dict]) -> str:
    """Render a (merged) families snapshot as Prometheus text format."""
    lines = []
    for name in sorted(families):
        fam = families[name]
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid Prometheus metric name {name!r}")
        if fam.get("help"):
            lines.append(f"# HELP {name} " +
                         fam["help"].replace("\n", " "))
        lines.append(f"# TYPE {name} {fam['type']}")
        for sample in fam["samples"]:
            labels = sample.get("labels", {})
            if fam["type"] == "histogram":
                # Prometheus buckets are CUMULATIVE with an le edge label;
                # the registry stores per-bucket counts, fold here.
                cum = 0
                for bound, count in zip(sample["bounds"],
                                        sample["buckets"]):
                    cum += count
                    le = 'le="' + _num(float(bound)) + '"'
                    lines.append(
                        f"{name}_bucket{_label_str(labels, le)} {cum}")
                cum += sample["buckets"][-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_label_str(labels, inf)} {cum}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_num(sample['sum'])}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{sample['count']}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_num(sample['value'])}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$")
_LABEL_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def parse_prometheus(text: str) -> Dict[str, str]:
    """Validate Prometheus text exposition; return {family: type}.

    The shared format-lint helper (tests + ``dryrun_metrics``): checks
    every sample line's shape, that each sample belongs to a declared
    ``# TYPE`` family, that histogram buckets are cumulative and end at
    ``+Inf`` with ``_count`` equal to the ``+Inf`` bucket. Raises
    ``ValueError`` with the offending line on any violation."""
    types: Dict[str, str] = {}
    hist_state: Dict[str, dict] = {}  # family(+labels) -> bucket audit
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram"):
                raise ValueError(f"malformed TYPE line: {line!r}")
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            raise ValueError(f"unknown comment line: {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        name, labels_s = m.group("name"), m.group("labels") or ""
        if labels_s:
            inner = labels_s[1:-1]
            for pair in _split_labels(inner):
                if not _LABEL_RE.match(pair):
                    raise ValueError(
                        f"malformed label {pair!r} in line: {line!r}")
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                family = base
                break
        if family not in types:
            raise ValueError(f"sample without TYPE declaration: {line!r}")
        if types[family] == "histogram" and name.endswith("_bucket"):
            le = re.search(r'le="([^"]*)"', labels_s)
            if le is None:
                raise ValueError(f"histogram bucket without le: {line!r}")
            key = family + _labels_key(labels_s, drop_le=True)
            st = hist_state.setdefault(key, {"last": -1.0, "prev": 0.0,
                                             "inf": None})
            edge = float("inf") if le.group(1) == "+Inf" \
                else float(le.group(1))
            cum = float(m.group("value"))
            if edge <= st["last"]:
                raise ValueError(f"bucket edges not increasing: {line!r}")
            if cum < st["prev"]:
                raise ValueError(f"bucket counts not cumulative: {line!r}")
            st["last"], st["prev"] = edge, cum
            if edge == float("inf"):
                st["inf"] = cum
        elif types[family] == "histogram" and name.endswith("_count"):
            key = family + _labels_key(labels_s)
            st = hist_state.get(key)
            if st is None or st["inf"] is None:
                raise ValueError(
                    f"histogram _count before +Inf bucket: {line!r}")
            if float(m.group("value")) != st["inf"]:
                raise ValueError(
                    f"histogram _count != +Inf bucket: {line!r}")
    for key, st in hist_state.items():
        if st["inf"] is None:
            raise ValueError(f"histogram {key!r} has no +Inf bucket")
    return types


def _labels_key(labels_s: str, drop_le: bool = False) -> str:
    """Canonical label-set key for bucket/series matching: sorted pairs,
    optionally without the ``le`` edge (empty set and no-braces agree)."""
    if not labels_s:
        return ""
    pairs = [p for p in _split_labels(labels_s[1:-1])
             if not (drop_le and p.startswith('le="'))]
    return ",".join(sorted(pairs))


def _split_labels(inner: str):
    """Split label pairs on commas outside quoted values."""
    out, buf, quoted, escaped = [], [], False, False
    for ch in inner:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            quoted = not quoted
            buf.append(ch)
            continue
        if ch == "," and not quoted:
            out.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


# -- HTTP server ---------------------------------------------------------------


def metrics_routes(provider: Callable[[], dict]):
    """The metrics endpoint as an ``obs.httpd`` route set: Prometheus
    text at ``GET /metrics`` (rendered from the provider's merged world
    view), the full structured snapshot at ``GET /metrics.json``, and
    the live engine/controller introspection fold at
    ``GET /v1/introspect`` (``hvd.health_report()``, docs/blackbox.md —
    the same snapshot a black-box incident dump embeds, served live so a
    slow-but-alive world can be poked without killing it). Shared
    verbatim by the standalone ``MetricsServer`` and the serving
    gateway's co-hosted metrics surface (docs/serving.md) — one
    implementation, two route sets."""

    def _metrics(_query, _headers, _body) -> HttpResponse:
        doc = provider()
        world = doc["world"] if isinstance(doc, dict) and "world" in doc \
            else doc
        return HttpResponse(
            200, "text/plain; version=0.0.4; charset=utf-8",
            render_prometheus(world).encode())

    def _metrics_json(_query, _headers, _body) -> HttpResponse:
        return HttpResponse(200, "application/json",
                            json.dumps(provider()).encode())

    def _introspect(_query, _headers, _body) -> HttpResponse:
        # lazy: obs/__init__ imports this module at package import time
        from . import health_report

        return HttpResponse(200, "application/json",
                            json.dumps(health_report()).encode())

    def _tensors(_query, _headers, _body) -> HttpResponse:
        # Numerics observatory (docs/tensorwatch.md): the FULL per-
        # tensor table + evidence-gate state — the registry only
        # carries the bounded worst-K labels, this route carries
        # everything. Lazy import like _introspect.
        from .tensorwatch import tensor_report

        return HttpResponse(200, "application/json",
                            json.dumps(tensor_report()).encode())

    return {("GET", "/metrics"): _metrics,
            ("GET", "/metrics.json"): _metrics_json,
            ("GET", "/v1/introspect"): _introspect,
            ("GET", "/v1/tensors"): _tensors}


class MetricsServer:
    """Loopback HTTP exposition of a snapshot provider (an
    ``obs.httpd.LoopbackHTTPD`` carrying the ``metrics_routes`` set).

    ``provider()`` returns ``{"world": families, "ranks": {rank:
    families}}`` (the ``metrics_snapshot(world=True)`` shape); scrapes
    call it fresh each time."""

    def __init__(self, port: int, provider: Callable[[], dict],
                 bind_host: str = "127.0.0.1") -> None:
        self._provider = provider
        self._httpd = LoopbackHTTPD("horovod-metrics", port,
                                    metrics_routes(provider),
                                    bind_host=bind_host)
        self.port = self._httpd.port

    def close(self) -> None:
        global _server
        self._httpd.close()
        if _server is self:
            _server = None


_server: Optional[MetricsServer] = None


def serve(port: int, provider: Callable[[], dict]) -> MetricsServer:
    """Start (and register as the process's) exposition server. The env
    gate — ``HOROVOD_METRICS_PORT`` 0/unset means never call this — lives
    with the caller (``basics.init``); here ``port`` may legitimately be
    0 for an ephemeral test port. A previously registered server is
    closed first: re-init must never leak the old serve thread and
    socket behind the new registration (the duplicate-server shutdown
    ordering the shared helper exists to fix)."""
    global _server
    if _server is not None:
        _server.close()
    server = MetricsServer(port, provider)
    _server = server
    return server


def active_server() -> Optional[MetricsServer]:
    return _server


def metrics_port() -> Optional[int]:
    """Port of the live exposition server, or None when disabled — the
    introspection hook scrape-yourself certifications use."""
    return _server.port if _server is not None else None
