"""Distributed tracing plane: clock alignment + straggler attribution.

The Horovod paper (1802.05799) calls straggler diagnosis the hardest
operational problem in synchronous data parallelism, and the MPI
characterization work (1810.11112) locates the damage at the
coordinator: arrival spread is where world-scale cycles die. Diagnosing
it needs two things this module provides on top of PR 5's metrics plane
(docs/tracing.md):

* **Clock alignment** — per-rank monotonic clocks are uncorrelatable, so
  :class:`ClockSync` runs an NTP-style handshake against the coordinator
  over the existing HMAC control wire: a battery of ``clock_probe``
  round trips, keep the sample with the smallest RTT (asymmetric delay
  corrupts the midpoint estimate, and the minimum-RTT sample bounds that
  error by rtt/2), offset = server_time - local_midpoint. The offset
  lands on the obs registry (``horovod_clock_offset_us``) and, when a
  timeline is recording, as ``CLOCK_SYNC`` metadata records that
  ``tools/trace_merge.py`` uses to fold per-rank trace files onto the
  coordinator's timebase.

* **Straggler attribution** — the coordinator charges each cycle's
  arrival spread to the last-arriving rank (``ops/controller.py``:
  ``horovod_straggler_last_arriver_total`` /
  ``horovod_straggler_blame_seconds_total`` /
  ``horovod_arrival_spread_seconds``). :func:`straggler_report` folds
  those families — riding the PR 5 snapshot wire, so any rank can ask —
  into per-rank blame fractions plus each rank's negotiation-wait vs
  execute breakdown. ``tools/straggler_report.py`` runs the same fold
  over a saved ``/metrics.json`` document.

Degrades deterministically: the native (C++) controller wire predates
the ``clock_probe`` RPC (``NativeControllerClient.clock_sync_supported``
is False, the metrics_pull pattern), so traces there keep their local
timebase and reports carry ``degraded: true`` instead of invented data.

Module level is deliberately STDLIB-ONLY (package imports stay inside
the functions that need them): ``tools/straggler_report.py`` analyzes
saved snapshots on machines without the training environment by loading
this file directly when ``import horovod_tpu`` (and therefore jax) is
unavailable — the report fold itself is pure dict math.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

# Families this module owns. Offset/RTT are per-rank identity values
# (gauges merge by MAX in the world fold, like world_size; the per-rank
# sections carry the real readings — docs/metrics.md).
GAUGE_OFFSET = "horovod_clock_offset_us"
GAUGE_RTT = "horovod_clock_rtt_us"
COUNTER_SYNCS = "horovod_clock_syncs_total"

# Coordinator-side attribution families (registered in ops/controller.py).
FAMILY_LAST = "horovod_straggler_last_arriver_total"
FAMILY_BLAME_S = "horovod_straggler_blame_seconds_total"
FAMILY_SPREAD = "horovod_arrival_spread_seconds"

# Below this mean attributed spread the coordinator is watching scheduler
# jitter, not a straggler: a "dominant rank" verdict needs both a
# majority of the blame seconds AND spreads worth acting on. 5 ms is an
# order of magnitude above healthy same-host jitter and well below any
# fault a human would chase (docs/tracing.md).
DEFAULT_MIN_SPREAD_S = 0.005


def _clock_gauges():
    """The one registration site for the clock families (get-or-create:
    help/type must agree wherever they are touched)."""
    from .registry import registry as _metrics

    reg = _metrics()
    return (
        reg.gauge(GAUGE_OFFSET,
                  "This rank's estimated monotonic-clock offset to the "
                  "coordinator (rank-0 timebase), microseconds"),
        reg.gauge(GAUGE_RTT,
                  "RTT of the minimum-RTT clock probe behind the current "
                  "offset estimate, microseconds"),
        reg.counter(COUNTER_SYNCS, "Completed clock-alignment handshakes"),
    )


def set_reference_clock(rank: int, timeline=None) -> None:
    """The coordinator-hosting rank IS the reference timebase: offset 0
    by definition, no probes. Sets the same gauges / timeline metadata a
    ClockSync would, so world snapshots and trace files stay uniform and
    trace_merge never special-cases rank 0."""
    g_offset, g_rtt, _ = _clock_gauges()
    g_offset.set(0)
    g_rtt.set(0)
    if timeline is not None and timeline.enabled:
        from ..utils.timeline import CLOCK_SYNC

        timeline.meta(CLOCK_SYNC, {"offset_us": 0.0, "rtt_us": 0.0,
                                   "rank": rank})


class ClockSync:
    """Periodic offset-to-coordinator estimation for one rank.

    Owns its own ANONYMOUS control-wire connection (the metrics-publisher
    pattern: never the engine's cycle client, whose request lock a probe
    battery would contend with mid-negotiation; tearing this connection
    down is never a rank death). ``sync_once`` runs a battery of
    ``probes`` round trips and keeps the minimum-RTT sample; failures
    drop the battery and redial next tick, degrading loudly after a
    persistent streak like every other plane here."""

    def __init__(self, addr, secret, world_id: str = "",
                 rank: int = 0, timeline=None,
                 probes: int = 8, interval_s: float = 30.0) -> None:
        self._addr = addr
        self._secret = secret
        self._world_id = world_id
        self._rank = rank
        self._timeline = timeline
        self._probes = max(int(probes), 1)
        self._interval_s = interval_s
        self._client = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._failures = 0
        self.offset_us: Optional[float] = None
        self.rtt_us: Optional[float] = None
        self._g_offset, self._g_rtt, self._c_syncs = _clock_gauges()

    def sync_once(self) -> Optional[Tuple[float, float]]:
        """One battery; returns ``(offset_us, rtt_us)`` or None on fault.

        The filter is MIN RTT, not mean: queueing delay is one-sided and
        bursty, so averaging mixes corrupted midpoints into the estimate,
        while the fastest round trip is the one that saw the least of it
        — its midpoint error is bounded by rtt/2 (docs/tracing.md)."""
        from ..runner.network import BasicClient

        try:
            if self._client is None:
                self._client = BasicClient(self._addr, secret=self._secret,
                                           timeout_s=5.0, attempts=3)
            best: Optional[Tuple[float, float]] = None  # (rtt_s, offset_us)
            for _ in range(self._probes):
                resp, t0, t1 = self._client.rtt_probe(
                    ("clock_probe", self._rank, self._world_id))
                kind, server_us = resp
                assert kind == "clock", resp
                rtt = t1 - t0
                midpoint_us = (t0 + t1) / 2.0 * 1e6
                offset_us = float(server_us) - midpoint_us
                if best is None or rtt < best[0]:
                    best = (rtt, offset_us)
            self._failures = 0
        except Exception as exc:  # noqa: BLE001 - drop battery, redial
            from ..core.logging import LOG

            self._failures += 1
            if self._failures == 3 and not self._stop.is_set():
                LOG.warning(
                    "clock sync: %d consecutive failed probe batteries "
                    "(last: %s); rank %d's trace timebase will drift "
                    "uncorrected until the wire recovers",
                    self._failures, exc, self._rank)
            if self._client is not None:
                try:
                    self._client.close()
                except Exception:  # noqa: BLE001
                    pass
                self._client = None
            return None
        rtt_s, offset_us = best
        self.offset_us = offset_us
        self.rtt_us = rtt_s * 1e6
        self._g_offset.set(round(offset_us, 1))
        self._g_rtt.set(round(self.rtt_us, 1))
        self._c_syncs.inc()
        if self._timeline is not None and self._timeline.enabled:
            from ..utils.timeline import CLOCK_SYNC

            self._timeline.meta(CLOCK_SYNC, {
                "offset_us": round(offset_us, 1),
                "rtt_us": round(self.rtt_us, 1),
                "rank": self._rank,
            })
        return offset_us, self.rtt_us

    def start(self) -> None:
        """Sync at init and every ``interval_s`` (<= 0: init-time only),
        on a daemon thread so a slow wire never blocks the engine."""

        def _loop() -> None:
            try:
                self.sync_once()
                if self._interval_s <= 0:
                    return
                while not self._stop.wait(self._interval_s):
                    self.sync_once()
            finally:
                if self._client is not None:
                    try:
                        self._client.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._client = None

        self._thread = threading.Thread(
            target=_loop, name="horovod-clock-sync", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


# -- straggler report ----------------------------------------------------------


def _histogram_quantile(bounds, buckets, q: float) -> Optional[float]:
    """Upper edge of the bucket where the cumulative count crosses q
    (the fixed-bucket approximation every consumer of these histograms
    uses — tools/metrics_summary.py renders the same number). Returns
    None when the quantile lands in the +Inf overflow bucket: the report
    is json.dumps'd verbatim (the tools' one-line-JSON contract), and
    float('inf') would serialize as the non-RFC token ``Infinity``."""
    total = sum(buckets)
    if total == 0:
        return None
    target = q * total
    cum = 0
    for bound, count in zip(bounds, buckets):
        cum += count
        if cum >= target:
            return float(bound)
    return None  # beyond the last finite bound


def _sum_labeled_counter(families: dict, name: str,
                         label: str = "rank") -> Dict[int, float]:
    """Fold a labeled counter family by one label (summing across any
    OTHER labels on the sample — the rank fold is island-agnostic and
    the island fold rank-agnostic, so both read the same family)."""
    out: Dict[int, float] = {}
    fam = families.get(name)
    if not fam:
        return out
    for sample in fam.get("samples", []):
        key = sample.get("labels", {}).get(label)
        if key is None:
            continue
        out[int(key)] = out.get(int(key), 0.0) + sample.get("value", 0.0)
    return out


def _unlabeled_sample(families: dict, name: str) -> Optional[dict]:
    fam = families.get(name)
    if not fam or not fam.get("samples"):
        return None
    return fam["samples"][0]


def build_straggler_report(ranks: Dict[int, dict],
                           min_spread_s: float = DEFAULT_MIN_SPREAD_S
                           ) -> dict:
    """Fold per-rank registry families into the attribution report.

    ``ranks`` is the ``metrics_snapshot(world=True)["ranks"]`` shape:
    {rank: families}. The attribution families live on the COORDINATOR's
    registry (rank 0's section); each rank's own section contributes its
    negotiation-wait vs execute breakdown. A document with no
    attribution families (native controller wire, or a pull that never
    reached the coordinator's snapshot) reports ``degraded: true``.

    ``dominant_rank`` is deliberately two-gated: a rank must own more
    than half the accumulated blame SECONDS (counts alone let a rank
    late by microseconds every cycle outrank one late by 50 ms on a
    tenth of them) AND the mean attributed spread must exceed
    ``min_spread_s`` — below that the coordinator is measuring scheduler
    jitter and naming a "straggler" would send an operator chasing
    noise."""
    last: Dict[int, float] = {}
    blame_s: Dict[int, float] = {}
    # Hierarchical worlds (docs/hierarchy.md): the same two families fold
    # a second way, by their ``island`` label — at the root the arrival
    # spread is measured BETWEEN island heads, so island blame is the
    # topology-level attribution (name the slow island before the slow
    # rank: a DCN-side cause charges the whole island roughly equally,
    # and the per-rank fold alone would smear it below the dominance
    # gate). Flat worlds stamp island=0 everywhere, collapsing the fold
    # to one row that can never dominate misleadingly (share == 1 needs
    # mean spread > min_spread_s too, same as a 1-rank world).
    island_last: Dict[int, float] = {}
    island_blame_s: Dict[int, float] = {}
    spread = None
    for fams in ranks.values():
        for rank, v in _sum_labeled_counter(fams, FAMILY_LAST).items():
            last[rank] = last.get(rank, 0.0) + v
        for rank, v in _sum_labeled_counter(fams, FAMILY_BLAME_S).items():
            blame_s[rank] = blame_s.get(rank, 0.0) + v
        for isl, v in _sum_labeled_counter(fams, FAMILY_LAST,
                                           label="island").items():
            island_last[isl] = island_last.get(isl, 0.0) + v
        for isl, v in _sum_labeled_counter(fams, FAMILY_BLAME_S,
                                           label="island").items():
            island_blame_s[isl] = island_blame_s.get(isl, 0.0) + v
        s = _unlabeled_sample(fams, FAMILY_SPREAD)
        if s is not None and s.get("count"):
            if spread is None:
                spread = {"bounds": list(s["bounds"]),
                          "buckets": list(s["buckets"]),
                          "sum": s["sum"], "count": s["count"]}
            else:  # same-family fold (pointwise: bounds fixed by contract)
                spread["buckets"] = [a + b for a, b in
                                     zip(spread["buckets"], s["buckets"])]
                spread["sum"] += s["sum"]
                spread["count"] += s["count"]
    cycles = int(sum(last.values()))
    total_blame = sum(blame_s.values())
    report: dict = {
        "cycles_attributed": cycles,
        "min_spread_s": min_spread_s,
        "degraded": cycles == 0,
        "blame": {},
        "per_rank": {},
        "dominant_rank": None,
        "islands": {},
        "dominant_island": None,
    }
    island_total = sum(island_blame_s.values())
    for isl in sorted(set(island_last) | set(island_blame_s)):
        seconds = island_blame_s.get(isl, 0.0)
        report["islands"][isl] = {
            "last_arriver_cycles": int(island_last.get(isl, 0)),
            "blame_seconds": seconds,
            "blame_share": (seconds / island_total) if island_total
            else 0.0,
        }
    for rank in sorted(set(last) | set(blame_s)):
        seconds = blame_s.get(rank, 0.0)
        report["blame"][rank] = {
            "last_arriver_cycles": int(last.get(rank, 0)),
            "cycle_share": (last.get(rank, 0.0) / cycles) if cycles else 0.0,
            "blame_seconds": seconds,
            "blame_share": (seconds / total_blame) if total_blame else 0.0,
        }
    if spread is not None:
        mean = spread["sum"] / spread["count"]
        report["spread"] = {
            "count": spread["count"],
            "mean_s": mean,
            "p50_s": _histogram_quantile(spread["bounds"],
                                         spread["buckets"], 0.50),
            "p99_s": _histogram_quantile(spread["bounds"],
                                         spread["buckets"], 0.99),
            "sum_s": spread["sum"],
        }
        if report["blame"]:
            top = max(report["blame"],
                      key=lambda r: report["blame"][r]["blame_seconds"])
            if report["blame"][top]["blame_share"] > 0.5 and \
                    mean > min_spread_s:
                report["dominant_rank"] = top
        if len(report["islands"]) > 1:
            # same two gates as dominant_rank — and only when the world
            # actually has islands to tell apart (one row is a flat
            # world's island=0 default, not a finding)
            top_i = max(report["islands"], key=lambda i:
                        report["islands"][i]["blame_seconds"])
            if report["islands"][top_i]["blame_share"] > 0.5 and \
                    mean > min_spread_s:
                report["dominant_island"] = top_i
    # Per-rank phase breakdown: where each rank's wall time went —
    # negotiation wait (client-observed cycle latency, straggler wait
    # included) vs executing negotiated responses.
    for rank, fams in sorted(ranks.items()):
        wait = _unlabeled_sample(fams, "horovod_negotiation_cycle_seconds")
        execute = _unlabeled_sample(fams, "horovod_execute_seconds")
        report["per_rank"][int(rank)] = {
            "negotiation_wait_s": wait["sum"] if wait else 0.0,
            "negotiation_cycles": wait["count"] if wait else 0,
            "execute_s": execute["sum"] if execute else 0.0,
        }
    return report


def straggler_report(min_spread_s: float = DEFAULT_MIN_SPREAD_S) -> dict:
    """Live attribution report for this job (docs/tracing.md).

    On the coordinator rank the attribution families are read from the
    live local registry; elsewhere they arrive via the PR 5 snapshot
    wire (``metrics_pull`` — only as fresh as rank 0's last publisher
    push, and absent entirely when the publisher plane is not opted in,
    in which case the report says ``degraded: true`` rather than
    guessing)."""
    from . import metrics_snapshot

    return build_straggler_report(
        metrics_snapshot(world=True)["ranks"], min_spread_s=min_spread_s)
