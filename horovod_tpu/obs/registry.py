"""Process-local metrics registry: counters, gauges, mergeable histograms.

The observability plane's core (docs/metrics.md). Everything this repo
grew beyond the reference's Chrome-tracing timeline — response-cache hit
rates (PR 3), reconnect/chaos events (PR 4), elastic epochs (PR 2), wire
byte counters — used to live in ad-hoc attributes scattered per object;
this registry is the one place a running job's state can be asked for
(the 1802.05799 operational lesson: diagnosing stragglers and stalls is
the hard part of running the system, and it needs live numbers, not
post-hoc log scraping).

Design constraints, in order:

* **Hot-path cheap.** ``Counter.inc`` is one lock acquire and one int
  add — O(1), no allocation beyond Python's int arithmetic — because it
  sits on the wire framing path (every framed byte counts through it).
  Locks, not bare ``+=``: the service's ``Wire`` is shared by every
  connection handler thread, and a bytecode-level read-modify-write race
  would silently undercount (the PR's multi-threaded-Wire satellite).
* **Mergeable.** Cross-rank aggregation is a pointwise fold over plain
  snapshots: counters and histogram buckets sum; gauges merge by MAX
  (every gauge this repo registers is world-identical or per-rank
  identity — world size, rank, epoch — and a sum would read as nonsense
  on the world view Prometheus scrapes; per-rank values stay readable in
  the unmerged sections). Histograms use FIXED bucket bounds chosen at
  registration, so a world merge is a bucket-wise sum with no
  re-binning — the property that makes
  ``merge_snapshots(per_rank_snapshots)`` exact.
* **Plain-data snapshots.** ``Registry.snapshot()`` returns
  pickle/JSON-able dicts, because snapshots ride the HMAC control wire
  (``ControllerService`` ``("metrics", rank, snap)``) and the
  ``/metrics.json`` endpoint verbatim.

Stdlib-only on purpose: the registry is imported by ``runner.network``,
which must stay importable without jax (launcher processes).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Tuple

from ..analysis.witness import maybe_wrap as _witness_wrap

# Latency-oriented default bounds (seconds), Prometheus-style: the last
# implicit bucket is +Inf. Negotiation cycles live in the 1-50 ms range
# (docs/response-cache.md steady-state table), stalls in whole seconds.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    """Monotonic counter. ``inc`` is the hot-path primitive; see module
    docstring for why it takes a lock."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Settable instantaneous value (world epoch, cache entries)."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram; mergeable by pointwise bucket sum.

    ``bounds`` are upper edges (a value v lands in the first bucket with
    v <= bound; values past the last bound land in the implicit +Inf
    bucket), so ``buckets`` has ``len(bounds) + 1`` slots."""

    __slots__ = ("_lock", "bounds", "_buckets", "_sum", "_count")

    def __init__(self, bounds: Iterable[float] = DEFAULT_BUCKETS) -> None:
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._buckets = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._buckets[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        # one lock: buckets/sum/count must be a consistent cut, or a
        # merged world histogram's _count could disagree with its buckets
        with self._lock:
            return {"bounds": list(self.bounds),
                    "buckets": list(self._buckets),
                    "sum": self._sum, "count": self._count}


_TYPE_NAMES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class Family:
    """One named metric family, optionally labeled.

    Without label names the family IS the metric (``fam.inc(...)``
    delegates to a single default child); with label names,
    ``fam.labels(kind="drop")`` returns the per-label-value child,
    created on demand. Children are cached forever — label values must
    be low-cardinality by contract (fault kinds, data-plane paths), not
    tensor names."""

    def __init__(self, name: str, help: str, metric_cls,
                 label_names: Tuple[str, ...] = (),
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.help = help
        self.metric_cls = metric_cls
        self.type = _TYPE_NAMES[metric_cls]
        self.label_names = tuple(label_names)
        self._buckets = tuple(buckets) if buckets is not None else None
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.label_names:
            self._children[()] = self._make()

    def _make(self):
        if self.metric_cls is Histogram:
            return Histogram(self._buckets or DEFAULT_BUCKETS)
        return self.metric_cls()

    def labels(self, **kv):
        try:
            key = tuple(str(kv[n]) for n in self.label_names)
        except KeyError as exc:
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {sorted(kv)}") from exc
        if len(kv) != len(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {sorted(kv)}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
        return child

    # -- unlabeled delegation (the hot-path spelling) -------------------------

    def _default(self):
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.label_names}; "
                f"call .labels(...) first")
        return self._children[()]

    def inc(self, n: float = 1) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1) -> None:
        self._default().dec(n)

    def set(self, v) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self):
        return self._default().value

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._children.items())
        samples: List[dict] = []
        for key, child in items:
            labels = dict(zip(self.label_names, key))
            if isinstance(child, Histogram):
                sample = child.snapshot()
            else:
                sample = {"value": child.value}
            sample["labels"] = labels
            samples.append(sample)
        return {"type": self.type, "help": self.help,
                "label_names": list(self.label_names), "samples": samples}


class Registry:
    """Named families, get-or-create. One process-global instance
    (``registry()``) serves the whole framework; construct private ones
    in tests."""

    def __init__(self) -> None:
        # lock witness (docs/analysis.md): the registry lock is grabbed
        # from every plane, so it anchors the global held-before graph
        # under HOROVOD_LOCK_WITNESS=1
        self._lock = _witness_wrap(threading.Lock(),
                                   "obs.registry.Registry._lock")
        self._families: Dict[str, Family] = {}

    def _family(self, name: str, help: str, metric_cls,
                labels: Tuple[str, ...],
                buckets: Optional[Tuple[float, ...]] = None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.metric_cls is not metric_cls or \
                        fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.type}{fam.label_names}, cannot re-register "
                        f"as {_TYPE_NAMES[metric_cls]}{tuple(labels)}")
                if metric_cls is Histogram and buckets is not None and \
                        fam._buckets is not None and \
                        fam._buckets != tuple(buckets):
                    # the in-process twin of merge_snapshots' cross-rank
                    # bounds check: silently observing into another
                    # caller's bounds would skew its distribution
                    raise ValueError(
                        f"metric {name!r} already registered with buckets "
                        f"{fam._buckets}, cannot re-register with "
                        f"{tuple(buckets)}")
                return fam
            fam = Family(name, help, metric_cls, tuple(labels), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, help, Counter, labels)

    def gauge(self, name: str, help: str = "",
              labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, help, Gauge, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Tuple[str, ...] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Family:
        return self._family(name, help, Histogram, labels, buckets)

    def snapshot(self) -> Dict[str, dict]:
        """Plain-data snapshot of every family (pickle/JSON-able)."""
        with self._lock:
            fams = list(self._families.items())
        return {name: fam.snapshot() for name, fam in fams}


def _sample_key(sample: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(sample.get("labels", {}).items()))


def merge_snapshots(snapshots: Iterable[Dict[str, dict]]) -> Dict[str, dict]:
    """Pointwise world merge of per-rank ``Registry.snapshot()`` dicts.

    Counters and histograms sum (histograms bucket-wise — exact because
    bounds are fixed at registration); gauges merge by MAX: the world
    size/rank/epoch gauges are identity values, and a sum would put
    size^2 or n(n-1)/2 on the only view ``/metrics`` serves (the merged
    world). Per-rank gauge readings stay visible in the unmerged
    ``ranks`` section. Mismatched types or histogram bounds for the same
    family name are a version skew across ranks and fail loudly."""
    merged: Dict[str, dict] = {}
    for snap in snapshots:
        for name, fam in snap.items():
            out = merged.get(name)
            if out is None:
                # deep-ish copy: samples are mutated below
                merged[name] = {
                    "type": fam["type"], "help": fam.get("help", ""),
                    "label_names": list(fam.get("label_names", [])),
                    "samples": [dict(s) for s in fam["samples"]],
                }
                for s in merged[name]["samples"]:
                    if "buckets" in s:
                        s["buckets"] = list(s["buckets"])
                continue
            if out["type"] != fam["type"]:
                raise ValueError(
                    f"metric {name!r} type mismatch across ranks: "
                    f"{out['type']} vs {fam['type']}")
            by_key = {_sample_key(s): s for s in out["samples"]}
            for sample in fam["samples"]:
                key = _sample_key(sample)
                into = by_key.get(key)
                if into is None:
                    into = dict(sample)
                    if "buckets" in into:
                        into["buckets"] = list(into["buckets"])
                    out["samples"].append(into)
                    by_key[key] = into
                    continue
                if "buckets" in sample:
                    if list(into["bounds"]) != list(sample["bounds"]):
                        raise ValueError(
                            f"metric {name!r} histogram bounds differ "
                            f"across ranks; cannot merge")
                    if len(into["buckets"]) != len(sample["buckets"]):
                        # The +Inf overflow bucket is the LAST slot
                        # (len(bounds)+1 buckets by construction, and
                        # quantile readers return None when a quantile
                        # lands there). A truncated bucket list would
                        # make the zip below silently DROP the overflow
                        # counts from the world fold — exactly the
                        # collapse a malformed/old-format snapshot could
                        # smuggle in — so mismatched lengths fail as
                        # loudly as mismatched bounds.
                        raise ValueError(
                            f"metric {name!r} histogram bucket count "
                            f"differs across ranks "
                            f"({len(into['buckets'])} vs "
                            f"{len(sample['buckets'])}); a truncated "
                            f"list would silently drop the +Inf "
                            f"overflow bucket from the world fold")
                    into["buckets"] = [a + b for a, b in
                                       zip(into["buckets"],
                                           sample["buckets"])]
                    into["sum"] += sample["sum"]
                    into["count"] += sample["count"]
                elif out["type"] == "gauge":
                    into["value"] = max(into["value"], sample["value"])
                else:
                    into["value"] += sample["value"]
    return merged


_global_registry = Registry()


def registry() -> Registry:
    """The process-global registry every subsystem instruments into."""
    return _global_registry
