#!/usr/bin/env python
"""Synthetic benchmark, reproducing the reference measurement protocol.

Reference: ``examples/pytorch_synthetic_benchmark.py:24-110`` — ResNet-50,
batch 32/device, SGD 0.01, synthetic ImageNet data; 10 warmup batches, then
``num_iters`` x ``num_batches_per_iter`` timed batches; report img/sec mean
± 1.96 sigma. Here the training step is the framework's product path: flax
ResNet-50 (bf16 compute / f32 params), ``hvd.DistributedOptimizer`` over the
data axis of the device mesh, jit-compiled so gradient averaging is an XLA
collective on ICI.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": R}

vs_baseline: the reference publishes exactly one absolute throughput figure
— 1656.82 total img/s for ResNet-101, batch 64/GPU, on 16 Pascal P100s
(``docs/benchmarks.md:19-38``), i.e. 103.55 img/s per device. That per-device
figure is the only anchor available (BASELINE.md), so vs_baseline =
our img/s/device ÷ 103.55 (note: ResNet-50 here vs ResNet-101 there).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Optional

import numpy as np

REFERENCE_PER_DEVICE_IMG_S = 1656.82 / 16  # docs/benchmarks.md:19-38


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)

# Per-chip bf16 peak TFLOP/s by TPU generation, for the MFU line. The
# measured step runs bf16 on the MXU (models/_common dtype policy), so the
# bf16 number is the right denominator. Override with
# HOROVOD_BENCH_PEAK_TFLOPS when the device kind isn't recognized.
_PEAK_TFLOPS_BY_KIND = {
    "v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
}


def _peak_tflops(device) -> Optional[float]:
    env = os.environ.get("HOROVOD_BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "") or ""
    for tag, peak in _PEAK_TFLOPS_BY_KIND.items():
        if tag in kind.lower().replace(" ", ""):
            return peak
    if device.platform in ("tpu", "axon"):
        return 197.0  # pool chips are v5e unless the kind says otherwise
    return None  # CPU runs: MFU is meaningless, skip the field


def _setup_accelerator_cache(jax_module) -> None:
    """Default the persistent compile cache ON for accelerator runs.

    The shared-pool tunnel wedges most often during the multi-minute first
    compile, and a warm cache turns a re-run's compile into a file read.
    One repo-local dir so consecutive runs — watcher, driver, human —
    share it. Gate on the RESOLVED backend (not env strings: an unpinned
    run on a CPU-only box has no platform env at all) so CPU CI sweeps
    don't accrete unbounded cache entries; set JAX_COMPILATION_CACHE_DIR
    to opt in anywhere. Safe post-init: the cache config is read at
    compile time. Shared by bench.py and benchmarks/lm_bench.py."""
    if (not os.environ.get("JAX_COMPILATION_CACHE_DIR")
            and jax_module.default_backend() != "cpu"):
        jax_module.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_bench_cache"))


def _git_head() -> Optional[str]:
    """Short HEAD sha of the repo this script lives in (shared helper:
    ``horovod_tpu.core.provenance``). Stamped into every capture so the
    wedge-fallback path can tell when the freshest capture was measured on
    an older revision."""
    from horovod_tpu.core.provenance import git_head_sha

    return git_head_sha(os.path.dirname(os.path.abspath(__file__)))


def _scan_cost_counts_body_once(log) -> bool:
    """Verify, on this backend, that ``cost_analysis()`` counts a
    ``lax.scan`` body once rather than times the trip count.

    The scan-mode MFU fields rest on that assumption; if a JAX/XLA
    version multiplied body flops by the trip count, mfu_pct/tflops
    would silently inflate by ``scan_batches``. Two toy compiles
    (64x64 matmul scanned 1x vs 4x) settle it at runtime; on any
    failure to measure, answer False so MFU is omitted rather than
    risk emitting inflated numbers.
    """
    try:
        import jax
        import jax.numpy as jnp

        def flops_at(length):
            def f(x):
                y, _ = jax.lax.scan(lambda c, _: (c @ c, None), x,
                                    None, length=length)
                return y
            comp = jax.jit(f).lower(
                jnp.ones((64, 64), jnp.float32)).compile()
            ca = comp.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            return float(ca.get("flops", 0.0))

        f1, f4 = flops_at(1), flops_at(4)
        if not f1 or not f4:
            log("scan cost-model check inconclusive (no flops reported); "
                "omitting MFU fields for the scan-mode row")
            return False
        once = f4 < 2.0 * f1
        if not once:
            log(f"cost_analysis multiplies scan body by trip count on this "
                f"backend (flops x{f4 / f1:.1f} at length 4); omitting MFU "
                f"fields for the scan-mode row")
        return once
    except Exception as exc:  # noqa: BLE001 - check is best-effort
        log(f"scan cost-model check failed ({exc!r}); omitting MFU fields "
            f"for the scan-mode row")
        return False


def _step_flops_of(compiled, log) -> Optional[float]:
    """XLA's own FLOP count for one compiled step (per-device SPMD
    program) — what MFU should be computed from; an analytic 2*MACs
    estimate would miss rematerialization and the optimizer/BN work XLA
    actually runs. Best-effort: None when the backend has no cost model."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception as exc:  # noqa: BLE001 - cost model is best-effort
        log(f"cost_analysis unavailable: {exc!r}")
        return None


def _add_mfu_fields(result: dict, step_flops: Optional[float],
                    steps_per_s: float, device, log) -> None:
    """Attach achieved TFLOP/s (+ mfu_pct on recognized accelerators)."""
    if not step_flops:
        return
    achieved = step_flops * steps_per_s
    # 4 decimals: tiny CPU validation runs land around 1e-3 TFLOP/s
    # and must not round to a meaningless 0.0
    result["tflops_per_device"] = round(achieved / 1e12, 4)
    peak_tf = _peak_tflops(device)
    if peak_tf:
        result["mfu_pct"] = round(100.0 * achieved / (peak_tf * 1e12), 1)
        log(f"MFU: {result['mfu_pct']}% "
            f"({result['tflops_per_device']} of {peak_tf} TFLOP/s peak)")


def _maybe_dump_hlo(compiled, log) -> None:
    """HOROVOD_BENCH_DUMP_HLO=<path>: write the backend-optimized HLO
    (post AllReduceCombiner / fusion) — the artifact for auditing dtypes
    and host transfers on real hardware. Shared env contract for every
    benchmark script."""
    dump = os.environ.get("HOROVOD_BENCH_DUMP_HLO")
    if not dump:
        return
    try:
        with open(dump, "w") as f:
            f.write(compiled.as_text())
        log(f"compiled HLO written to {dump}")
    except Exception as exc:  # noqa: BLE001
        log(f"HLO dump failed: {exc!r}")


def _maybe_profile_one_batch(run_batch, wait_on, log) -> None:
    """HOROVOD_BENCH_PROFILE=<dir>: capture a device profile (XPlane, see
    tools/profile_summary.py) of ONE warm batch BEFORE the timed
    iterations, so trace overhead never pollutes the reported numbers.
    ``wait_on()`` must block until the dispatched batch completes. The
    trace is always stopped — a live trace across the timed loop would
    silently deflate every reported number."""
    profile_dir = os.environ.get("HOROVOD_BENCH_PROFILE")
    if not profile_dir:
        return
    import jax

    tracing = False
    try:
        jax.profiler.start_trace(profile_dir)
        tracing = True
        run_batch()
        wait_on()
        log(f"profile written to {profile_dir}")
    except Exception as exc:  # noqa: BLE001 - profiling is best-effort
        log(f"profile capture failed: {exc!r}")
    finally:
        if tracing:
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001
                log(f"stop_trace failed: {exc!r}")


def _preflight_backend(attempts: Optional[int] = None,
                       probe_timeout_s: Optional[float] = None,
                       fatal: bool = True):
    """Verify the accelerator backend initializes before touching it here.

    Round-1 postmortem: ``hvd.init()`` was the first JAX backend query in
    this process and it died with "Unable to initialize backend 'axon':
    UNAVAILABLE" — no diagnostics, no retry, rc=1, and no number was ever
    recorded. The plugin can also *hang* (not fail) when the chip is held
    by a stale process, which would turn rc=1 into rc=124. So: probe in a
    subprocess (a hang costs one timeout, not the whole bench), retry with
    backoff (a chip being released frees within seconds), and on exhaustion
    print every actionable fact we can gather before exiting nonzero.
    """
    # Probe with an actual jitted computation, not a device listing: the
    # tunnel has been observed answering jax.devices() in seconds while
    # real compute still hung (round-3 log: listing-probe OK, then both
    # 1100 s measurement attempts died before the first compile finished).
    probe = ("import jax, jax.numpy as jnp; "
             "x = jnp.ones((512, 512), jnp.bfloat16); "
             "jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x)); "
             "d = jax.devices(); print(d[0].platform, len(d), flush=True)")
    log = _log
    if attempts is None:
        # The shared TPU pool has multi-minute busy windows; a driver with
        # a generous job timeout can raise this to ride one out.
        attempts = int(os.environ.get("HOROVOD_BENCH_PREFLIGHT_ATTEMPTS",
                                      "4"))
    if probe_timeout_s is None:
        # Env-tunable so CI tests that exercise the wedge/fallback paths
        # against a nonexistent backend don't pay the full hang budget.
        probe_timeout_s = float(os.environ.get(
            "HOROVOD_BENCH_PROBE_TIMEOUT_S", "120"))
    if os.environ.get("HOROVOD_BENCH_PREFLIGHT", "1") == "0":
        # CI/CPU validation runs pre-pin the platform themselves; the
        # probe would re-discover the (possibly absent) accelerator.
        log("[preflight] skipped (HOROVOD_BENCH_PREFLIGHT=0)")
        return None
    for attempt in range(1, attempts + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                text=True, timeout=probe_timeout_s)
        except subprocess.TimeoutExpired:
            log(f"[preflight {attempt}/{attempts}] backend probe HUNG "
                f"(> {probe_timeout_s:.0f}s) — the accelerator plugin is "
                f"wedged, likely a stale process holding the chip.")
            _print_chip_diagnostics(log)
            # A HUNG probe is not a transient failure: a wedged plugin
            # stays wedged across back-to-back probes, and each identical
            # retry costs the full probe timeout (round 5 burned ~8 min on
            # 4 x 120 s hangs before reaching the fallback line). Fail
            # fast so the caller's fallback/diagnosis runs while the job
            # budget still has room; transient NON-ZERO exits below keep
            # their full retry budget (those do recover within seconds).
            if attempt < attempts:
                log(f"[preflight] skipping the remaining "
                    f"{attempts - attempt} attempt(s): identical hangs "
                    f"would burn "
                    f"{(attempts - attempt) * probe_timeout_s:.0f}s "
                    f"without new information")
            break
        if out.returncode == 0 and out.stdout.strip():
            # The probe's own print is a 2-token line; scan from the end so
            # plugin banners on stdout cannot break the parse.
            for line in reversed(out.stdout.strip().splitlines()):
                tokens = line.split()
                if len(tokens) == 2 and tokens[1].isdigit():
                    platform, ndev = tokens
                    log(f"[preflight {attempt}/{attempts}] backend OK: "
                        f"{ndev} {platform} device(s)")
                    return platform
            log(f"[preflight {attempt}/{attempts}] probe exited 0 but "
                f"printed no recognizable result: {out.stdout!r}")
        log(f"[preflight {attempt}/{attempts}] backend probe failed "
            f"(rc={out.returncode}):")
        for line in out.stderr.strip().splitlines()[-8:]:
            log(f"    {line}")
        _print_chip_diagnostics(log)
        if attempt < attempts:
            time.sleep(5.0 * attempt)
    log("[preflight] giving up: the accelerator backend never initialized. "
        "Fix the environment (kill the chip holder / unset JAX_PLATFORMS) "
        "and re-run.")
    if fatal:
        sys.exit(1)
    return None


def _print_chip_diagnostics(log) -> None:
    """Everything a human (or the next round's builder) needs to unwedge."""
    log(f"    JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')!r} "
        f"XLA_FLAGS={os.environ.get('XLA_FLAGS', '<unset>')!r}")
    me = os.getpid()
    try:
        for pid in sorted(int(p) for p in os.listdir("/proc") if p.isdigit()):
            if pid == me:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode(
                        errors="replace").strip()
            except OSError:
                continue
            # only processes actually RUNNING python (first token), not
            # shells/tools whose argument text merely mentions a keyword
            parts = cmd.split()
            first = os.path.basename(parts[0]) if parts else ""
            if first.startswith("python") and any(
                    k in cmd for k in ("jax", "bench", "graft", "tpu")):
                log(f"    possible chip holder: pid {pid}: {cmd[:120]}")
    except OSError:
        pass


def _harvest_blackbox(args, log, since: float = 0.0) -> list:
    """Satellite of docs/blackbox.md: a failed or timed-out round must
    carry the incident that explains it. Glob any ``blackbox-*.json``
    beside the BENCH json (cwd, ``--timeline-dir``,
    ``HOROVOD_FLIGHTREC_DIR``), classify each with the flight recorder's
    own classifier, and return ``[{path, verdict}]`` for the capture
    record — the r01–r05 hung-preflight rounds produced ZERO diagnostics,
    and this is what makes the next wedged window self-explaining.
    ``since`` bounds the harvest to THIS round's incidents: a stale file
    a previous job left beside the cwd must not be attached as this
    round's explanation (its verdict would point the postmortem at a
    different world's failure)."""
    import glob
    import json as _json

    dirs = [os.getcwd()]
    if getattr(args, "timeline_dir", ""):
        dirs.append(args.timeline_dir)
    try:
        from horovod_tpu.core.config import HOROVOD_FLIGHTREC_DIR

        env_dir = os.environ.get(HOROVOD_FLIGHTREC_DIR, "")
        if env_dir:
            dirs.append(env_dir)
    except Exception:  # noqa: BLE001 - harvest is best-effort
        pass
    seen = set()
    out = []
    for directory in dirs:
        for path in sorted(glob.glob(os.path.join(directory,
                                                  "blackbox-*.json"))):
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            try:
                if since and os.path.getmtime(real) < since:
                    log(f"[blackbox] ignoring stale incident {path} "
                        "(predates this round)")
                    continue
            except OSError:
                continue
            verdict = "unclassifiable"
            try:
                from horovod_tpu.obs.flightrec import classify_incident

                with open(path, "r", encoding="utf-8") as fh:
                    verdict = classify_incident(
                        _json.load(fh))["verdict"]
            except Exception as exc:  # noqa: BLE001 - still record it
                verdict = f"unclassifiable ({exc})"
            log(f"[blackbox] incident {path}: {verdict}")
            out.append({"path": os.path.relpath(path), "verdict": verdict})
    if not out:
        log("[blackbox] no incident files found beside the BENCH json")
    return out


def _emit_fallback(args, log, blackbox: list = ()) -> bool:
    """Emit the newest REAL watcher-captured measurement when live
    measurement is impossible.

    Rounds 1-3 all ended with ``rc=1`` because the shared-pool tunnel was
    wedged at the moment the driver ran this script — even in round 2,
    where the chip had answered for a mid-round window and a real ResNet-50
    number had been measured and recorded by the in-repo watcher. A healthy
    window must survive to the driver's artifact: when the preflight or the
    supervisor gives up, scan the watcher output dirs for the most recent
    real capture of this exact (model, batch size) config and print it as
    the JSON line with explicit provenance fields (``live: false``,
    ``captured_by``, ``captured_at``) so the record is honest about not
    being a live run. ``HOROVOD_BENCH_FALLBACK=0`` disables (the watcher
    itself runs with it off so it can never satisfy itself from old data).
    """
    if os.environ.get("HOROVOD_BENCH_FALLBACK", "1") == "0":
        return False
    import glob
    # Freshness bound: a capture from an old round measured different code;
    # re-emitting it forever would keep the scoreboard green on numbers that
    # no longer describe this tree. Default 24h covers one round's captures.
    max_age_s = float(os.environ.get("HOROVOD_BENCH_FALLBACK_MAX_AGE_S",
                                     "86400"))
    now = time.time()
    expected = f"{args.model}_synthetic_train_images_per_sec_per_device"
    root = os.path.dirname(os.path.abspath(__file__))
    pattern = os.environ.get(
        "HOROVOD_BENCH_FALLBACK_GLOB",
        os.path.join(root, "bench_results_*", "*.json"))
    head = _git_head()
    # Prefer captures measured on the CURRENT revision; fall back to the
    # newest capture of any revision but say so in the emitted line — a
    # within-round capture can still predate perf-relevant commits.
    best = None  # ((revision_matches, captured_at), record, path)
    for path in glob.glob(pattern):
        try:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines()
                         if ln.startswith("{")]
            if not lines:
                continue
            rec = json.loads(lines[-1])
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict) or rec.get("metric") != expected:
            continue
        if rec.get("live") is False:
            continue  # a fallback line must never chain another fallback
        # Config must match the requested one. Captures made before the
        # batch_size stamp existed only qualify for the protocol default.
        if rec.get("batch_size", 32) != args.batch_size:
            continue
        if rec.get("scan_batches"):
            continue  # diagnostic scan-mode runs are not the protocol
        if bool(rec.get("fp16_allreduce")) != args.fp16_allreduce:
            continue  # compression changes the measured step
        if bool(rec.get("int8_allreduce")) != args.int8_allreduce:
            continue
        captured = rec.get("captured_at")
        if not isinstance(captured, (int, float)):
            try:
                captured = os.path.getmtime(path)
            except OSError:
                continue
        if now - captured > max_age_s:
            continue
        rev_match = bool(head) and rec.get("git_sha") == head
        # full-protocol captures beat partials (a run killed mid-protocol
        # banked its completed iterations — honest but lower-confidence),
        # then current-revision beats stale-revision, then freshest wins
        key = (not rec.get("partial", False), rev_match, captured)
        if best is None or key > best[0]:
            best = (key, rec, path)
    if best is None:
        log("[fallback] no previously captured measurement matches "
            f"metric={expected} batch_size={args.batch_size}")
        return False
    (full_protocol, rev_match, captured), rec, path = best
    if not full_protocol:
        log(f"[fallback] NOTE: best capture is a PARTIAL line "
            f"({rec.get('iters_completed')} of the protocol's iterations "
            f"completed before the run was killed)")
    rec["live"] = False
    rec["captured_by"] = "chip_watch"
    rec["captured_at"] = captured
    rec["captured_from"] = os.path.relpath(path, root)
    if blackbox:
        # a wedged round that DID leave flight-recorder incidents: carry
        # their paths + verdict lines in the capture record so the
        # postmortem starts from the emitted artifact (docs/blackbox.md)
        rec["blackbox"] = list(blackbox)
    if head is not None:
        rec["revision_match"] = rev_match
        if not rev_match:
            log(f"[fallback] NOTE: capture was measured on revision "
                f"{rec.get('git_sha') or 'unknown'}, current HEAD is {head} "
                f"— the number may predate perf-relevant commits")
    log(f"[fallback] live measurement impossible — emitting the most "
        f"recent real capture ({path}, captured_at={captured:.0f})")
    print(json.dumps(rec), flush=True)
    return True


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawTextHelpFormatter)
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "vgg16",
                                 "inception3"],
                        help="resnet50 default; resnet101/vgg16/inception3 "
                             "complete the reference's benchmark trio "
                             "(docs/benchmarks.md:5-6)")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="batch size per device (reference default 32)")
    parser.add_argument("--fp16-allreduce", action="store_true",
                        default=False,
                        help="gradient compression during allreduce "
                             "(reference flag; rides bf16 on TPU — the "
                             "MXU-native 16-bit format)")
    parser.add_argument("--int8-allreduce", action="store_true",
                        default=False,
                        help="EQuARX-style block-quantized int8 gradient "
                             "allreduce: ~4x fewer wire bytes than f32 at "
                             "a bounded block-relative error "
                             "(docs/compression.md)")
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--timeline-dir", default="",
                        help="capture per-rank Chrome-trace timeline "
                             "artifacts of the eager control plane into "
                             "this directory alongside the BENCH json "
                             "(sets HOROVOD_TIMELINE + "
                             "HOROVOD_TIMELINE_ALL_RANKS; merge with "
                             "tools/trace_merge.py — docs/tracing.md)")
    parser.add_argument("--autotune", action="store_true", default=False,
                        help="enable the closed-loop tuning plane for "
                             "this run (HOROVOD_AUTOTUNE=1) and capture "
                             "its JSONL decision log beside the BENCH "
                             "json (into --timeline-dir when set, else "
                             "the cwd; render with tools/tune_report.py "
                             "— docs/autotune.md). Governs the eager "
                             "control plane; SPMD steps have no cycles "
                             "to tune.")
    parser.add_argument("--subbuffers", type=int, default=0,
                        help="generation-ordered sub-buffer flush count "
                             "for the eager data plane "
                             "(HOROVOD_FUSION_SUBBUFFERS=N, "
                             "docs/tensor-fusion.md): >=2 overlaps "
                             "backprop compute with in-flight allreduce; "
                             "achieved overlap ratio lands in the BENCH "
                             "json. Governs the eager control plane; "
                             "SPMD steps overlap inside XLA.")
    parser.add_argument("--fused-apply", action="store_true",
                        default=False,
                        help="arm the fused reduce+apply plane for this "
                             "run (HOROVOD_FUSED_APPLY=1, "
                             "docs/tensor-fusion.md §fused apply): "
                             "hvd.apply_step lands applied parameters "
                             "from one reduce+apply program per batch; "
                             "apply-batch and dispatch provenance lands "
                             "in the BENCH json. Governs the eager "
                             "plane; SPMD steps fuse inside XLA.")
    parser.add_argument("--zero1", action="store_true",
                        default=False,
                        help="arm the ZeRO-1 partitioned-optimizer plane "
                             "for this run (HOROVOD_ZERO=1, "
                             "docs/sharding.md): hvd.apply_step shards "
                             "optimizer state across ranks and flushes "
                             "batches as one reduce-scatter+apply+"
                             "all-gather program; zero1 batch and "
                             "per-rank slot-residency provenance lands "
                             "in the BENCH json. Implies the fused "
                             "reduce+apply plane.")
    parser.add_argument("--grad-sentry", default="",
                        choices=["", "off", "warn", "skip", "zero",
                                 "abort"],
                        help="arm the gradient sentry for this run "
                             "(HOROVOD_GRAD_SENTRY=<policy>, "
                             "docs/integrity.md): reduced gradients are "
                             "screened for NaN/Inf on the eager plane and "
                             "guarded in the compiled SPMD step; trip "
                             "counters land in the BENCH json")
    parser.add_argument("--tensorwatch", type=int, default=0,
                        help="arm the gradient numerics observatory for "
                             "this run (HOROVOD_TENSORWATCH_INTERVAL_"
                             "STEPS=N, docs/tensorwatch.md): every Nth "
                             "eager allreduce batch is measured — "
                             "per-tensor norms, decode SNR, the top-k "
                             "sparse-readiness curve — and SNR/top-k "
                             "provenance lands in the BENCH json. "
                             "Governs the eager control plane; SPMD "
                             "steps have no engine batches to sample.")
    parser.add_argument("--hierarchy", default="",
                        help="arm the hierarchical negotiation tree for "
                             "this run (HOROVOD_HIERARCHY=auto|islands:N, "
                             "docs/hierarchy.md): island heads merge "
                             "their members' negotiation traffic and the "
                             "root absorbs one submission per island per "
                             "cycle; topology and root-message-count "
                             "provenance lands in the BENCH json off the "
                             "live registry. Needs the Python controller "
                             "wire (armed alongside); a world the "
                             "planner cannot split degrades to flat "
                             "with a warning and honest zero counters.")
    parser.add_argument("--_measure", action="store_true",
                        help=argparse.SUPPRESS)  # internal: child mode
    parser.add_argument("--warm-init-cache", action="store_true",
                        default=False,
                        help="build this config's host-init cache entry "
                             "on CPU and exit without touching the "
                             "accelerator (run with "
                             "HOROVOD_BENCH_PLATFORM=cpu); a warm entry "
                             "lets a real attempt reach its first device "
                             "op in seconds instead of after a ~90s host "
                             "init, which matters when the tunnel's "
                             "healthy windows are short")
    parser.add_argument("--warm-devices", type=int, default=1,
                        help="device count of the topology --warm-init-"
                             "cache targets (global batch = batch-size x "
                             "this); default 1, the single-chip bench")
    args = parser.parse_args(argv)
    if args.fp16_allreduce and args.int8_allreduce:
        # reject before preflight/supervision spin up the accelerator: a
        # CLI usage error must not reach the wedge/fallback machinery
        parser.error("--fp16-allreduce and --int8-allreduce are "
                     "mutually exclusive")
    return args


def _init_cache_path(args, global_batch, side) -> str:
    """Host-init cache entry for this bench config (shared policy:
    ``core.platform.init_cache_path`` — this file is hashed in so editing
    ``synthesize()``/init code here invalidates its own entries)."""
    from horovod_tpu.core.platform import init_cache_path

    return init_cache_path(f"{args.model}_gb{global_batch}_s{side}",
                           extra_sources=[os.path.abspath(__file__)])


def _supervise(args) -> None:
    """Run the measurement in a killable child, retrying on wedge/failure.

    Round-2 postmortem: preflight passed, ``hvd.init()`` saw the chip, and
    then the FIRST compile RPC hung for ~35 minutes before erroring
    UNAVAILABLE — the shared-pool tunnel can wedge after a clean startup,
    not just during it. A hang inside this process would eat the driver's
    whole job budget, so the measurement runs in a subprocess whose life is
    bounded by HOROVOD_BENCH_MEASURE_TIMEOUT (default 20 min) and retried
    (HOROVOD_BENCH_MEASURE_ATTEMPTS, default 2); the child is killed with
    its whole process group because a wedged TPU client ignores SIGTERM.
    Child stderr is inherited so progress streams into the driver log; the
    JSON result line is relayed from child stdout.
    """
    log = _log
    round_start = time.time()  # recency bound for _harvest_blackbox
    timeout_s = float(os.environ.get("HOROVOD_BENCH_MEASURE_TIMEOUT",
                                     "1200"))
    attempts = int(os.environ.get("HOROVOD_BENCH_MEASURE_ATTEMPTS", "2"))
    child_argv = [sys.executable, os.path.abspath(__file__), "--_measure",
                  "--model", args.model,
                  "--batch-size", str(args.batch_size),
                  "--num-warmup-batches", str(args.num_warmup_batches),
                  "--num-batches-per-iter", str(args.num_batches_per_iter),
                  "--num-iters", str(args.num_iters)] + \
        (["--fp16-allreduce"] if args.fp16_allreduce else []) + \
        (["--int8-allreduce"] if args.int8_allreduce else []) + \
        (["--timeline-dir", args.timeline_dir] if args.timeline_dir
         else []) + \
        (["--autotune"] if args.autotune else []) + \
        (["--grad-sentry", args.grad_sentry] if args.grad_sentry else []) + \
        (["--subbuffers", str(args.subbuffers)] if args.subbuffers else []) + \
        (["--fused-apply"] if args.fused_apply else []) + \
        (["--zero1"] if args.zero1 else []) + \
        (["--tensorwatch", str(args.tensorwatch)]
         if args.tensorwatch else []) + \
        (["--hierarchy", args.hierarchy] if args.hierarchy else [])
    import signal
    import subprocess as sp

    timed_out = False  # last attempt's outcome gates the wedge fallback
    for attempt in range(1, attempts + 1):
        log(f"[supervise {attempt}/{attempts}] measuring "
            f"(timeout {timeout_s:.0f}s)")
        child = sp.Popen(child_argv, stdout=sp.PIPE, text=True,
                         start_new_session=True)
        timed_out = False
        try:
            stdout, _ = child.communicate(timeout=timeout_s)
        except sp.TimeoutExpired:
            timed_out = True
            log(f"[supervise {attempt}/{attempts}] measurement HUNG "
                f"> {timeout_s:.0f}s — killing the child process group")
            try:
                os.killpg(child.pid, signal.SIGKILL)
            except OSError:
                pass
            # re-communicate to salvage the pipe: a child that finished
            # measuring and printed its result before wedging in TPU
            # client *teardown* still produced a good number
            stdout, _ = child.communicate()
        if child.returncode == 0 or timed_out:
            # relay the one JSON result line (last stdout line). Validate it
            # parses: a line truncated mid-write by the SIGKILL must fall
            # through to the retry path, not reach the driver as corrupt JSON.
            from horovod_tpu.core.provenance import last_json_line

            line, _ = last_json_line(stdout, want=dict)
            if line is not None:
                print(line, flush=True)
                return
            log(f"[supervise {attempt}/{attempts}] no JSON result line "
                f"{'salvaged from the killed child' if timed_out else 'in child stdout'}: "
                f"{(stdout or '')[-200:]!r}")
        else:
            log(f"[supervise {attempt}/{attempts}] measurement failed "
                f"(rc={child.returncode})")
        if attempt < attempts:
            if timed_out and os.environ.get("HOROVOD_BENCH_PREFLIGHT",
                                            "1") != "0":
                # A SIGKILLed TPU client can leave the tunnel lease held
                # for a while; respawning after a fixed 10 s burned whole
                # attempts on a chip that wasn't back yet (round-3 log:
                # attempt 2 hung in hvd.init 18 s after the kill). Probe
                # until the backend answers again — non-fatally, so an
                # exhausted probe still lets the last attempt try.
                log(f"[supervise {attempt}/{attempts}] waiting for the "
                    f"backend to come back before the next attempt")
                _preflight_backend(fatal=False)
            else:
                time.sleep(10.0)
    if timed_out:
        # Only a LAST attempt that HUNG qualifies for the provenance-marked
        # fallback: a child that *fails* (rc != 0) with a healthy chip is a
        # code regression, and masking it with a stale capture would let
        # the bench rot green — even if an earlier attempt hit a wedge, the
        # final fast failure is the diagnosis that stands. Wedges that
        # strike before this point (the backend never initializing) take
        # the preflight fallback in main().
        log("[supervise] giving up: no measurement completed. The "
            "accelerator pool stayed wedged; re-run when the chip frees up.")
        if _emit_fallback(args, log, blackbox=_harvest_blackbox(
                args, log, since=round_start)):
            return
    else:
        log("[supervise] giving up: the last measurement attempt failed "
            "without hanging — that is a bench/code failure, not a chip "
            "wedge; no fallback will be emitted.")
        # a failed round should still name its incident: any black-box
        # dump the dying world left explains the failure better than rc=1
        _harvest_blackbox(args, log, since=round_start)
    sys.exit(1)


def main() -> None:
    run_start = time.time()  # recency bound for _harvest_blackbox
    args = _parse_args()

    if args.warm_init_cache:
        # Warm mode never needs the accelerator: pin CPU (unless the
        # caller pinned something else) and skip preflight/supervision.
        os.environ.setdefault("HOROVOD_BENCH_PLATFORM", "cpu")
        resolved = os.environ["HOROVOD_BENCH_PLATFORM"].strip().lower()
        if resolved != "cpu":
            # The documented contract is ZERO accelerator contact; a
            # session-pinned platform would silently turn the warm pass
            # into a full accelerator session. Refuse before any backend
            # query so the contract holds even in the failure path.
            _log(f"--warm-init-cache requires the CPU backend but "
                 f"HOROVOD_BENCH_PLATFORM={resolved!r} is pinned; unset it "
                 f"or set HOROVOD_BENCH_PLATFORM=cpu for the warm pass.")
            sys.exit(2)

    if not args._measure and not args.warm_init_cache:
        preflight_on = os.environ.get("HOROVOD_BENCH_PREFLIGHT", "1") != "0"
        # The chip watcher runs a jitted-matmul compute probe seconds
        # before spawning the bench; its runs skip only this INITIAL
        # preflight (one fewer backend spin-up inside a healthy window)
        # while keeping the supervisor's inter-attempt backend wait.
        initial_on = os.environ.get("HOROVOD_BENCH_PREFLIGHT_INITIAL",
                                    "1") != "0"
        if preflight_on and initial_on:
            if _preflight_backend(fatal=False) is None:
                if _emit_fallback(args, _log,
                                  blackbox=_harvest_blackbox(
                                      args, _log, since=run_start)):
                    return
                sys.exit(1)
        # Supervision defaults to following preflight (CI/CPU runs that
        # pin the platform in-process skip both); HOROVOD_BENCH_SUPERVISE
        # overrides either way, and the CPU regression test uses it with
        # HOROVOD_BENCH_PLATFORM=cpu to exercise this exact driver path.
        if os.environ.get("HOROVOD_BENCH_SUPERVISE",
                          "1" if preflight_on else "0") != "0":
            _supervise(args)
            return

    if args.timeline_dir:
        # Per-rank timeline capture (docs/tracing.md): BEFORE hvd.init()
        # reads the config. setdefault so an operator's explicit
        # HOROVOD_TIMELINE pins win; ALL_RANKS makes the artifacts
        # rank-suffixed and therefore merge-ready for trace_merge.py the
        # moment a healthy accelerator window produces them.
        os.makedirs(args.timeline_dir, exist_ok=True)
        os.environ.setdefault(
            "HOROVOD_TIMELINE",
            os.path.join(args.timeline_dir, f"{args.model}_timeline.json"))
        os.environ.setdefault("HOROVOD_TIMELINE_ALL_RANKS", "1")
        os.environ.setdefault("HOROVOD_TIMELINE_MARK_CYCLES", "1")
        _log(f"timeline capture -> {os.environ['HOROVOD_TIMELINE']} "
             f"(per-rank; merge with tools/trace_merge.py)")

    if args.grad_sentry:
        # Data-plane integrity plane (docs/integrity.md): like --autotune,
        # BEFORE hvd.init() reads the config (and before the SPMD step
        # traces — the in-program guard reads the policy at trace time);
        # setdefault so an operator's explicit pin wins.
        os.environ.setdefault("HOROVOD_GRAD_SENTRY", args.grad_sentry)
        _log(f"grad sentry armed: "
             f"HOROVOD_GRAD_SENTRY={os.environ['HOROVOD_GRAD_SENTRY']} "
             f"(trip counters land in the BENCH json)")

    if args.subbuffers:
        # Sub-buffer flush pipelining (docs/tensor-fusion.md): like
        # --grad-sentry, BEFORE hvd.init() reads the config; setdefault
        # so an operator's explicit pin wins.
        os.environ.setdefault("HOROVOD_FUSION_SUBBUFFERS",
                              str(args.subbuffers))
        _log(f"sub-buffer flush armed: HOROVOD_FUSION_SUBBUFFERS="
             f"{os.environ['HOROVOD_FUSION_SUBBUFFERS']} (overlap ratio "
             f"lands in the BENCH json)")

    if args.fused_apply:
        # Fused reduce+apply (docs/tensor-fusion.md §fused apply): like
        # --subbuffers, BEFORE hvd.init() reads the config; setdefault
        # so an operator's explicit pin wins.
        os.environ.setdefault("HOROVOD_FUSED_APPLY", "1")
        _log(f"fused reduce+apply armed: HOROVOD_FUSED_APPLY="
             f"{os.environ['HOROVOD_FUSED_APPLY']} (apply-batch and "
             f"dispatch provenance lands in the BENCH json)")

    if args.zero1:
        # ZeRO-1 partitioned optimizer state (docs/sharding.md): like
        # --fused-apply, BEFORE hvd.init() reads the config; setdefault
        # so an operator's explicit pin wins. The zero1 flush IS a
        # fused program, so the fused-apply plane is armed alongside.
        os.environ.setdefault("HOROVOD_ZERO", "1")
        os.environ.setdefault("HOROVOD_FUSED_APPLY", "1")
        _log(f"ZeRO-1 sharding armed: HOROVOD_ZERO="
             f"{os.environ['HOROVOD_ZERO']} (zero1 batch and slot-"
             f"residency provenance lands in the BENCH json)")

    if args.tensorwatch:
        # Gradient numerics observatory (docs/tensorwatch.md): like
        # --grad-sentry, BEFORE hvd.init() reads the config; setdefault
        # so an operator's explicit pin wins.
        os.environ.setdefault("HOROVOD_TENSORWATCH_INTERVAL_STEPS",
                              str(args.tensorwatch))
        _log(f"numerics observatory armed: "
             f"HOROVOD_TENSORWATCH_INTERVAL_STEPS="
             f"{os.environ['HOROVOD_TENSORWATCH_INTERVAL_STEPS']} "
             f"(SNR/top-k provenance lands in the BENCH json)")

    if args.hierarchy:
        # Negotiation tree (docs/hierarchy.md): like --grad-sentry,
        # BEFORE hvd.init() reads the config; setdefault so an
        # operator's explicit pins win. The island RPCs ride the Python
        # controller wire, so that is armed alongside — the native
        # controller would silently degrade the run to flat and the
        # capture would measure nothing tree-shaped.
        os.environ.setdefault("HOROVOD_HIERARCHY", args.hierarchy)
        os.environ.setdefault("HOROVOD_NATIVE_CONTROLLER", "0")
        _log(f"negotiation tree armed: HOROVOD_HIERARCHY="
             f"{os.environ['HOROVOD_HIERARCHY']} (topology and "
             f"root-message provenance lands in the BENCH json)")

    if args.autotune:
        # Closed-loop tuning plane (docs/autotune.md): like --timeline-dir,
        # BEFORE hvd.init() reads the config; setdefault so an operator's
        # explicit pins win. The decision log lands beside the other
        # artifacts so a capture round carries its own tuning audit.
        dest = args.timeline_dir or "."
        os.makedirs(dest, exist_ok=True)
        os.environ.setdefault("HOROVOD_AUTOTUNE", "1")
        os.environ.setdefault(
            "HOROVOD_AUTOTUNE_DECISIONS",
            os.path.join(dest, f"{args.model}_autotune_decisions.jsonl"))
        _log(f"autotune decision log -> "
             f"{os.environ['HOROVOD_AUTOTUNE_DECISIONS']} "
             f"(render with tools/tune_report.py)")

    import jax

    platform_pin = os.environ.get("HOROVOD_BENCH_PLATFORM")
    if platform_pin:
        jax.config.update("jax_platforms", platform_pin)
    _setup_accelerator_cache(jax)
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from benchmarks._dp_step import make_dp_train_step
    from horovod_tpu.models import InceptionV3, ResNet50, ResNet101, VGG16

    hvd.init()
    n_dev = hvd.local_device_count()
    mesh = hvd.parallel.data_parallel_mesh()
    log = _log
    log(f"Model: {args.model}, batch {args.batch_size}/device, "
        f"devices: {n_dev} ({jax.devices()[0].platform})")

    model_cls = {"resnet50": ResNet50, "resnet101": ResNet101,
                 "vgg16": VGG16, "inception3": InceptionV3}[args.model]
    model = model_cls(num_classes=1000)
    side = 299 if args.model == "inception3" else 224
    # Warm mode runs on the host backend, whose device count is not the
    # topology being warmed for — size the arrays for the target instead
    # so a real attempt's cache lookup hits (--warm-devices, default the
    # single-chip bench).
    global_batch = args.batch_size * (args.warm_devices
                                      if args.warm_init_cache else n_dev)

    def synthesize():
        rng = jax.random.PRNGKey(0)
        return (jax.random.normal(rng, (global_batch, side, side, 3),
                                  jnp.float32),
                jax.random.randint(rng, (global_batch,), 0, 1000))

    # Model init and data synthesis are full extra device compiles that
    # contribute nothing to the measurement; run both on the host CPU
    # backend (see core.platform.init_on_host_cpu for the postmortem) and
    # place the transfers with the step's own shardings — batch split on
    # the data axis, everything else replicated; committed arrays are
    # never auto-resharded by the jitted step. The AOT train-step compile
    # stays the attempt's ONLY big accelerator compile.
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.core.platform import host_init_cached, init_on_host_cpu

    cache_path = _init_cache_path(args, global_batch, side)

    def make_host():
        return (*synthesize(),
                model.init(jax.random.PRNGKey(1),
                           np.zeros((2, side, side, 3), np.float32)))

    if args.warm_init_cache:
        # CPU-only mode: build the cache entry and stop before any
        # accelerator contact (pin HOROVOD_BENCH_PLATFORM=cpu when the
        # session env points at the chip). Belt-and-braces platform
        # check on the CONFIG, never on jax.devices() — a device query
        # would itself initialize the accelerator backend this guard
        # exists to refuse (and hang on a wedged chip).
        resolved_cfg = str(getattr(jax.config, "jax_platforms", "") or "")
        if resolved_cfg != "cpu":
            log(f"--warm-init-cache requires jax_platforms='cpu' but the "
                f"config resolved to {resolved_cfg!r} — refusing to warm "
                f"the cache through an accelerator session.")
            sys.exit(2)
        host_init_cached(cache_path, make_host, log=log)
        log("init cache warmed; exiting without accelerator contact")
        return

    placed = init_on_host_cpu(
        lambda: host_init_cached(cache_path, make_host, log=log),
        (NamedSharding(mesh, P("data")), NamedSharding(mesh, P("data")),
         NamedSharding(mesh, P())), log=log)
    if placed is not None:
        images, labels, variables = placed
    else:
        log("host-CPU init/placement unavailable (see warning above); "
            "initializing on device")
        images, labels = synthesize()
        variables = model.init(jax.random.PRNGKey(1), images[:2])
    log("model initialized")
    # vgg16 has no BatchNorm -> no batch_stats collection
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    # --fp16-allreduce maps to bf16 cast-compression on TPU (the format
    # the ICI collectives and MXU natively carry; fp16 would round-trip
    # through an alien dtype); --int8-allreduce rides the EQuARX
    # block-quantized wire; reference flag semantics otherwise
    # (mutual exclusion enforced in _parse_args)
    compression = (hvd.Compression.int8 if args.int8_allreduce
                   else hvd.Compression.bf16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(optax.sgd(0.01), axis_name="data",
                                   compression=compression)
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    # HOROVOD_BENCH_SCAN_BATCHES (opt-in): execute batches in lax.scan-ned
    # device calls — =1 means one call per whole iteration
    # (--num-batches-per-iter batches), =N>1 means N-batch calls (N must
    # divide --num-batches-per-iter). Diagnostic, not the reference
    # protocol — comparing against the default isolates
    # Python-dispatch/pipeline-drain overhead from true device time. The
    # result line is marked (scan_batches, vs_baseline null) and the wedge
    # fallback never substitutes a scan-mode capture for a protocol run.
    scan_env = int(os.environ.get("HOROVOD_BENCH_SCAN_BATCHES", "0"))
    scan_mode = scan_env > 0
    scan_batches = ((args.num_batches_per_iter if scan_env == 1
                     else scan_env) if scan_mode else 1)
    if scan_mode:
        if args.num_batches_per_iter % scan_batches:
            log(f"HOROVOD_BENCH_SCAN_BATCHES={scan_batches} must divide "
                f"--num-batches-per-iter {args.num_batches_per_iter}")
            sys.exit(2)
        log(f"scan mode: {scan_batches} batches per dispatched call "
            f"(NOT the reference protocol)")
    step = make_dp_train_step(model, opt, mesh, axis_name="data",
                              scan_batches=scan_batches,
                              # compressed allreduce must CARRY the bytes:
                              # see _dp_step's explicit_grad_reduce note
                              explicit_grad_reduce=(args.fp16_allreduce
                                                    or args.int8_allreduce)
                              or None)

    # AOT-compile once; _step_flops_of reads the executable's own cost
    # analysis for the MFU denominator's numerator.
    log("Compiling train step (AOT)...")
    compiled = step.lower(params, opt_state, batch_stats, images,
                          labels).compile()
    step_flops = _step_flops_of(compiled, log)
    _maybe_dump_hlo(compiled, log)

    def run_batch():
        nonlocal params, opt_state, batch_stats
        params, opt_state, batch_stats = compiled(
            params, opt_state, batch_stats, images, labels)

    # in scan mode each dispatched call IS scan_batches batches; ceil so
    # at least the requested warmup runs, and 0 still means none
    warmup_calls = -(-args.num_warmup_batches // scan_batches)
    calls_per_iter = args.num_batches_per_iter // scan_batches
    log(f"Running {warmup_calls * scan_batches} warmup batches...")
    for _ in range(warmup_calls):
        run_batch()
    jax.block_until_ready(params)

    img_secs = []
    _maybe_profile_one_batch(run_batch,
                             lambda: jax.block_until_ready(params), log)

    # Provenance stamps shared by partial and final lines: captures are
    # self-describing so the wedge-fallback path (_emit_fallback) can
    # match them to a requested config and rank them by freshness.
    provenance = {
        "metric": f"{args.model}_synthetic_train_images_per_sec_per_device",
        "unit": "img/s",
        "live": True,
        "batch_size": args.batch_size,
        "n_devices": n_dev,
        "git_sha": _git_head(),
    }
    if scan_mode:
        provenance["scan_batches"] = scan_batches  # marked: not protocol
    if args.fp16_allreduce:
        provenance["fp16_allreduce"] = True
    if args.int8_allreduce:
        provenance["int8_allreduce"] = True
    if args.grad_sentry:
        provenance["grad_sentry"] = args.grad_sentry
    if args.subbuffers:
        provenance["subbuffers"] = args.subbuffers
    if args.fused_apply:
        provenance["fused_apply"] = True
    if args.zero1:
        provenance["zero1"] = True
    if args.tensorwatch:
        provenance["tensorwatch"] = args.tensorwatch
    if args.hierarchy:
        provenance["hierarchy"] = args.hierarchy

    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(calls_per_iter):
            run_batch()
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        rate = global_batch * args.num_batches_per_iter / dt
        img_secs.append(rate)
        log(f"Iter #{i}: {rate:.1f} img/sec total")
        # Incremental partial capture: a run killed at iteration k still
        # banks an honest number — the line is provenance-marked
        # (partial: true, iters_completed) so the supervisor's salvage and
        # the wedge fallback can use it while preferring full-protocol
        # lines. The FINAL result line below is printed last, so
        # last-JSON-line consumers see partials only when the run died.
        if i + 1 < args.num_iters:
            partial = dict(provenance)
            partial.update({
                "value": round(float(np.mean(img_secs)) / n_dev, 2),
                "iters_completed": i + 1,
                "partial": True,
                "captured_at": round(time.time(), 1),
            })
            print(json.dumps(partial), flush=True)

    mean = float(np.mean(img_secs))
    conf = float(1.96 * np.std(img_secs))
    per_device = mean / n_dev
    log(f"Img/sec/device: {per_device:.1f} +- {conf / n_dev:.1f}")
    log(f"Total img/sec on {n_dev} device(s): {mean:.1f} +- {conf:.1f}")

    # the P100 anchor is a ResNet-101 figure; a cross-model ratio would be
    # meaningless for vgg16/inception3, so emit null there — and for the
    # non-protocol scan diagnostic, whatever the model
    vs_baseline = (round(per_device / REFERENCE_PER_DEVICE_IMG_S, 3)
                   if args.model.startswith("resnet") and not scan_mode
                   else None)
    result = dict(provenance)
    result.update({
        "value": round(per_device, 2),
        "vs_baseline": vs_baseline,
        "captured_at": round(time.time(), 1),
    })
    if args.grad_sentry:
        # integrity-plane audit beside the number (docs/integrity.md):
        # eager-plane trips/checks plus the compiled step's guarded
        # lowerings, straight off the metrics registry
        snap = hvd.metrics_snapshot()

        def _total(family):
            fam = snap.get(family)
            return sum(s["value"] for s in fam["samples"]) if fam else 0

        result["sentry_trips"] = _total("horovod_sentry_trips_total")
        result["sentry_checks"] = _total("horovod_sentry_checks_total")
        result["sentry_spmd_guards"] = _total(
            "horovod_sentry_spmd_guards_total")
    if args.subbuffers:
        # overlap audit beside the number (docs/tensor-fusion.md): the
        # eager engine's achieved overlap ratio and pipeline depth. Read
        # off the LIVE engine only — the SPMD bench loop itself has no
        # eager cycles, and spinning an engine up just to report zeros
        # would be a side effect, not provenance.
        from horovod_tpu.ops import engine as _engine_mod

        eng = _engine_mod._engine
        ov = eng.overlap_stats() if eng is not None else {
            "flushes": 0, "inflight_peak": 0, "overlap_seconds": 0.0,
            "execute_busy_seconds": 0.0}
        busy = ov["execute_busy_seconds"]
        result["subbuffer_flushes"] = ov["flushes"]
        result["flush_inflight_peak"] = ov["inflight_peak"]
        result["overlap_seconds"] = round(ov["overlap_seconds"], 6)
        result["overlap_ratio"] = round(
            ov["overlap_seconds"] / busy, 4) if busy > 0 else 0.0
    if args.fused_apply:
        # apply-fused audit beside the number (docs/tensor-fusion.md
        # §fused apply): apply-capable batches by execution strategy and
        # the dispatches-per-step story, read off the LIVE engine only
        # (the --subbuffers pattern: the SPMD bench loop has no eager
        # cycles, and a side-effect engine would be fake provenance).
        from horovod_tpu.ops import engine as _engine_mod

        eng = _engine_mod._engine
        ap = eng.apply_stats() if eng is not None else {
            "exec_fused": False, "fused_batches": 0, "split_batches": 0,
            "apply_dispatches": 0}
        result["apply_fused_batches"] = ap["fused_batches"]
        result["apply_split_batches"] = ap["split_batches"]
        result["apply_dispatches"] = ap["apply_dispatches"]
        batches = ap["fused_batches"] + ap["split_batches"]
        result["apply_dispatches_per_batch"] = round(
            ap["apply_dispatches"] / batches, 3) if batches else 0.0
    if args.zero1:
        # zero1 audit beside the number (docs/sharding.md): batches that
        # flushed as one reduce-scatter+apply+all-gather program and this
        # rank's resident slot bytes, read off the LIVE engine and the
        # sharding-plane gauges (the --fused-apply pattern).
        from horovod_tpu.obs.registry import registry as _reg
        from horovod_tpu.ops import engine as _engine_mod

        eng = _engine_mod._engine
        ap = eng.apply_stats() if eng is not None else {
            "exec_zero1": False, "zero1_batches": 0}
        result["zero1_exec"] = bool(ap.get("exec_zero1"))
        result["zero1_batches"] = ap.get("zero1_batches", 0)
        fams = _reg().snapshot()
        slot_fam = fams.get("horovod_shard_slot_bytes") or {}
        samples = slot_fam.get("samples") or [{}]
        result["zero1_slot_bytes"] = samples[0].get("value", 0)
    if args.tensorwatch:
        # numerics-observatory audit beside the number
        # (docs/tensorwatch.md): sampled-batch count off the LIVE
        # engine's watch (the --subbuffers pattern — no side-effect
        # engine), worst decode SNR and the sparse-readiness curve off
        # the registry gauges the observatory maintains.
        from horovod_tpu.obs.tensorwatch import (
            FAMILY_CODEC_SNR,
            FAMILY_TOPK,
            _labeled_values,
        )
        from horovod_tpu.ops import engine as _engine_mod

        eng = _engine_mod._engine
        watch = getattr(eng, "_tensorwatch", None) \
            if eng is not None else None
        tw = watch.stats() if watch is not None else {
            "batches": 0, "samples": 0, "tensors": 0}
        result["tensorwatch_samples"] = tw["samples"]
        result["tensorwatch_tensors"] = tw["tensors"]
        snap = hvd.metrics_snapshot()

        def _labeled(family, label):
            # the report fold's one definition of the labeled-samples
            # extraction (obs.tensorwatch), not a local re-implementation
            return _labeled_values(snap, family, label)

        snrs = _labeled(FAMILY_CODEC_SNR, "codec")
        if snrs:
            result["tensorwatch_worst_snr_db"] = round(
                min(snrs.values()), 2)
            result["tensorwatch_snr_by_codec"] = {
                c: round(v, 2) for c, v in sorted(snrs.items())}
        topk = _labeled(FAMILY_TOPK, "k")
        if topk:
            result["tensorwatch_topk_mass"] = {
                k: round(v, 4) for k, v in sorted(topk.items())}
    if args.hierarchy:
        # tree-plane audit beside the number (docs/hierarchy.md): the
        # resolved topology and the root's absorbed message count off
        # the LIVE registry — a degraded-to-flat run reports islands 0
        # and zero root messages, never a guessed topology.
        snap = hvd.metrics_snapshot()

        def _hier_total(family):
            fam = snap.get(family)
            return sum(s["value"] for s in fam["samples"]) if fam else 0

        result["hier_islands"] = int(
            _hier_total("horovod_hier_islands"))
        result["hier_root_messages"] = int(
            _hier_total("horovod_hier_root_messages_total"))
        result["hier_merged_cycles"] = int(
            _hier_total("horovod_hier_merged_cycles_total"))
        result["hier_raw_cycles"] = int(
            _hier_total("horovod_hier_raw_cycles_total"))
    # cost_analysis() reports the per-device SPMD program's flops — and for
    # a lax.scan program it must count the loop BODY once, not times the
    # trip count, or mfu/tflops inflate by scan_batches. One body == one
    # batch in either mode, so the rate to multiply by is batches/s — but
    # in scan mode only after verifying the count-once behavior on this
    # backend (two toy compiles; omit MFU fields if it doesn't hold).
    if not scan_mode or _scan_cost_counts_body_once(log):
        _add_mfu_fields(result, step_flops, mean / global_batch,
                        jax.devices()[0], log)
    print(json.dumps(result))
    hvd.shutdown()


if __name__ == "__main__":
    main()
