#!/bin/bash
# Chip watcher: wait for the axon TPU tunnel to actually COMPUTE, then
# capture every bench entry that has not produced a parseable JSON result
# yet, re-probing between entries so a half-wedged tunnel costs a sleep,
# not the whole series.
#
# This is the consolidation of the five round-grown variants
# (chip_watch.sh v1 … chip_watch5.sh); their hard-won behaviors are now
# defaults here:
#   * the probe is a real jitted matmul with block_until_ready, not
#     jax.devices() — the tunnel can list devices in seconds and still
#     hang the first computation for >15 min (round-3 postmortem);
#   * only missing entries re-run, keyed on a parseable last JSON line,
#     so a kill/restart resumes instead of repeating landed captures;
#   * 45 s idle cadence (round-5: 120 s could miss a <5-minute healthy
#     window outright; the shared persistent compile cache keeps
#     re-probes cheap);
#   * HOROVOD_BENCH_FALLBACK=0 (round 4: a wedge must leave a hole, not
#     a stale number) and HOROVOD_BENCH_PREFLIGHT_INITIAL=0 (round 5:
#     the compute probe seconds earlier is stronger than the bench's
#     initial preflight, whose redundant backend spin-up cost the 08:32
#     window its first device op).
#
# Usage: chip_watch.sh [--out DIR] [--idle-sleep SECS]
#                      [--probe-timeout SECS] [--entries a,b,c]
#   --out           results directory (default bench_results_r5; use a
#                   fresh dir per round so prior wedge logs stay intact)
#   --idle-sleep    seconds between probes while the chip is wedged
#   --probe-timeout seconds the compute probe may take before it counts
#                   as wedged
#   --entries       comma-separated subset of entry names to capture
#                   (default: the full series; see ENTRIES below)
#
# Run it under tools/chip_watch_deadline.sh when the round has a hard
# end: the supervisor SIGKILLs this watcher's whole process group at the
# deadline so the driver's own bench run owns the tunnel alone.
# Kill a bare watcher with: pkill -f chip_watch
set -u
cd /root/repo

OUT=bench_results_r5
IDLE_SLEEP=45
PROBE_TIMEOUT=150
ONLY_ENTRIES=""
while [ $# -gt 0 ]; do
    case "$1" in
        --out) OUT="$2"; shift 2 ;;
        --idle-sleep) IDLE_SLEEP="$2"; shift 2 ;;
        --probe-timeout) PROBE_TIMEOUT="$2"; shift 2 ;;
        --entries) ONLY_ENTRIES="$2"; shift 2 ;;
        -h|--help) grep '^# ' "$0" | sed 's/^# //'; exit 0 ;;
        *) echo "unknown arg: $1 (try --help)" >&2; exit 2 ;;
    esac
done
mkdir -p "$OUT"
log() { echo "[chip_watch $(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }

# name|args — ONCHIP / TORCH / SCAN / LM are dispatch markers, anything
# else is bench.py arguments. Order is capture priority.
ENTRIES=(
    "resnet50|"
    "resnet101_bs64|--model resnet101 --batch-size 64"
    "resnet50_bs128|--model resnet50 --batch-size 128"
    "resnet50_bs256|--model resnet50 --batch-size 256"
    "resnet50_scan|SCAN"
    "torch_synthetic|TORCH"
    "lm_flash|LM --attention flash"
    "lm_dense|LM --attention dense"
    "lm_flash_4k|LM --attention flash --seq-len 4096 --batch-size 2 --remat"
    "vgg16|--model vgg16"
    "inception3|--model inception3"
    "onchip_tpu|ONCHIP"
)

wanted() {  # no --entries = everything; else exact-name membership
    [ -z "$ONLY_ENTRIES" ] && return 0
    case ",$ONLY_ENTRIES," in *",$1,"*) return 0 ;; esac
    return 1
}

compute_probe() {
    timeout "$PROBE_TIMEOUT" python -c "
import jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = jax.jit(lambda a: (a @ a).sum())(x)
jax.block_until_ready(y)
print('COMPUTE_OK', jax.devices()[0].platform, flush=True)
" > "$OUT/probe.out" 2>&1
    local rc=$?
    if [ $rc -eq 0 ] && grep -q COMPUTE_OK "$OUT/probe.out"; then
        return 0
    fi
    log "compute probe failed rc=$rc: $(tail -1 "$OUT/probe.out" 2>/dev/null)"
    return 1
}

have_result() {  # a bench is done when its .json holds a parseable FULL
    # capture — bench.py's incremental partial lines ("partial": true)
    # from a timed-out attempt must not mark the entry done, or the
    # resume loop would never re-capture it (the round-4 rule: a wedge
    # leaves a hole, not a stale number)
    python - "$OUT/$1.json" <<'EOF' >/dev/null 2>&1
import json, sys
with open(sys.argv[1]) as f:
    lines = [l for l in f.read().splitlines() if l.startswith("{")]
sys.exit(1 if json.loads(lines[-1]).get("partial") else 0)
EOF
}

run_bench() {
    local name="$1"; shift
    log "bench $name starting: $*"
    HOROVOD_BENCH_MEASURE_TIMEOUT=1100 HOROVOD_BENCH_MEASURE_ATTEMPTS=2 \
    HOROVOD_BENCH_PREFLIGHT_ATTEMPTS=2 HOROVOD_BENCH_PREFLIGHT_INITIAL=0 \
    HOROVOD_BENCH_FALLBACK=0 \
        timeout 3300 python bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    log "bench $name done rc=$?: $(tail -1 "$OUT/$name.json" 2>/dev/null)"
}

run_onchip() {
    log "onchip path bench starting"
    timeout 900 python benchmarks/onchip_path_bench.py \
        > "$OUT/onchip_tpu.json" 2> "$OUT/onchip_tpu.log"
    log "onchip path bench rc=$?: $(tail -1 "$OUT/onchip_tpu.json" 2>/dev/null)"
}

run_torch() {
    # Torch front-end on the device plane: model compute is torch-CPU (no
    # torch TPU backend in this image); the measured path is the per-step
    # hook->engine->XLA-plane round trip through the real chip.
    log "torch synthetic bench starting"
    HOROVOD_DATA_PLANE=xla timeout 1200 \
        python examples/pytorch_synthetic_benchmark.py --json \
        --num-iters 5 --num-batches-per-iter 2 \
        > "$OUT/torch_synthetic.json" 2> "$OUT/torch_synthetic.log"
    log "torch bench rc=$?: $(tail -1 "$OUT/torch_synthetic.json" 2>/dev/null)"
}

run_lm() {  # $1 = name, rest = lm_bench args
    local name="$1"; shift
    log "lm bench $name starting: $*"
    timeout 2400 python benchmarks/lm_bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    log "lm bench $name done rc=$?: $(tail -1 "$OUT/$name.json" 2>/dev/null)"
}

log "watcher started (pid $$, out=$OUT, idle=${IDLE_SLEEP}s)"
round=0
while true; do
    round=$((round + 1))
    missing=0
    for entry in "${ENTRIES[@]}"; do
        name="${entry%%|*}"; benchargs="${entry#*|}"
        wanted "$name" || continue
        have_result "$name" && continue
        missing=$((missing + 1))
        if ! compute_probe; then
            # break, not continue: probing once per MISSING ENTRY would
            # pay (probe timeout + idle sleep) up to 12x per round on a
            # wedged chip; one failed probe wedges the whole round, and
            # the outer loop re-probes after the idle sleep
            log "round $round: chip not computing; sleeping ${IDLE_SLEEP}s"
            sleep "$IDLE_SLEEP"
            break
        fi
        log "round $round: chip computes OK -> $name"
        if [ "$benchargs" = "ONCHIP" ]; then
            run_onchip
        elif [ "$benchargs" = "TORCH" ]; then
            run_torch
        elif [ "$benchargs" = "SCAN" ]; then
            # dispatch-overhead diagnostic: same bs32 point, one scanned
            # device call per iteration — scan==separate rules dispatch
            # out of the cap attribution; scan>separate convicts it
            HOROVOD_BENCH_SCAN_BATCHES=1 run_bench "$name"
        elif [ "${benchargs%% *}" = "LM" ]; then
            if [ "$name" = "lm_flash" ]; then
                # the flash kernel's on-TPU HLO + device profile ride the
                # first LM capture (same artifacts as the resnet50 entry)
                HOROVOD_BENCH_DUMP_HLO="$OUT/lm_flash_hlo.txt" \
                HOROVOD_BENCH_PROFILE="$OUT/lm_flash_profile" \
                    run_lm "$name" ${benchargs#LM }
            else
                # shellcheck disable=SC2086
                run_lm "$name" ${benchargs#LM }
            fi
        elif [ "$name" = "resnet50" ]; then
            HOROVOD_BENCH_DUMP_HLO="$OUT/resnet50_hlo.txt" \
            HOROVOD_BENCH_PROFILE="$OUT/resnet50_profile" \
                run_bench "$name"
            # summarize only when the bench actually landed its number —
            # a timed-out attempt can leave a partial trace on disk, and
            # attributing from it would put wrong evidence next to nothing
            if have_result resnet50 && [ -d "$OUT/resnet50_profile" ]; then
                # the captured XPlane -> bottleneck attribution, written
                # next to the numbers (the bs32 MFU-cap evidence)
                timeout 300 python tools/profile_summary.py \
                    "$OUT/resnet50_profile" \
                    --out "$OUT/resnet50_profile_summary.md" \
                    > "$OUT/resnet50_profile_summary.log" 2>&1
                log "profile summary rc=$?"
            fi
        else
            # shellcheck disable=SC2086
            run_bench "$name" $benchargs
        fi
    done
    if [ $missing -eq 0 ]; then
        log "ALL BENCHES CAPTURED after $round round(s)"
        break
    fi
    sleep 30
done
