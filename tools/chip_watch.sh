#!/bin/bash
# Watch the axon TPU tunnel; the moment it answers, capture the full
# benchmark sequence (resnet50 protocol row, resnet101 bs64 anchor row,
# vgg16, inception3) into bench_results_r3/.  The chip wedges for hours
# at a time (rounds 1-2), so capture must be automatic and immediate.
set -u
cd /root/repo
OUT=bench_results_r3
mkdir -p "$OUT"
log() { echo "[chip_watch $(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }

log "watcher started (pid $$)"
while true; do
    timeout 90 python -c "import jax; print(jax.devices())" \
        > "$OUT/probe.out" 2>&1
    rc=$?
    if [ $rc -eq 0 ] && grep -qi "axon\|tpu" "$OUT/probe.out"; then
        log "chip ANSWERED: $(tail -1 "$OUT/probe.out")"
        break
    fi
    log "probe rc=$rc (wedged); sleeping 240s"
    sleep 240
done

run_bench() {
    name="$1"; shift
    log "bench $name starting: $*"
    HOROVOD_BENCH_MEASURE_TIMEOUT=900 HOROVOD_BENCH_MEASURE_ATTEMPTS=2 \
        timeout 2400 python bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    rc=$?
    log "bench $name done rc=$rc: $(cat "$OUT/$name.json" 2>/dev/null | tail -1)"
}

HOROVOD_BENCH_DUMP_HLO="$OUT/resnet50_hlo.txt" \
    HOROVOD_BENCH_PROFILE="$OUT/resnet50_profile" run_bench resnet50
run_bench resnet101_bs64 --model resnet101 --batch-size 64
run_bench vgg16 --model vgg16
run_bench inception3 --model inception3
run_bench resnet50_bs128 --model resnet50 --batch-size 128

# Device-resident eager path on the real chip (VERDICT r2 item 3):
# fusion_bench needs a 2-process world (impossible on one chip), so the
# single-chip isolation of the same claim — on-chip pack/psum/unpack vs
# host-staged D2H/pack/H2D through the same XlaDataPlane — runs instead.
# Retry like run_bench: this runs LAST, hours after the probe, and the
# tunnel re-wedges after clean startups (round-1/2 postmortems) — one
# hung attempt must not cost the round's only real-chip residency row.
for attempt in 1 2; do
    log "onchip path bench attempt $attempt"
    timeout 900 python benchmarks/onchip_path_bench.py \
        > "$OUT/onchip_tpu.json" 2> "$OUT/onchip_tpu.log"
    rc=$?
    log "onchip path bench rc=$rc: $(tail -1 "$OUT/onchip_tpu.json" 2>/dev/null)"
    [ $rc -eq 0 ] && break
    sleep 30
done
log "ALL BENCHES DONE"
