#!/usr/bin/env bash
# Sweep the chaos fault grid (docs/chaos.md) across both controller
# implementations and both negotiation cores:
#
#   HOROVOD_NATIVE_CONTROLLER=0/1  — Python vs C++ controller service
#   HOROVOD_NATIVE_CORE=0/1        — Python vs C++ negotiation core
#
# Every cell must end "healed" or "escalated", never hang. The Python
# controller wire carries the request-dedup envelope, so single faults
# HEAL there; the native controller's binary wire has no dedup, so faults
# escalate by design (--allow-escalation). Extra args are forwarded to
# horovod_tpu.chaos.matrix (e.g. --spec "drop@rank1:every5" --steps 16).
#
# --data-plane runs the data-plane integrity grid instead
# (docs/integrity.md): nan/flipbits faults x sentry policy / consensus
# cells, swept over both negotiation cores (the sentry verdict RPC and
# the digest wire need the Python controller, so only
# HOROVOD_NATIVE_CORE varies there).
set -euo pipefail
cd "$(dirname "$0")/.."

# --serving sweeps the serving-plane grid (docs/serving.md) instead:
# drop/delay/close on the serving RPC wire (heal via the dedup envelope)
# and kill-rank-mid-batch (recover through the elastic driver), on both
# negotiation cores (the serving world is a real hvd world; the serving
# RPC rides its own connection either way).
if [ "${1:-}" = "--serving" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== serving plane: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --serving "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

# --checkpoint sweeps the checkpoint-plane grid (docs/checkpoint.md)
# instead: kill-before-commit and kill-between-chunks on the async
# commit pipeline must relaunch and restore the last SEALED commit
# bit-exactly, and a clean async run must never relaunch — on both
# negotiation cores (the commit stream rides the elastic service wire,
# which is core-independent, so the sweep certifies exactly that).
if [ "${1:-}" = "--checkpoint" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== checkpoint plane: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --checkpoint "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

# --recovery sweeps the recovery-plane grid (docs/recovery.md) instead:
# kill-one-rank and partition-past-the-window must WARM-relaunch
# (survivor PIDs unchanged, sealed restore bit-exact), the partition
# inside the reconnect window and the headstop succession drill must
# heal with zero relaunches, and a head kill must recover with the
# island under its planned standby successor — never a hang. Blackbox
# assertion rides along: a recovered cell rode a world abort, so it
# owes a classifiable incident dump exactly like an escalation. The
# recovery RPCs need the Python controller, so only
# HOROVOD_NATIVE_CORE varies.
if [ "${1:-}" = "--recovery" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== recovery plane: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --recovery --blackbox "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

# --blackbox runs the flight-recorder assertion mode (docs/blackbox.md):
# the escalation cell and the data-plane grid on both negotiation cores,
# where every ESCALATED cell must also leave a classifiable
# blackbox-*.json incident file — an escalation with no dump fails.
if [ "${1:-}" = "--blackbox" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== blackbox escalation cell: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --escalation --blackbox "$@"; then
      rc=1
    fi
    echo "=== blackbox data plane: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --data-plane --blackbox "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

# --hierarchy sweeps the negotiation-tree grid (docs/hierarchy.md)
# instead: member-link drop/delay/close under islands:2 must heal with
# the tree LIVE and bit-exact results; a sub-coordinator kill must
# escalate in-deadline with the island named in the abort (certified
# through the black-box verdict when the killed rank's exit races the
# survivors' reports). Tree RPCs need the Python controller, so only
# HOROVOD_NATIVE_CORE varies.
if [ "${1:-}" = "--hierarchy" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== negotiation tree: HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --hierarchy "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

if [ "${1:-}" = "--data-plane" ]; then
  shift
  rc=0
  for core in 0 1; do
    echo "=== data plane: HOROVOD_NATIVE_CONTROLLER=0 HOROVOD_NATIVE_CORE=$core ==="
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix --data-plane "$@"; then
      rc=1
    fi
  done
  exit $rc
fi

rc=0
for nc in 0 1; do
  for core in 0 1; do
    echo "=== HOROVOD_NATIVE_CONTROLLER=$nc HOROVOD_NATIVE_CORE=$core ==="
    extra=()
    if [ "$nc" = "1" ]; then
      extra+=(--allow-escalation)
    fi
    if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=$nc \
        HOROVOD_NATIVE_CORE=$core \
        python -m horovod_tpu.chaos.matrix "${extra[@]}" "$@"; then
      rc=1
    fi
  done
done

echo "=== escalation cell (refuse budget beyond retry) ==="
if ! JAX_PLATFORMS=cpu HOROVOD_NATIVE_CONTROLLER=0 \
    python -m horovod_tpu.chaos.matrix --escalation; then
  rc=1
fi

exit $rc
