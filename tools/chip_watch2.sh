#!/bin/bash
# Chip watcher v2.  v1 probed with `jax.devices()` — but the axon tunnel
# can answer that in seconds and still hang the first real computation
# for >15 min (observed 01:04-01:35 this round: probe OK in 4 s, two
# 900 s measurement attempts died before the first compile finished).
# So v2:
#   * probes with an actual jitted matmul (block_until_ready), not a
#     device listing;
#   * loops over the bench series indefinitely, re-running only the
#     entries that have not produced a JSON result yet, re-probing
#     between entries — a half-wedged tunnel costs a sleep, not the
#     whole series;
#   * enables the JAX persistent compilation cache so a timed-out
#     attempt's compile work is reused by the retry.
# Kill it with: pkill -f chip_watch2
set -u
cd /root/repo
OUT=bench_results_r3
mkdir -p "$OUT"
# bench.py defaults JAX_COMPILATION_CACHE_DIR to a repo-local dir shared
# by watcher/driver/human runs; leave the env unset so that single
# in-bench default stays the one source of truth (the probe's tiny
# compile is below JAX's persist threshold anyway).
log() { echo "[chip_watch2 $(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }

compute_probe() {
    timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = jax.jit(lambda a: (a @ a).sum())(x)
jax.block_until_ready(y)
print('COMPUTE_OK', jax.devices()[0].platform, flush=True)
" > "$OUT/probe.out" 2>&1
    local rc=$?
    if [ $rc -eq 0 ] && grep -q COMPUTE_OK "$OUT/probe.out"; then
        return 0
    fi
    log "compute probe failed rc=$rc: $(tail -1 "$OUT/probe.out" 2>/dev/null)"
    return 1
}

have_result() {  # a bench is done when its .json holds a parseable line
    python - "$OUT/$1.json" <<'EOF' >/dev/null 2>&1
import json, sys
with open(sys.argv[1]) as f:
    lines = [l for l in f.read().splitlines() if l.startswith("{")]
json.loads(lines[-1])
EOF
}

run_bench() {
    local name="$1"; shift
    log "bench $name starting: $*"
    HOROVOD_BENCH_MEASURE_TIMEOUT=1100 HOROVOD_BENCH_MEASURE_ATTEMPTS=2 \
    HOROVOD_BENCH_PREFLIGHT_ATTEMPTS=2 \
        timeout 3300 python bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    log "bench $name done rc=$?: $(tail -1 "$OUT/$name.json" 2>/dev/null)"
}

run_onchip() {
    log "onchip path bench starting"
    timeout 900 python benchmarks/onchip_path_bench.py \
        > "$OUT/onchip_tpu.json" 2> "$OUT/onchip_tpu.log"
    log "onchip path bench rc=$?: $(tail -1 "$OUT/onchip_tpu.json" 2>/dev/null)"
}

log "watcher v2 started (pid $$)"
round=0
while true; do
    round=$((round + 1))
    missing=0
    for entry in \
        "resnet50|" \
        "resnet101_bs64|--model resnet101 --batch-size 64" \
        "vgg16|--model vgg16" \
        "inception3|--model inception3" \
        "resnet50_bs128|--model resnet50 --batch-size 128" \
        "resnet50_bs256|--model resnet50 --batch-size 256" \
        "onchip_tpu|ONCHIP"; do
        name="${entry%%|*}"; benchargs="${entry#*|}"
        have_result "$name" && continue
        missing=$((missing + 1))
        if ! compute_probe; then
            # short sleep: chip-free windows can be minutes long (03:15
            # today answered for <60 s) — detection latency must be small
            log "round $round: chip not computing; sleeping 120s"
            sleep 120
            continue
        fi
        log "round $round: chip computes OK -> $name"
        if [ "$benchargs" = "ONCHIP" ]; then
            run_onchip
        elif [ "$name" = "resnet50" ]; then
            HOROVOD_BENCH_DUMP_HLO="$OUT/resnet50_hlo.txt" \
                HOROVOD_BENCH_PROFILE="$OUT/resnet50_profile" \
                run_bench "$name"
        else
            # shellcheck disable=SC2086
            run_bench "$name" $benchargs
        fi
    done
    if [ $missing -eq 0 ]; then
        log "ALL BENCHES CAPTURED after $round round(s)"
        break
    fi
    sleep 30
done
