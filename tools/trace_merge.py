#!/usr/bin/env python
"""Fold per-rank timeline files into one clock-corrected Chrome trace.

``HOROVOD_TIMELINE=<base>.json HOROVOD_TIMELINE_ALL_RANKS=1`` makes every
member rank record spans into ``<base>.rank<N>.json`` (docs/tracing.md).
This tool merges them into a single chrome://tracing / Perfetto document:

    python tools/trace_merge.py /tmp/trace.json --out /tmp/trace.merged.json
    python tools/trace_merge.py /tmp/trace.rank0.json /tmp/trace.rank1.json

* each rank becomes its own PROCESS lane (``pid`` = rank, named
  ``rank N``), with the per-tensor thread rows preserved inside it;
* every timestamp is corrected onto the coordinator's (rank 0's)
  timebase using the minimum-RTT ``CLOCK_SYNC`` metadata record the
  rank's ClockSync wrote into its own file (``obs/tracing.py``) — no
  side-channel manifest. A file with no sync record (native controller
  wire, sync disabled) merges uncorrected and the summary says so;
* span nesting is validated per (rank, tid): every E must close a B and
  timestamps must be monotone within the lane — a violation means the
  artifact is corrupt and the tool fails loudly rather than emitting a
  trace that silently lies.

The final stdout line is one JSON object (the repo's tool contract):
``{"ranks": N, "events": M, "corrected": K, "out": path}``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# Metadata record names; mirrors horovod_tpu.utils.timeline (kept as
# literals so the tool works from a checkout without the package
# importable, e.g. against artifacts copied off a pod).
TRACE_META = "horovod_trace_meta"
CLOCK_SYNC = "horovod_clock_sync"


def _load_records(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        content = fh.read()
    try:
        records = json.loads(content)
    except ValueError:
        # A live (unclosed) file is a truncated array — Chrome tolerates
        # it, so we do too: drop the trailing partial line and close it.
        body = content.rstrip()
        if body.endswith(","):
            body = body[:-1]
        elif "\n" in body:
            body = body.rsplit("\n", 1)[0].rstrip().rstrip(",")
        records = json.loads(body + "]")
    if not isinstance(records, list):
        raise ValueError(f"{path}: not a Chrome-tracing JSON array")
    # the Python writer terminates with a bare {} element
    return [r for r in records if isinstance(r, dict) and r]


def _rank_of(path: str, records: list):
    """Lane identity: the TRACE_META record, else the .rankN suffix."""
    for rec in records:
        if rec.get("name") == TRACE_META and rec.get("ph") == "M":
            return int(rec["args"]["rank"])
    import re

    m = re.search(r"\.rank(\d+)(?:\.json)?$", path)
    if m:
        return int(m.group(1))
    return None


def _offset_of(records: list):
    """Best clock correction for this file: the CLOCK_SYNC record with
    the smallest filter RTT (the least queueing-corrupted estimate),
    or None when the file never synced."""
    best = None
    for rec in records:
        if rec.get("name") != CLOCK_SYNC or rec.get("ph") != "M":
            continue
        args = rec.get("args", {})
        rtt = float(args.get("rtt_us", 0.0))
        if best is None or rtt < best[0]:
            best = (rtt, float(args.get("offset_us", 0.0)))
    return None if best is None else best[1]


def _validate_nesting(records: list, rank) -> int:
    """Monotone span nesting per (pid, tid); returns the span count.
    Unclosed B's at EOF are fine (the job may have died mid-span); an E
    without a B, or time running backwards inside a lane, is corruption."""
    stacks: dict = {}
    spans = 0
    last_ts: dict = {}
    for rec in records:
        ph = rec.get("ph")
        if ph not in ("B", "E"):
            continue
        key = (rec.get("pid", 0), rec.get("tid", 0))
        ts = rec.get("ts")
        if ts is None:
            raise ValueError(f"rank {rank}: span record without ts: {rec}")
        if key in last_ts and ts < last_ts[key]:
            raise ValueError(
                f"rank {rank}: timestamps run backwards in lane {key} "
                f"({ts} after {last_ts[key]})")
        last_ts[key] = ts
        stack = stacks.setdefault(key, [])
        if ph == "B":
            stack.append(ts)
        else:
            if not stack:
                raise ValueError(
                    f"rank {rank}: E record without a matching B in lane "
                    f"{key} at ts {ts}")
            begin = stack.pop()
            if ts < begin:
                raise ValueError(
                    f"rank {rank}: span ends before it begins in lane "
                    f"{key} ({begin} -> {ts})")
            spans += 1
    return spans


def merge(paths, out_path: str) -> dict:
    """Merge per-rank timeline files; returns the summary dict."""
    merged = []
    ranks = []
    unsynced = []
    corrected = 0
    for path in sorted(paths):
        records = _load_records(path)
        rank = _rank_of(path, records)
        if rank is None:
            raise ValueError(
                f"{path}: no {TRACE_META} record and no .rankN suffix — "
                f"cannot assign a lane")
        _validate_nesting(records, rank)
        offset = _offset_of(records)
        ranks.append(rank)
        if offset is None:
            unsynced.append(rank)
        lane_note = (f"rank {rank}" if offset is None else
                     f"rank {rank} (clock {offset:+.0f}us)")
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": lane_note}})
        if offset is None:
            print(f"[trace_merge] {path}: no {CLOCK_SYNC} record; lane "
                  f"rank {rank} keeps its LOCAL timebase (native "
                  f"controller wire, or clock sync disabled)",
                  file=sys.stderr)
        for rec in records:
            rec = dict(rec)
            rec["pid"] = rank
            if offset is not None and "ts" in rec:
                rec["ts"] = rec["ts"] + offset
                corrected += 1
            merged.append(rec)
    if len(set(ranks)) != len(ranks):
        raise ValueError(f"duplicate rank lanes in inputs: {sorted(ranks)}")
    # Global ordering by corrected time reads better in Perfetto and is a
    # cheap smoke test that the correction produced sane numbers.
    merged.sort(key=lambda r: (r.get("ts", 0.0), r.get("pid", 0)))
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(merged, fh)
    # unsynced_ranks makes the correction claim FALSIFIABLE per lane: a
    # consumer asserting "clocks aligned" must check this list is empty,
    # not just that corrected > 0 (rank 0's offset-0 records alone would
    # satisfy that while every other lane drifted uncorrected).
    return {"ranks": len(ranks), "events": len(merged),
            "corrected": corrected, "unsynced_ranks": sorted(unsynced),
            "out": out_path}


def expand_inputs(args_paths) -> list:
    """CLI convenience: a single base path (the HOROVOD_TIMELINE value)
    expands to its rank-suffixed family; explicit file lists pass
    through. The base itself usually does not exist under ALL_RANKS —
    only its ``.rankN`` family does."""
    if len(args_paths) != 1:
        return list(args_paths)
    base = args_paths[0]
    stem = base[:-len(".json")] if base.endswith(".json") else base
    family = sorted(glob.glob(glob.escape(stem) + ".rank*[0-9].json") +
                    glob.glob(glob.escape(stem) + ".rank*[0-9]"))
    if family:
        return family
    return [base] if os.path.exists(base) else []


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="+",
                        help="per-rank timeline files, or the one "
                             "HOROVOD_TIMELINE base path (expands to its "
                             ".rankN family)")
    parser.add_argument("--out", default="",
                        help="merged trace path (default: <first "
                             "input>.merged.json)")
    args = parser.parse_args(argv)
    paths = expand_inputs(args.paths)
    if not paths:
        print(f"no input trace files found for {args.paths}",
              file=sys.stderr)
        return 1
    out = args.out or (paths[0].rsplit(".json", 1)[0] + ".merged.json")
    try:
        summary = merge(paths, out)
    except (OSError, ValueError) as exc:
        print(f"trace merge failed: {exc}", file=sys.stderr)
        return 1
    print(f"[trace_merge] {summary['ranks']} rank lane(s), "
          f"{summary['events']} events ({summary['corrected']} "
          f"clock-corrected) -> {out}", file=sys.stderr)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
