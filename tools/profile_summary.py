#!/usr/bin/env python
"""Summarize a captured device profile into a bottleneck attribution.

The round-3 verdict's open question is WHY ResNet-50 bs32 caps at ~11% MFU
on a v5e chip — the BN/bandwidth-bound hypothesis needs the device profile
(``HOROVOD_BENCH_PROFILE=<dir>`` in bench.py) to confirm or refute it.
This tool turns that captured XPlane into the answer without TensorBoard:

    python tools/profile_summary.py bench_results_r4/resnet50_profile \
        [--top 25] [--out bench_results_r4/resnet50_profile_summary.md]

It extracts xprof's ``hlo_stats`` table (self-time, bound_by, HBM
bandwidth, FLOP rate per HLO op — populated for TPU traces) with
``framework_op_stats`` as the fallback (host/CPU traces), aggregates
self-time by op category, and prints the top ops. The final line is one
JSON object so captures can be post-processed mechanically.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _tables(obj):
    """Yield every gviz-style {cols, rows} table in a tool's JSON output
    (some tools return one table, some a list of tables)."""
    if isinstance(obj, dict) and "cols" in obj and "rows" in obj:
        yield obj
    elif isinstance(obj, list):
        for item in obj:
            yield from _tables(item)


def _rows_as_dicts(table):
    ids = [c["id"] for c in table["cols"]]
    for row in table.get("rows", []):
        cells = [c.get("v") if isinstance(c, dict) else None
                 for c in row["c"]]
        yield dict(zip(ids, cells))


def _pick_time_key(row) -> str | None:
    for key in ("total_self_time", "total_self_time_in_us",
                "self_time_us", "total_self_time_us"):
        if key in row:
            return key
    return None


def summarize(profile_dir: str, top: int = 25):
    """Returns (lines, summary_dict). Raises with a clear message when the
    dir holds no parseable profile."""
    paths = sorted(glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(
            f"no *.xplane.pb under {profile_dir!r} — was the profile "
            f"captured (HOROVOD_BENCH_PROFILE)?")
    # jax.profiler writes each capture into its own timestamped
    # plugins/profile/<ts>/ session dir and never clears old ones; a retried
    # bench therefore leaves several sessions under one HOROVOD_BENCH_PROFILE
    # dir. Summarize only the NEWEST session — merging them would
    # double-count every op in the attribution artifact.
    by_session: dict[str, list[str]] = {}
    for p in paths:
        by_session.setdefault(os.path.dirname(p), []).append(p)
    if len(by_session) > 1:
        newest = max(by_session, key=lambda d: max(
            os.path.getmtime(p) for p in by_session[d]))
        skipped = sorted(set(by_session) - {newest})
        print(f"[profile_summary] {len(by_session)} capture sessions under "
              f"{profile_dir!r}; using newest {newest!r}, ignoring "
              f"{skipped}", file=sys.stderr)
        paths = sorted(by_session[newest])
    from xprof.convert import raw_to_tool_data as r2t

    rows = []
    tool_used = None
    for tool in ("hlo_stats", "framework_op_stats"):
        try:
            data, _ = r2t.xspace_to_tool_data(list(paths), tool, {})
        except Exception as exc:  # noqa: BLE001 - try the next tool
            print(f"[profile_summary] {tool} failed: {exc!r}",
                  file=sys.stderr)
            continue
        if isinstance(data, bytes):
            data = data.decode()
        try:
            obj = json.loads(data)
        except ValueError:
            continue
        for table in _tables(obj):
            cand = [row for row in _rows_as_dicts(table)
                    if _pick_time_key(row)]
            # an IDLE-only / all-zero table is no attribution at all —
            # keep looking (and ultimately fall back to raw trace events)
            if cand and any(float(row.get(_pick_time_key(row)) or 0) > 0
                            for row in cand):
                rows = cand
                tool_used = tool
                break
        if rows:
            break
    if not rows:
        # Final fallback: aggregate raw trace events (CPU traces populate
        # neither hlo_stats nor device op stats; TPU captures never reach
        # this branch). Wall duration by event name stands in for self
        # time — good enough to rank the hot ops.
        try:
            data, _ = r2t.xspace_to_tool_data(
                list(paths), "trace_viewer@", {"trace_viewer_options": {}})
            if isinstance(data, bytes):
                data = data.decode()
            events = json.loads(data).get("traceEvents", [])
        except Exception as exc:  # noqa: BLE001
            raise RuntimeError(
                "profile parsed but no op table carried self-time rows "
                f"(and trace_viewer fallback failed: {exc!r})") from exc
        agg: dict[str, dict] = {}
        for ev in events:
            if ev.get("ph") != "X" or not ev.get("dur"):
                continue
            name = str(ev.get("name", "?"))
            slot = agg.setdefault(
                name, {"operation": name, "type": "trace",
                       "total_self_time": 0.0, "occurrences": 0})
            slot["total_self_time"] += float(ev["dur"])
            slot["occurrences"] += 1
        rows = list(agg.values())
        tool_used = "trace_viewer"
    if not rows:
        raise RuntimeError(
            "profile parsed but no op table carried self-time rows "
            "(empty trace? idle-only capture?)")

    tkey = _pick_time_key(rows[0])
    total = sum(float(row.get(tkey) or 0.0) for row in rows)
    by_cat: dict[str, float] = {}
    for row in rows:
        cat = str(row.get("category") or row.get("type") or "?")
        by_cat[cat] = by_cat.get(cat, 0.0) + float(row.get(tkey) or 0.0)

    lines = [f"# profile summary: {profile_dir}",
             f"tool: {tool_used}; ops: {len(rows)}; "
             f"total self time: {total:.0f} us", "",
             "## self-time by category"]
    cats = sorted(by_cat.items(), key=lambda kv: -kv[1])
    for cat, us in cats:
        lines.append(f"  {cat:<32} {us:>12.0f} us  "
                     f"{100.0 * us / total if total else 0.0:5.1f}%")
    lines += ["", f"## top {top} ops by self time"]
    name_key = "hlo_op_name" if "hlo_op_name" in rows[0] else "operation"
    for row in sorted(rows, key=lambda r: -float(r.get(tkey) or 0.0))[:top]:
        extras = []
        for k, fmt in (("bound_by", "{}"), ("hbm_bw", "hbm={:.1f}GB/s"),
                       ("measured_memory_bw", "bw={:.1f}GB/s"),
                       ("model_flop_rate", "flops={:.2f}G/s"),
                       ("occurrences", "x{}")):
            v = row.get(k)
            if v not in (None, "", 0, "0"):
                try:
                    extras.append(fmt.format(float(v) if "{:" in fmt else v))
                except (ValueError, TypeError):
                    extras.append(f"{k}={v}")
        lines.append(
            f"  {float(row.get(tkey) or 0):>10.0f} us "
            f"{100.0 * float(row.get(tkey) or 0) / total if total else 0:5.1f}%"
            f"  {str(row.get('category') or row.get('type') or ''):<16}"
            f" {str(row.get(name_key) or '')[:60]:<60} {' '.join(extras)}")

    summary = {
        "profile_dir": profile_dir,
        "tool": tool_used,
        "total_self_time_us": round(total, 1),
        "by_category_us": {c: round(u, 1) for c, u in cats},
        "top_op": (sorted(rows, key=lambda r: -float(r.get(tkey) or 0.0))[0]
                   .get(name_key) if rows else None),
    }
    return lines, summary


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("profile_dir")
    parser.add_argument("--top", type=int, default=25)
    parser.add_argument("--out", help="also write the report to this file")
    args = parser.parse_args()
    lines, summary = summarize(args.profile_dir, args.top)
    report = "\n".join(lines)
    print(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report + "\n")
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
