#!/usr/bin/env python
"""Pretty-print a saved metrics snapshot (docs/metrics.md).

    curl -s http://127.0.0.1:$HOROVOD_METRICS_PORT/metrics.json > snap.json
    python tools/metrics_summary.py snap.json
    python tools/metrics_summary.py snap.json --rank 1
    python tools/metrics_summary.py snap.json --family horovod_wire

Reads either shape the observability plane emits: the ``/metrics.json``
document (``{"world": families, "ranks": {rank: families}}``) or a bare
``metrics_snapshot()`` families dict, and renders one aligned table per
section — counters and gauges as values, histograms as count / mean /
approximate p50/p99 read off the cumulative buckets. The world section
prints first; ``--rank N`` adds that rank's unmerged section, ``--all``
adds every rank. ``--family PREFIX`` filters family names.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional


def _fmt_num(v) -> str:
    if isinstance(v, float) and v != int(v):
        return f"{v:.6g}"
    n = int(v)
    return f"{n:_}" if abs(n) >= 10000 else str(n)


def _quantile(bounds, buckets, q: float) -> Optional[float]:
    """Approximate quantile from per-bucket counts: the upper edge of the
    bucket where the cumulative count crosses q (+Inf reports the last
    finite edge with a ``>`` marker upstream)."""
    total = sum(buckets)
    if total == 0:
        return None
    target = q * total
    cum = 0
    for bound, count in zip(bounds, buckets):
        cum += count
        if cum >= target:
            return float(bound)
    return float("inf")


def _render_family(name: str, fam: dict, out) -> None:
    for sample in fam["samples"]:
        labels = sample.get("labels") or {}
        label_s = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(labels.items())) + "}"
                   if labels else "")
        if fam["type"] == "histogram":
            count = sample["count"]
            mean = sample["sum"] / count if count else 0.0
            p50 = _quantile(sample["bounds"], sample["buckets"], 0.50)
            p99 = _quantile(sample["bounds"], sample["buckets"], 0.99)

            def edge(p):
                if p is None:
                    return "-"
                if p == float("inf"):
                    return f">{sample['bounds'][-1]:g}"
                return f"<={p:g}"

            detail = (f"count={_fmt_num(count)} mean={mean:.6g} "
                      f"p50{edge(p50)} p99{edge(p99)}")
        else:
            detail = _fmt_num(sample["value"])
        out.write(f"  {name + label_s:<58} {fam['type']:<9} {detail}\n")


# Tuning-plane families (docs/autotune.md) get their own section: the
# current knob gauges, retune/revert counters, and eviction advisories
# are the "is the closed loop doing anything?" glance, and burying them
# in the alphabetical world listing hid exactly that.
TUNING_PREFIXES = ("horovod_autotune_", "horovod_straggler_evict")

# Integrity-plane families (docs/integrity.md) likewise: sentry trips and
# consensus mismatches are the "is the data plane numerically healthy and
# bit-identical?" glance — zero trips is only meaningful next to a
# non-zero check count, so the two must read together.
INTEGRITY_PREFIXES = ("horovod_sentry_", "horovod_consensus_")

# Serving-plane families (docs/serving.md): request codes, queue depth,
# batch fill, and the latency histogram (p50/p99 read off the cumulative
# buckets by the shared histogram renderer) are the "is the gateway
# serving inside its SLO?" glance.
SERVING_PREFIXES = ("horovod_serving_",)

# Flight-recorder families (docs/blackbox.md): ring traffic, overwrites,
# and incident dumps written/failed — the "would an abort leave a
# postmortem?" glance, plus the timeline's own truncation counter (a
# dropped trace event is the same black-box-coverage question).
FLIGHTREC_PREFIXES = ("horovod_flightrec_", "horovod_timeline_dropped_")

# Numerics-observatory families (docs/tensorwatch.md): sampled batches,
# the worst-K per-tensor gauges, the decode-SNR-by-codec gauges, and the
# top-k sparse-readiness curve — the "is the lossy wire numerically
# safe, and is the data skewed?" glance. Full table:
# tools/tensorwatch_report.py or GET /v1/tensors.
NUMERICS_PREFIXES = ("horovod_tensorwatch_", "horovod_tensor_",
                     "horovod_codec_snr_db")

# Sparse-wire families (docs/compression.md §sparse): selected/dropped
# entry counters, the per-rank residual-norm gauge, and wire bytes by
# path — the "how much mass is the top-k wire shipping vs banking?"
# glance. A growing residual norm beside a healthy selected/dropped
# ratio is the error-feedback loop working; a runaway one is the
# collapse signal the evidence gate reverts on.
SPARSE_PREFIXES = ("horovod_sparse_",)

# Recovery-plane families (docs/recovery.md): warm-vs-cold relaunch
# counters, survivors reused, the MTTR histogram, and standby head
# successions — the "did the last fault cost a full cold restart?"
# glance. Warm pacing cold means survivors keep being reused; an MTTR
# p99 near the cold relaunches' is the warm path silently degrading.
RECOVERY_PREFIXES = ("horovod_recovery_",)

# Hierarchy-plane families (docs/hierarchy.md): the resolved island
# gauge, merged-vs-raw island cycle counters, the root's absorbed
# message count, and head pass-throughs — the "is the negotiation tree
# live, and is it actually merging?" glance. A zero islands gauge under
# HOROVOD_HIERARCHY is the degraded-to-flat tell; a raw counter pacing
# the merged one means members' cycles keep deviating and the root is
# absorbing near-flat load.
HIER_PREFIXES = ("horovod_hier_",)

# Sharding-plane families (docs/sharding.md): per-rank shard
# geometry/residency gauges, pad + repartition counters, and the
# contribution-ratio gauge — the "is ZeRO-1 actually saving memory, and
# is any rank's partition doing outsized work?" glance. Slot bytes near
# the replicated footprint means sharding silently degraded; a reshard
# counter tick is an elastic world-size change repartitioning state.
SHARD_PREFIXES = ("horovod_shard_",)

# Checkpoint-plane families (docs/checkpoint.md): commit/seal counters,
# the sealed-commit watermark, digest mismatches, stream bytes/seconds,
# the commit-stall histogram, and journal depth — the "is training
# durable, and what does durability cost the step loop?" glance. A
# sealed watermark that trails commits is the in-flight window a kill
# would replay; any digest mismatch is a shard-divergence alarm.
CKPT_PREFIXES = ("horovod_ckpt_",)


def _render_section(title: str, families: Dict[str, dict], prefix: str,
                    out, skip: tuple = ()) -> None:
    names = [n for n in sorted(families) if n.startswith(prefix)
             and not n.startswith(skip)]
    out.write(f"{title} ({len(names)} families)\n")
    if not names:
        out.write("  (none match)\n")
    for name in names:
        _render_family(name, families[name], out)
    out.write("\n")


def _render_tuning_section(families: Dict[str, dict], prefix: str,
                           out) -> None:
    tuning = {n: f for n, f in families.items()
              if n.startswith(TUNING_PREFIXES) and n.startswith(prefix)}
    if not tuning:
        return  # no tuning plane in this snapshot: no empty section
    _render_section("tuning plane", tuning, prefix, out)


def _render_integrity_section(families: Dict[str, dict], prefix: str,
                              out) -> None:
    integrity = {n: f for n, f in families.items()
                 if n.startswith(INTEGRITY_PREFIXES)
                 and n.startswith(prefix)}
    if not integrity:
        return  # no integrity plane in this snapshot: no empty section
    _render_section("integrity plane", integrity, prefix, out)


def _render_serving_section(families: Dict[str, dict], prefix: str,
                            out) -> None:
    serving = {n: f for n, f in families.items()
               if n.startswith(SERVING_PREFIXES) and n.startswith(prefix)}
    if not serving:
        return  # no serving plane in this snapshot: no empty section
    _render_section("serving plane", serving, prefix, out)


def _render_flightrec_section(families: Dict[str, dict], prefix: str,
                              out) -> None:
    flightrec = {n: f for n, f in families.items()
                 if n.startswith(FLIGHTREC_PREFIXES)
                 and n.startswith(prefix)}
    if not flightrec:
        return  # recorder disabled in this snapshot: no empty section
    _render_section("flight recorder", flightrec, prefix, out)


def _render_numerics_section(families: Dict[str, dict], prefix: str,
                             out) -> None:
    numerics = {n: f for n, f in families.items()
                if n.startswith(NUMERICS_PREFIXES)
                and n.startswith(prefix)}
    if not numerics:
        return  # observatory off in this snapshot: no empty section
    _render_section("numerics plane", numerics, prefix, out)


def _render_sparse_section(families: Dict[str, dict], prefix: str,
                           out) -> None:
    sparse = {n: f for n, f in families.items()
              if n.startswith(SPARSE_PREFIXES) and n.startswith(prefix)}
    if not sparse:
        return  # no sparse wire in this snapshot: no empty section
    _render_section("sparse wire", sparse, prefix, out)


def _render_shard_section(families: Dict[str, dict], prefix: str,
                          out) -> None:
    shard = {n: f for n, f in families.items()
             if n.startswith(SHARD_PREFIXES) and n.startswith(prefix)}
    if not shard:
        return  # no sharding plane in this snapshot: no empty section
    _render_section("sharding plane", shard, prefix, out)


def _render_ckpt_section(families: Dict[str, dict], prefix: str,
                         out) -> None:
    ckpt = {n: f for n, f in families.items()
            if n.startswith(CKPT_PREFIXES) and n.startswith(prefix)}
    if not ckpt:
        return  # no checkpoint plane in this snapshot: no empty section
    _render_section("checkpoint plane", ckpt, prefix, out)


def _render_hier_section(families: Dict[str, dict], prefix: str,
                         out) -> None:
    hier = {n: f for n, f in families.items()
            if n.startswith(HIER_PREFIXES) and n.startswith(prefix)}
    if not hier:
        return
    _render_section("hierarchy plane", hier, prefix, out)


def _render_recovery_section(families: Dict[str, dict], prefix: str,
                             out) -> None:
    recovery = {n: f for n, f in families.items()
                if n.startswith(RECOVERY_PREFIXES)
                and n.startswith(prefix)}
    if not recovery:
        return  # no recovery plane in this snapshot: no empty section
    _render_section("recovery plane", recovery, prefix, out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pretty-print a saved /metrics.json or "
                    "metrics_snapshot() document")
    ap.add_argument("path", help="snapshot file, or - for stdin")
    ap.add_argument("--rank", type=int, default=None,
                    help="also print this rank's unmerged section")
    ap.add_argument("--all", action="store_true",
                    help="print every rank's unmerged section")
    ap.add_argument("--family", default="",
                    help="only families whose name starts with this")
    args = ap.parse_args(argv)

    fh = sys.stdin if args.path == "-" else open(args.path)
    with fh:
        doc = json.load(fh)

    if "world" in doc and "ranks" in doc:
        world, ranks = doc["world"], doc["ranks"]
    else:
        # a bare metrics_snapshot() families dict: one local section
        world, ranks = doc, {}

    _render_tuning_section(world, args.family, sys.stdout)
    _render_integrity_section(world, args.family, sys.stdout)
    _render_serving_section(world, args.family, sys.stdout)
    _render_flightrec_section(world, args.family, sys.stdout)
    _render_numerics_section(world, args.family, sys.stdout)
    _render_sparse_section(world, args.family, sys.stdout)
    _render_shard_section(world, args.family, sys.stdout)
    _render_ckpt_section(world, args.family, sys.stdout)
    _render_hier_section(world, args.family, sys.stdout)
    _render_recovery_section(world, args.family, sys.stdout)
    _render_section("world", world, args.family, sys.stdout,
                    skip=TUNING_PREFIXES + INTEGRITY_PREFIXES
                    + SERVING_PREFIXES + FLIGHTREC_PREFIXES
                    + NUMERICS_PREFIXES + SPARSE_PREFIXES
                    + SHARD_PREFIXES + CKPT_PREFIXES + HIER_PREFIXES
                    + RECOVERY_PREFIXES)
    # JSON round-trips rank keys as strings; accept either
    by_rank = {int(k): v for k, v in ranks.items()}
    wanted = sorted(by_rank) if args.all else (
        [args.rank] if args.rank is not None else [])
    for rank in wanted:
        if rank not in by_rank:
            print(f"rank {rank}: not in snapshot "
                  f"(have {sorted(by_rank)})", file=sys.stderr)
            return 1
        _render_section(f"rank {rank}", by_rank[rank], args.family,
                        sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
