#!/usr/bin/env python
"""hvdlint — the repo's contract-analysis suite (docs/analysis.md).

Statically enforces the conventions review memory used to carry: knob
registry (HVL1xx), lock order (HVL2xx), collective order (HVL3xx), wire
compatibility (HVL4xx), metrics/docs agreement (HVL5xx), error taxonomy
(HVL6xx), pytest markers (HVL701), baseline hygiene (HVL9xx).

    python tools/hvdlint.py              # human report, exit != 0 on findings
    python tools/hvdlint.py --json       # findings to stderr, final stdout
                                         # line is one JSON summary object
    python tools/hvdlint.py --only locks,knobs
    python tools/hvdlint.py --list-codes

Pure stdlib, no jax: runs anywhere ``runner.network`` does. When the
``horovod_tpu`` package cannot be imported (jax-less workstation), the
``horovod_tpu/analysis/`` package is loaded straight from its files —
it is stdlib-only for exactly this reason (the obs/tracing precedent).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Load horovod_tpu/analysis straight from its files — never through
    the horovod_tpu package, whose __init__ imports jax and applies
    platform steering; a linter must not pay (or depend on) any of
    that. The package is stdlib-only by contract, so the by-path load
    works everywhere."""
    import importlib.util

    pkg_dir = os.path.join(_REPO, "horovod_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "hvdlint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hvdlint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


analysis = _load_analysis()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--json", action="store_true",
                        help="findings to stderr; final stdout line is "
                             "one JSON summary (the repo tool contract)")
    parser.add_argument("--only", default="",
                        help="comma-separated checker subset (e.g. "
                             "'locks,knobs')")
    parser.add_argument("--baseline", default="",
                        help="override the baseline file path (default: "
                             f"{analysis.BASELINE_REL})")
    parser.add_argument("--root", default=_REPO,
                        help="repo root to analyze (default: this "
                             "checkout)")
    parser.add_argument("--list-codes", action="store_true",
                        help="print the finding-code catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_codes:
        for code, desc in sorted(analysis.CODES.items()):
            print(f"{code}  {desc}")
        return 0

    only = [c.strip() for c in args.only.split(",") if c.strip()] or None
    baseline = args.baseline or None
    try:
        result = analysis.run_all(args.root, baseline_path=baseline,
                                  only=only)
    except ValueError as exc:  # typo'd --only must fail loudly, not pass
        print(f"hvdlint: {exc}", file=sys.stderr)
        return 2

    out = sys.stderr if args.json else sys.stdout
    for f in result["findings"]:
        print(f.render(), file=out)
    n = len(result["findings"])
    human = (f"[hvdlint] {n} finding(s), {result['waived']} waived, "
             f"checkers: {', '.join(result['checkers'])}")
    if args.json:
        print(human, file=sys.stderr)
        print(analysis.summary_json(result))
    else:
        print(human)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
