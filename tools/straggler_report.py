#!/usr/bin/env python
"""Who is slow, in which phase: the coordinator's straggler attribution.

Reads a metrics document — a saved ``/metrics.json`` file, a live
exposition URL, or a bare ``metrics_snapshot(world=True)`` dict — and
folds the coordinator's arrival-order families (docs/tracing.md) into
per-rank blame fractions plus each rank's negotiation-wait vs execute
breakdown:

    curl -s http://127.0.0.1:$HOROVOD_METRICS_PORT/metrics.json > snap.json
    python tools/straggler_report.py snap.json
    python tools/straggler_report.py http://127.0.0.1:9090/metrics.json

In-job, the same report is ``hvd.straggler_report()``. The final stdout
line is the report as one JSON object (the repo's tool contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# runnable straight from a checkout: `python tools/straggler_report.py`
# puts tools/ (not the repo root) on sys.path
sys.path.insert(0, _REPO)


def _load_fold():
    """The report fold lives in horovod_tpu.obs.tracing — but this tool
    must analyze snapshots copied OFF a pod, on machines where importing
    the package would pull in jax. obs/tracing.py keeps its module level
    stdlib-only for exactly this: when the package import fails, load the
    file directly (the fold is pure dict math)."""
    try:
        from horovod_tpu.obs.tracing import (
            DEFAULT_MIN_SPREAD_S,
            build_straggler_report,
        )

        return DEFAULT_MIN_SPREAD_S, build_straggler_report
    except ImportError:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_straggler_fold",
            os.path.join(_REPO, "horovod_tpu", "obs", "tracing.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.DEFAULT_MIN_SPREAD_S, mod.build_straggler_report


DEFAULT_MIN_SPREAD_S, build_straggler_report = _load_fold()


def _load(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.loads(resp.read().decode())
    with open(source, encoding="utf-8") as fh:
        return json.load(fh)


def _ranks_of(doc: dict) -> dict:
    """Accept both emitted shapes: the ``/metrics.json`` document
    ({"world": ..., "ranks": {rank: families}}) or a bare families dict
    (``metrics_snapshot()`` — single-rank view, degraded unless it is
    the coordinator's)."""
    if "ranks" in doc and isinstance(doc["ranks"], dict):
        return {int(r): fams for r, fams in doc["ranks"].items()}
    return {0: doc}


def render(report: dict, out=sys.stdout) -> None:
    w = out.write
    cycles = report["cycles_attributed"]
    w(f"# straggler report: {cycles} attributed cycle(s)\n")
    if report["degraded"]:
        w("DEGRADED: no attribution families in this document — the "
          "coordinator's snapshot never reached it (native controller "
          "wire, publisher not opted in, or a single-rank snapshot from "
          "a non-coordinator rank).\n")
    spread = report.get("spread")
    if spread:
        def q(v):  # None = beyond the histogram's last finite bound
            return "beyond range" if v is None else f"<= {v * 1e3:.2f} ms"

        w(f"arrival spread: mean {spread['mean_s'] * 1e3:.2f} ms, "
          f"p50 {q(spread['p50_s'])}, p99 {q(spread['p99_s'])} over "
          f"{spread['count']} cycle(s)\n")
    # Hierarchy plane (docs/hierarchy.md): name the slow ISLAND before
    # the slow rank — at the root the spread is measured between island
    # heads, so a DCN-side cause shows up here even when no single rank
    # clears the per-rank dominance gate.
    dom_island = report.get("dominant_island")
    islands = report.get("islands") or {}
    if dom_island is not None:
        w(f"dominant island: {dom_island}\n")
    elif len(islands) > 1:
        w("dominant island: none (no island owns >50% of blame seconds "
          "with spreads above the significance floor)\n")
    dom = report["dominant_rank"]
    if dom is not None:
        w(f"dominant rank: {dom}\n")
    else:
        w("dominant rank: none (no rank owns >50% of blame seconds with "
          "spreads above the significance floor)\n")
    if len(islands) > 1:
        w("\n## island blame (negotiation tree)\n")
        w(f"{'island':>6} {'cycles':>8} {'blame s':>10} {'blame%':>8}\n")
        for isl, b in sorted(islands.items()):
            w(f"{isl:>6} {b['last_arriver_cycles']:>8} "
              f"{b['blame_seconds']:>10.4f} "
              f"{100 * b['blame_share']:>7.1f}%\n")
    if report["blame"]:
        w("\n## last-arriver blame\n")
        w(f"{'rank':>6} {'cycles':>8} {'cycle%':>8} "
          f"{'blame s':>10} {'blame%':>8}\n")
        for rank, b in sorted(report["blame"].items()):
            w(f"{rank:>6} {b['last_arriver_cycles']:>8} "
              f"{100 * b['cycle_share']:>7.1f}% "
              f"{b['blame_seconds']:>10.4f} "
              f"{100 * b['blame_share']:>7.1f}%\n")
    if report["per_rank"]:
        w("\n## phase breakdown (negotiation wait vs execute)\n")
        w(f"{'rank':>6} {'cycles':>8} {'neg wait s':>12} "
          f"{'execute s':>12}\n")
        for rank, p in sorted(report["per_rank"].items()):
            w(f"{rank:>6} {p['negotiation_cycles']:>8} "
              f"{p['negotiation_wait_s']:>12.4f} "
              f"{p['execute_s']:>12.4f}\n")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("source",
                        help="/metrics.json file path or live URL")
    parser.add_argument("--min-spread-ms", type=float,
                        default=DEFAULT_MIN_SPREAD_S * 1e3,
                        help="significance floor for the dominant-rank "
                             "verdict (mean attributed spread below this "
                             "is scheduler jitter, not a straggler)")
    args = parser.parse_args(argv)
    try:
        doc = _load(args.source)
    except (OSError, ValueError) as exc:
        print(f"cannot read metrics document {args.source!r}: {exc}",
              file=sys.stderr)
        return 1
    report = build_straggler_report(
        _ranks_of(doc), min_spread_s=args.min_spread_ms / 1e3)
    render(report)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
