#!/usr/bin/env python
"""Worst-SNR / highest-spread tensor table: the numerics observatory's
report (docs/tensorwatch.md).

Reads a metrics document — a saved ``/metrics.json`` file, a live
exposition URL, or a bare ``metrics_snapshot(world=True)`` dict — and
folds the ``horovod_tensor_*`` / ``horovod_codec_snr_db`` families into
the per-tensor numerics table: post-reduce norm², the cross-rank
pre-reduce norm spread (the data-skew detector), the per-tensor decode
SNR, plus the batch-level codec SNR and the top-k mass-coverage
(sparse-readiness) curve:

    curl -s http://127.0.0.1:$HOROVOD_METRICS_PORT/metrics.json > snap.json
    python tools/tensorwatch_report.py snap.json
    python tools/tensorwatch_report.py http://127.0.0.1:9090/metrics.json

The registry only carries the worst-K tensors by the labeling contract;
the FULL in-job table is ``hvd.tensor_report()`` / ``GET /v1/tensors``.
The final stdout line is the report as one JSON object (the repo's tool
contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# runnable straight from a checkout: `python tools/tensorwatch_report.py`
# puts tools/ (not the repo root) on sys.path
sys.path.insert(0, _REPO)


def _load_fold():
    """The report fold lives in horovod_tpu.obs.tensorwatch — but this
    tool must analyze snapshots copied OFF a pod, on machines where
    importing the package would pull in jax. tensorwatch.py keeps its
    module level stdlib-only for exactly this (the straggler_report /
    blackbox_report precedent): when the package import fails, load the
    file directly — the fold is pure dict math."""
    try:
        from horovod_tpu.obs.tensorwatch import build_tensor_report

        return build_tensor_report
    except ImportError:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_tensorwatch_fold",
            os.path.join(_REPO, "horovod_tpu", "obs", "tensorwatch.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.build_tensor_report


build_tensor_report = _load_fold()


def _load(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.loads(resp.read().decode())
    with open(source, encoding="utf-8") as fh:
        return json.load(fh)


def _ranks_of(doc: dict) -> dict:
    """Accept both emitted shapes (the straggler_report precedent): the
    ``/metrics.json`` document or a bare families dict."""
    if "ranks" in doc and isinstance(doc["ranks"], dict):
        return {int(r): fams for r, fams in doc["ranks"].items()}
    return {0: doc}


def render(report: dict, out=sys.stdout) -> None:
    w = out.write
    w(f"# numerics observatory: {report['samples']:.0f} sampled "
      f"batch(es), {report['tensor_count']} labeled tensor(s)\n")
    if report["degraded"]:
        w("DEGRADED: no tensorwatch families in this document — the "
          "observatory is off (HOROVOD_TENSORWATCH_INTERVAL_STEPS=0), "
          "the publisher never pushed, or this snapshot predates the "
          "plane.\n")
    if report["codec_snr_db"]:
        parts = ", ".join(f"{c}: {v:.1f} dB" for c, v in
                          sorted(report["codec_snr_db"].items()))
        w(f"decode SNR (worst tensor of last sample): {parts}\n")
    if report["topk_mass"]:
        # the sparse-readiness curve (docs/tensorwatch.md): how much of
        # the gradient energy a top-k wire at each k would carry
        def pct(k):
            v = report["topk_mass"].get(k)
            return "-" if v is None else f"{100 * v:.2f}%"

        w(f"sparse readiness (share of grad energy): top 0.1% -> "
          f"{pct('0.1')}, top 1% -> {pct('1')}, top 10% -> "
          f"{pct('10')}\n")
    if report["tensors"]:
        w("\n## worst tensors (lowest SNR first, then highest skew)\n")
        w(f"{'tensor':<32} {'norm2':>12} {'snr dB':>8} "
          f"{'skew x':>8}\n")
        for row in report["tensors"]:
            snr = row.get("worst_snr_db")
            spread = row.get("spread")
            w(f"{row['tensor']:<32.32} {row['norm2']:>12.4g} "
              f"{'-' if snr is None else format(snr, '>8.1f'):>8} "
              f"{'-' if spread is None else format(spread, '>8.2f'):>8}"
              f"\n")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("source",
                        help="/metrics.json file path or live URL")
    parser.add_argument("--top", type=int, default=20,
                        help="table rows to keep (worst first)")
    args = parser.parse_args(argv)
    try:
        doc = _load(args.source)
    except (OSError, ValueError) as exc:
        print(f"cannot read metrics document {args.source!r}: {exc}",
              file=sys.stderr)
        return 1
    report = build_tensor_report(_ranks_of(doc), top=args.top)
    render(report)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
