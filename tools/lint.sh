#!/usr/bin/env bash
# Contract-analysis gate (docs/analysis.md): run the full hvdlint suite
# — knob registry, lock order, collective divergence, wire compat,
# metrics/docs drift, error taxonomy, pytest markers — and exit non-zero
# on any unwaived finding. The final stdout line is one JSON summary
# (the repo tool contract, like tools/chaos_matrix.sh's cells).
#
# Extra args are forwarded to tools/hvdlint.py, e.g.:
#   tools/lint.sh --only locks,collectives
#   tools/lint.sh --list-codes
#
# Pure stdlib, no jax: runs on the same boxes runner.network does.
set -euo pipefail
cd "$(dirname "$0")/.."

python tools/hvdlint.py --json "$@"
