#!/usr/bin/env python
"""Merge and classify black-box incident dumps (docs/blackbox.md).

Reads one or more ``blackbox-*.json`` incident files — a coordinator's
merged cross-rank dump, or the per-rank files the native-controller
degrade writes — folds them into one document, and classifies it:

    python tools/blackbox_report.py blackbox-full-2-0.json
    python tools/blackbox_report.py /var/log/horovod/          # glob dir
    python tools/blackbox_report.py bb.rank0.json bb.rank1.json

Human-readable sections print first (the verdict line, the per-rank
last-cycle table, the parked-rendezvous table, each rank's final
events); the final stdout line is the classification as one JSON object
(the repo's tool contract, like trace_merge/straggler_report).

Verdict lines: ``stall@rank2 cycle 417`` (a stall escalation, with the
last cycle every rank agrees on), ``consensus-fork@rank1 window 12``,
``nonfinite@rank1 step 3``, ``dead@rank1 cycle 9``, ``desync:
flush_ordinal``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# runnable straight from a checkout: `python tools/blackbox_report.py`
# puts tools/ (not the repo root) on sys.path
sys.path.insert(0, _REPO)


def _load_classifier():
    """The classifier lives in horovod_tpu.obs.flightrec — but this tool
    must read incident files copied OFF a pod, on machines where
    importing the package would pull in jax. flightrec.py keeps its
    module level stdlib-only for exactly this: when the package import
    fails, load the file directly (classification is pure dict math)."""
    try:
        from horovod_tpu.obs.flightrec import (
            classify_incident,
            merge_incidents,
        )

        return merge_incidents, classify_incident
    except ImportError:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_blackbox_classifier",
            os.path.join(_REPO, "horovod_tpu", "obs", "flightrec.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.merge_incidents, mod.classify_incident


merge_incidents, classify_incident = _load_classifier()


def _expand(paths):
    out = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(glob.glob(
                os.path.join(path, "blackbox-*.json"))))
        else:
            out.append(path)
    return out


_EVENT_DEFAULTS = [0, "", -1, -1, ""]


def _fmt_event(event) -> str:
    # pad per-FIELD so a short event gets each missing field's own
    # sentinel (a 3-field event must read aux=-1, not aux=0)
    event = list(event)[:5]
    ts, kind, ordinal, aux, detail = event + _EVENT_DEFAULTS[len(event):]
    parts = [f"{ts}us", str(kind)]
    if ordinal not in (-1, None):
        parts.append(f"ord={ordinal}")
    if aux not in (-1, None):
        parts.append(f"aux={aux}")
    if detail:
        parts.append(str(detail)[:60])
    return " ".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge + classify blackbox-*.json incident dumps")
    ap.add_argument("paths", nargs="+",
                    help="incident file(s), or a directory to glob")
    ap.add_argument("--tail", type=int, default=8,
                    help="per-rank final events to print (default 8)")
    args = ap.parse_args(argv)

    files = _expand(args.paths)
    if not files:
        print("no blackbox-*.json files found", file=sys.stderr)
        return 1
    docs = []
    for path in files:
        with open(path, "r", encoding="utf-8") as fh:
            docs.append(json.load(fh))
    merged = merge_incidents(docs)
    report = classify_incident(merged)
    report["sources"] = [os.path.basename(p) for p in files]

    print(f"incident: world={report['world_id']} epoch={report['epoch']} "
          f"({len(files)} file(s))")
    print(f"verdict: {report['verdict']}")
    reason = (report.get("reason") or "").replace("\n", " ")
    if reason:
        print(f"reason: {reason[:200]}")
    print(f"last agreed cycle: {report['last_agreed_cycle']}  "
          f"per-rank: {report['per_rank_last_cycle']}")
    if report.get("chaos_ranks"):
        print(f"fault injections recorded on rank(s): "
              f"{report['chaos_ranks']}")
    if report.get("first_diverging_rank") is not None:
        print(f"first diverging rank: {report['first_diverging_rank']} "
              f"(stream forks at: "
              f"{_fmt_event(report['fork_event'] or [])})")
    parked = report.get("parked_rendezvous") or {}
    for channel, table in sorted(parked.items()):
        if table:
            print(f"parked {channel} rendezvous: {table}")
    for rank in sorted(merged.get("ranks", {}), key=int):
        payload = merged["ranks"][rank] or {}
        events = payload.get("events", [])
        offset = payload.get("clock_offset_us")
        print(f"rank {rank}: {len(events)} retained events"
              + (f", clock offset {offset}us" if offset is not None
                 else "") +
              (f", error: {str(payload.get('error'))[:120]}"
               if payload.get("error") else ""))
        for event in events[-args.tail:]:
            print(f"    {_fmt_event(event)}")
    # the one-line-JSON tool contract: the LAST stdout line parses
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
