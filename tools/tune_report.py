#!/usr/bin/env python
"""Render a tuning-plane JSONL decision log (docs/autotune.md).

    HOROVOD_AUTOTUNE_DECISIONS=/tmp/decisions.jsonl python train.py
    python tools/tune_report.py /tmp/decisions.jsonl

One line per decision, as written by the policy
(``horovod_tpu/tune/policy.py``): ``init`` records the starting config
and loop parameters, ``retune`` an applied knob move, ``revert`` a
rollback to the best-known config. The report prints the decision
history, per-knob move/revert counts, the score trajectory, and the
final config — then one machine-readable JSON summary as the LAST line
(the same final-line contract as tools/trace_merge.py and
tools/straggler_report.py). Stdlib-only: runs on a workstation without
the training environment.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load_decisions(path) -> List[dict]:
    fh = sys.stdin if path == "-" else open(path, encoding="utf-8")
    with fh:
        records = []
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise SystemExit(
                    f"{path}:{lineno}: not a JSONL decision record: {exc}")
    return records


def summarize(records: List[dict]) -> dict:
    per_knob: Dict[str, Dict[str, int]] = {}
    scores = []
    final_config = None
    init = None
    for rec in records:
        action = rec.get("action")
        if action == "init":
            init = rec
            final_config = rec.get("config")
            continue
        if action not in ("retune", "revert", "discard"):
            continue
        knob = rec.get("knob", "?")
        slot = per_knob.setdefault(knob, {"retunes": 0, "reverts": 0,
                                          "discards": 0})
        slot[action + "s"] += 1
        if "score" in rec:
            scores.append(rec["score"])
        final_config = rec.get("config", final_config)
    return {
        "decisions": sum(v["retunes"] + v["reverts"] + v["discards"]
                         for v in per_knob.values()),
        "retunes": sum(v["retunes"] for v in per_knob.values()),
        "reverts": sum(v["reverts"] for v in per_knob.values()),
        "discards": sum(v["discards"] for v in per_knob.values()),
        "per_knob": per_knob,
        "initial_config": (init or {}).get("config"),
        "final_config": final_config,
        "best_score": max((r.get("best_score", 0.0) for r in records
                           if r.get("action") in ("retune", "revert")),
                          default=None),
        "score_first": scores[0] if scores else None,
        "score_last": scores[-1] if scores else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a HOROVOD_AUTOTUNE_DECISIONS JSONL log")
    ap.add_argument("path", help="decision log file, or - for stdin")
    ap.add_argument("--history", action="store_true",
                    help="also print every decision line")
    args = ap.parse_args(argv)

    records = load_decisions(args.path)
    if not records:
        print("empty decision log", file=sys.stderr)
        print(json.dumps({"decisions": 0}))
        return 0
    summary = summarize(records)

    if args.history:
        for rec in records:
            action = rec.get("action", "?")
            if action == "init":
                print(f"  init    config={rec.get('config')}")
            else:
                print(f"  {action:<7} {rec.get('knob')} -> "
                      f"{rec.get('value')!r}  score={rec.get('score'):.4g} "
                      f"best={rec.get('best_score'):.4g}")
        print()
    print(f"decisions: {summary['decisions']} "
          f"({summary['retunes']} retunes, {summary['reverts']} reverts, "
          f"{summary['discards']} discards)")
    for knob, counts in sorted(summary["per_knob"].items()):
        print(f"  {knob:<28} retunes={counts['retunes']} "
              f"reverts={counts['reverts']} "
              f"discards={counts['discards']}")
    if summary["best_score"] is not None:
        print(f"best score: {summary['best_score']:.6g} bytes/us")
    print(f"initial config: {summary['initial_config']}")
    print(f"final config:   {summary['final_config']}")
    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
