#!/usr/bin/env python
"""Render the measured-results markdown table from watcher captures.

    python tools/bench_table.py bench_results_r4

Reads every ``*.json`` bench capture in the directory (one JSON line per
file, as written by ``tools/chip_watch.sh``) and prints the
docs/benchmarks.md measured table — config, img|tokens/s/device, ±1.96σ
when present, achieved TFLOP/s, MFU, and vs-reference ratio — so landing
a capture into the docs is one copy-paste, not hand-transcription.
"""

from __future__ import annotations

import glob
import json
import os
import sys

_LABELS = {
    "serving_continuous_batching_speedup":
        "Serving gateway, continuous batching (batch {batch_max}) vs "
        "naive, peak rps at p99<={p99_budget_ms}ms",
    "resnet50": "ResNet-50, bs {batch_size}",
    "resnet101": "ResNet-101, bs {batch_size}",
    "vgg16": "VGG-16, bs {batch_size}",
    "inception3": "Inception V3, bs {batch_size}",
    "transformer_lm": "Transformer LM ({attention}, seq {seq_len}, "
                      "bs {batch_size})",
    "torch": "Torch front-end (hooks → engine → {data_plane} plane), "
             "bs {batch_size}",
}


def _label(rec: dict) -> str:
    model = rec.get("metric", "").split("_synthetic")[0]
    model = model.replace("_train_images_per_sec_per_device", "")
    model = model.replace("_tokens_per_sec_per_device", "")
    tmpl = _LABELS.get(rec.get("metric", ""), _LABELS.get(model,
                                                          model or "?"))
    try:
        label = tmpl.format(**rec)
    except KeyError:
        label = tmpl
    if rec.get("scan_batches"):
        # non-protocol dispatch-overhead diagnostic; must never read as a
        # second protocol row
        label += f" — scan diagnostic ({rec['scan_batches']}/call)"
    return label


def _render_serving(rec: dict) -> None:
    """The serving_bench.py final-line contract (docs/serving.md): the
    per-mode offered-QPS sweeps rendered as the docs/benchmarks.md
    serving table — p50/p99 latency next to achieved throughput, naive
    and batched side by side per offered level."""
    sweeps = rec["serving"]
    by_offered = {}
    for mode in ("naive", "batched"):
        for row in sweeps.get(mode, []):
            by_offered.setdefault(row["offered_qps"], {})[mode] = row
    print()
    print(f"Serving sweep (batch_max {rec.get('batch_max', '?')}, "
          f"{rec.get('clients', '?')} clients, p99 budget "
          f"{rec.get('p99_budget_ms', '?')} ms) — speedup "
          f"{rec.get('value', '?')}x:")
    print("| Offered QPS | naive rps | naive p50/p99 ms | batched rps |"
          " batched p50/p99 ms |")
    print("|---|---|---|---|---|")

    def _cell(row, key):
        return "—" if row is None or row.get(key) is None else row[key]

    for offered in sorted(by_offered):
        naive = by_offered[offered].get("naive")
        batched = by_offered[offered].get("batched")
        print(f"| {offered:g} "
              f"| {_cell(naive, 'achieved_rps')} "
              f"| {_cell(naive, 'p50_ms')} / {_cell(naive, 'p99_ms')} "
              f"| {_cell(batched, 'achieved_rps')} "
              f"| {_cell(batched, 'p50_ms')} / {_cell(batched, 'p99_ms')} "
              f"|")


def _render_hierarchy(rec: dict) -> None:
    """The controller_bench.py --scaling final-line contract
    (docs/hierarchy.md): simulated-world root-load rows rendered as the
    docs table — flat vs tree root messages and bytes per cycle, with
    the in-process Negotiator cycle rate alongside."""
    rows = rec["hierarchy"].get("rows", [])
    print()
    print(f"Negotiation-tree root load "
          f"({rec['hierarchy'].get('tensors_per_cycle', '?')} "
          f"tensors/cycle, islands = floor(sqrt(ranks))) — "
          f"{rec.get('value', '?')}x fewer root messages at "
          f"{rec.get('ranks', '?')} ranks:")
    print("| Ranks | Islands | flat msgs/cyc | tree msgs/cyc |"
          " flat B/cyc | tree B/cyc | flat cyc/s | tree cyc/s |")
    print("|---|---|---|---|---|---|---|---|")
    for row in rows:
        print(f"| {row.get('ranks', '—')} | {row.get('islands', '—')} "
              f"| {row.get('flat_root_msgs', '—')} "
              f"| {row.get('tree_root_msgs', '—')} "
              f"| {row.get('flat_root_bytes', '—')} "
              f"| {row.get('tree_root_bytes', '—')} "
              f"| {row.get('flat_cycles_per_s', '—')} "
              f"| {row.get('tree_cycles_per_s', '—')} |")


def main() -> None:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "bench_results_r5"
    rows = []
    serving_recs = []
    hier_recs = []
    for path in sorted(glob.glob(os.path.join(out_dir, "*.json"))):
        try:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines()
                         if ln.startswith("{")]
            rec = json.loads(lines[-1])
        except (OSError, ValueError, IndexError):
            continue
        if "metric" not in rec or "value" not in rec:
            continue  # onchip bench etc. have their own tables
        if isinstance(rec.get("serving"), dict):
            serving_recs.append(rec)
        if isinstance(rec.get("hierarchy"), dict):
            # root-load capture, not a per-device-rate row — render its
            # own table and keep it out of the throughput table
            hier_recs.append(rec)
            continue
        rows.append((os.path.basename(path), rec))
    if not rows and not hier_recs:
        print(f"(no parseable captures in {out_dir})", file=sys.stderr)
        sys.exit(1)
    if rows:
        print("| Config | per-device rate | TFLOP/s | MFU | vs reference |"
              " live |")
        print("|---|---|---|---|---|---|")
    for name, rec in rows:
        unit = rec.get("unit", "")
        tf = rec.get("tflops_per_device")
        mfu = rec.get("mfu_pct")
        vs = rec.get("vs_baseline")
        print(f"| {_label(rec)} | {rec['value']} {unit} | "
              f"{tf if tf is not None else '—'} | "
              f"{str(mfu) + '%' if mfu is not None else '—'} | "
              f"{str(vs) + 'x' if vs is not None else '—'} | "
              f"{'yes' if rec.get('live', True) else 'watcher'} |")
    for rec in serving_recs:
        _render_serving(rec)
    for rec in hier_recs:
        _render_hierarchy(rec)


if __name__ == "__main__":
    main()
