#!/bin/bash
# Deadline supervisor for the chip watcher.
#
# The builder session that killed watcher v5 at 19:35 expected the round to
# end immediately; the driver instead restarted the builder, leaving free
# tail minutes in which a late healthy tunnel window could still land the
# queued series.  This wrapper runs tools/chip_watch.sh (any extra
# arguments are forwarded to it) but guarantees the
# end-of-round hygiene rule (the driver's bench run must own the tunnel
# alone) mechanically: at DEADLINE_EPOCH it SIGKILLs the watcher's whole
# process group, including any in-flight bench child.
#
# Usage: setsid bash tools/chip_watch_deadline.sh <deadline_epoch> [watcher args...] &
set -u
DEADLINE=${1:?usage: chip_watch_deadline.sh <deadline_epoch> [watcher args...]}
case "$DEADLINE" in
    ''|*[!0-9]*) echo "deadline must be a unix epoch, got: $DEADLINE" >&2; exit 2 ;;
esac
shift  # the rest is forwarded to chip_watch.sh (e.g. --out, --entries)
if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "deadline $DEADLINE is already in the past; refusing to start" >&2
    exit 2
fi
cd /root/repo
# Log beside the watcher: mirror a forwarded --out so the kill-audit
# trail lands in the same watch.log the watcher writes.
OUT=bench_results_r5
args=("$@")
for i in "${!args[@]}"; do
    if [ "${args[$i]}" = "--out" ] && [ $((i + 1)) -lt ${#args[@]} ]; then
        OUT="${args[$((i + 1))]}"
    fi
done
mkdir -p "$OUT"
log() { echo "[deadline $(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }

# Refuse to start while a prior watcher or an orphaned bench child is
# alive: the group kill below only covers the watcher THIS script spawns,
# so strays from an earlier instance (e.g. a `pkill -f chip_watch` that
# killed the watcher bash but not its bench child) would survive the
# deadline.  Match every process shape the watcher tree can leave
# behind: the relative-path supervisor itself (`^python bench\.py`, how
# chip_watch.sh spawns it), the supervisor's measure child
# (`<python> /abs/path/bench.py --_measure` — the anchored pattern never
# matches an absolute interpreter or script path), and the python
# invocations of lm_bench / onchip_path / the torch synthetic benchmark
# — anchored on `python... <path>.py` so an editor or `tail -f` whose
# argv merely mentions a file name cannot match.  The patterns contain
# tokens absent from this script's own argv
# (chip_watch_deadline.sh <epoch> ...; `chip_watch\.sh` needs the dot
# right after "watch", which the _deadline suffix breaks), so the guard
# cannot match itself.
orphan_pat='^python bench\.py|bench\.py --_measure|python[0-9.]* [^ ]*(lm_bench|onchip_path_bench|pytorch_synthetic_benchmark)\.py'
if pgrep -f 'chip_watch\.sh' >/dev/null || pgrep -f "$orphan_pat" >/dev/null; then
    echo "a chip_watch/bench process is already running; kill it first" >&2
    exit 2
fi

# setsid makes the watcher a session+group leader, so its pgid == $WPID —
# no ps round-trip (which races the child's setsid()) needed.
setsid bash tools/chip_watch.sh "$@" &
WPID=$!
log "watcher restarted for round tail (pid/pgid $WPID), hard deadline $(date -d @"$DEADLINE" +%H:%M:%S)"

while kill -0 "$WPID" 2>/dev/null; do
    if [ "$(date +%s)" -ge "$DEADLINE" ]; then
        log "deadline reached: killing watcher group $WPID so the driver's bench owns the tunnel"
        break
    fi
    r=$(( DEADLINE - $(date +%s) ))
    sleep $(( r < 10 ? (r > 0 ? r : 1) : 10 ))
done
# Unconditional group kill on every exit path: if the watcher bash died
# (e.g. pkill -f chip_watch) while a bench child survived in its group,
# the orphan must not hold the tunnel past the deadline either.
kill -KILL -- "-$WPID" 2>/dev/null
log "deadline supervisor exiting (group $WPID killed)"
