#!/bin/bash
# Chip watcher v5 (round 5).  v4 (45s idle cadence, torch entry, r5 output
# dir) plus two time-to-first-device-op cuts, because the 08:32 window
# closed before the first bench attempt's device op landed:
#   * HOROVOD_BENCH_PREFLIGHT_INITIAL=0 on bench runs — the watcher's own
#     compute probe (a jitted matmul, stronger than preflight's
#     jax.devices()) ran seconds earlier, so the bench's INITIAL preflight
#     is a redundant extra backend spin-up over the tunnel; the
#     supervisor's inter-attempt backend wait stays on;
#   * bench.py's host-init disk cache (pre-warmed for every entry) makes
#     the measure child's first accelerator touch follow within seconds.
# Kill it with: pkill -f chip_watch5
set -u
cd /root/repo
OUT=bench_results_r5
mkdir -p "$OUT"
log() { echo "[chip_watch5 $(date +%H:%M:%S)] $*" >> "$OUT/watch.log"; }

compute_probe() {
    timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = jax.jit(lambda a: (a @ a).sum())(x)
jax.block_until_ready(y)
print('COMPUTE_OK', jax.devices()[0].platform, flush=True)
" > "$OUT/probe.out" 2>&1
    local rc=$?
    if [ $rc -eq 0 ] && grep -q COMPUTE_OK "$OUT/probe.out"; then
        return 0
    fi
    log "compute probe failed rc=$rc: $(tail -1 "$OUT/probe.out" 2>/dev/null)"
    return 1
}

have_result() {  # a bench is done when its .json holds a parseable line
    python - "$OUT/$1.json" <<'EOF' >/dev/null 2>&1
import json, sys
with open(sys.argv[1]) as f:
    lines = [l for l in f.read().splitlines() if l.startswith("{")]
json.loads(lines[-1])
EOF
}

run_bench() {
    local name="$1"; shift
    log "bench $name starting: $*"
    HOROVOD_BENCH_MEASURE_TIMEOUT=1100 HOROVOD_BENCH_MEASURE_ATTEMPTS=2 \
    HOROVOD_BENCH_PREFLIGHT_ATTEMPTS=2 HOROVOD_BENCH_PREFLIGHT_INITIAL=0 \
    HOROVOD_BENCH_FALLBACK=0 \
        timeout 3300 python bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    log "bench $name done rc=$?: $(tail -1 "$OUT/$name.json" 2>/dev/null)"
}

run_onchip() {
    log "onchip path bench starting"
    timeout 900 python benchmarks/onchip_path_bench.py \
        > "$OUT/onchip_tpu.json" 2> "$OUT/onchip_tpu.log"
    log "onchip path bench rc=$?: $(tail -1 "$OUT/onchip_tpu.json" 2>/dev/null)"
}

run_torch() {
    # Torch front-end on the device plane: model compute is torch-CPU (no
    # torch TPU backend in this image); the measured path is the per-step
    # hook->engine->XLA-plane round trip through the real chip.
    log "torch synthetic bench starting"
    HOROVOD_DATA_PLANE=xla timeout 1200 \
        python examples/pytorch_synthetic_benchmark.py --json \
        --num-iters 5 --num-batches-per-iter 2 \
        > "$OUT/torch_synthetic.json" 2> "$OUT/torch_synthetic.log"
    log "torch bench rc=$?: $(tail -1 "$OUT/torch_synthetic.json" 2>/dev/null)"
}

run_lm() {  # $1 = name, rest = lm_bench args
    local name="$1"; shift
    log "lm bench $name starting: $*"
    timeout 2400 python benchmarks/lm_bench.py "$@" \
        > "$OUT/$name.json" 2> "$OUT/$name.log"
    log "lm bench $name done rc=$?: $(tail -1 "$OUT/$name.json" 2>/dev/null)"
}

log "watcher v5 started (pid $$)"
round=0
while true; do
    round=$((round + 1))
    missing=0
    for entry in \
        "resnet50|" \
        "resnet101_bs64|--model resnet101 --batch-size 64" \
        "resnet50_bs128|--model resnet50 --batch-size 128" \
        "resnet50_bs256|--model resnet50 --batch-size 256" \
        "resnet50_scan|SCAN" \
        "torch_synthetic|TORCH" \
        "lm_flash|LM --attention flash" \
        "lm_dense|LM --attention dense" \
        "lm_flash_4k|LM --attention flash --seq-len 4096 --batch-size 2 --remat" \
        "vgg16|--model vgg16" \
        "inception3|--model inception3" \
        "onchip_tpu|ONCHIP"; do
        name="${entry%%|*}"; benchargs="${entry#*|}"
        have_result "$name" && continue
        missing=$((missing + 1))
        if ! compute_probe; then
            log "round $round: chip not computing; sleeping 45s"
            sleep 45
            continue
        fi
        log "round $round: chip computes OK -> $name"
        if [ "$benchargs" = "ONCHIP" ]; then
            run_onchip
        elif [ "$benchargs" = "TORCH" ]; then
            run_torch
        elif [ "$benchargs" = "SCAN" ]; then
            # dispatch-overhead diagnostic: same bs32 point, one scanned
            # device call per iteration — scan==separate rules dispatch
            # out of the cap attribution; scan>separate convicts it
            HOROVOD_BENCH_SCAN_BATCHES=1 run_bench "$name"
        elif [ "${benchargs%% *}" = "LM" ]; then
            if [ "$name" = "lm_flash" ]; then
                # the flash kernel's on-TPU HLO + device profile ride the
                # first LM capture (same artifacts as the resnet50 entry)
                HOROVOD_BENCH_DUMP_HLO="$OUT/lm_flash_hlo.txt" \
                HOROVOD_BENCH_PROFILE="$OUT/lm_flash_profile" \
                    run_lm "$name" ${benchargs#LM }
            else
                # shellcheck disable=SC2086
                run_lm "$name" ${benchargs#LM }
            fi
        elif [ "$name" = "resnet50" ]; then
            HOROVOD_BENCH_DUMP_HLO="$OUT/resnet50_hlo.txt" \
            HOROVOD_BENCH_PROFILE="$OUT/resnet50_profile" \
                run_bench "$name"
            # summarize only when the bench actually landed its number —
            # a timed-out attempt can leave a partial trace on disk, and
            # attributing from it would put wrong evidence next to nothing
            if have_result resnet50 && [ -d "$OUT/resnet50_profile" ]; then
                # the captured XPlane -> bottleneck attribution, written
                # next to the numbers (the bs32 MFU-cap evidence)
                timeout 300 python tools/profile_summary.py \
                    "$OUT/resnet50_profile" \
                    --out "$OUT/resnet50_profile_summary.md" \
                    > "$OUT/resnet50_profile_summary.log" 2>&1
                log "profile summary rc=$?"
            fi
        else
            # shellcheck disable=SC2086
            run_bench "$name" $benchargs
        fi
    done
    if [ $missing -eq 0 ]; then
        log "ALL BENCHES CAPTURED after $round round(s)"
        break
    fi
    sleep 30
done
