"""MNIST via the Flax front-end — the ``keras_mnist.py`` analog (reference
``examples/keras_mnist.py``): build a model, wrap the optimizer with the
front-end's ``DistributedTrainState`` (the ``hvd.DistributedOptimizer``
Keras wrap), broadcast initial state, train data-parallel, checkpoint on
rank 0, and prove resume via ``load_model``.

Run single-host:   python examples/flax_mnist.py
Run multi-process: python -m horovod_tpu.runner -np 2 --host-data-plane \
                       python examples/flax_mnist.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.flax as hvd_flax
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n: int, seed: int):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 28, 28, 1)).astype(np.float32) * 0.1
    w = rng.standard_normal((28 * 28, 10)).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    n_dev = hvd.local_device_count()
    global_batch = args.batch_size * n_dev

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))

    # Scale LR by world size (reference keras_mnist.py: lr * hvd.size()) and
    # wrap via the front-end; axis_name routes averaging onto the mesh.
    def make_state():
        return hvd_flax.DistributedTrainState.create(
            apply_fn=model.apply, params=params,
            tx=optax.sgd(args.lr * hvd.num_devices(), momentum=0.9),
            axis_name=hvd.parallel.DATA_AXIS)

    state = hvd_flax.broadcast_train_state(make_state(), root_rank=0)

    def train_step(state, x, y):
        def loss_fn(p):
            logits = state.apply_fn(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        loss = jax.lax.pmean(loss, hvd.parallel.DATA_AXIS)
        return state.apply_gradients(grads=grads), loss

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(hvd.parallel.DATA_AXIS), P(hvd.parallel.DATA_AXIS)),
        out_specs=(P(), P())))

    x_all, y_all = synthetic_mnist(global_batch * 10, seed=1000 + hvd.rank())
    for epoch in range(args.epochs):
        losses = []
        for b in range(x_all.shape[0] // global_batch):
            sl = slice(b * global_batch, (b + 1) * global_batch)
            state, loss = step(state, x_all[sl], y_all[sl])
            losses.append(float(jnp.mean(loss)))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    # Rank-0 checkpoint + load_model resume (test_keras.py:62-246 pattern).
    # The path is rank-0's and shared (restore is collective: every rank
    # loads, then root's copy is broadcast — checkpoint.restore contract).
    ckpt = os.path.join(tempfile.mkdtemp(), "flax_mnist_ckpt")
    hvd_flax.save_model(ckpt, state)
    # Broadcasting rank-0's path doubles as the write barrier: no rank can
    # learn the path (and start reading) before rank 0 finished saving.
    ckpt = hvd.broadcast_object(ckpt, 0)
    restored = hvd_flax.load_model(ckpt, make_state())
    assert int(restored.step) == int(state.step)
    if hvd.rank() == 0:
        print(f"restored at step {int(restored.step)}: OK")
    hvd.shutdown()


if __name__ == "__main__":
    main()
