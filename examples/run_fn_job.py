"""Programmatic job API — the ``horovod.spark.run(fn)`` analog
(``horovod/spark/__init__.py:80-196``) without Spark: the function below is
cloudpickled by value, shipped to one worker process per rank over the
driver's authenticated TCP service, executed with the world initialized,
and per-rank return values come back as a list — the exact driver/task
contract of the reference's Spark orchestrator (SURVEY §3.4).

Run: python examples/run_fn_job.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def train_fn(scale: float):
    """Runs on every rank; calls hvd.init() itself, exactly like reference
    user fns do under horovod.spark.run."""
    import os

    import numpy as np

    # workers are fresh processes: let EXAMPLE_PLATFORM=cpu steer them off
    # the TPU (e.g. for CI smoke runs on a machine whose chip is busy)
    platform = os.environ.get("EXAMPLE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    import horovod_tpu as hvd

    hvd.init()
    # every rank contributes its rank; the sum proves the collective ran
    contribution = np.array([hvd.rank() * scale], dtype=np.float32)
    total = hvd.allreduce(contribution, average=False, name="job.sum")
    result = {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "sum": float(np.asarray(total)[0]),
    }
    hvd.shutdown()
    return result


def main() -> None:
    import horovod_tpu.runner as runner

    results = runner.run(train_fn, args=(10.0,), np=2)
    print("per-rank results:", results)
    expected = sum(range(2)) * 10.0
    assert all(r["sum"] == expected for r in results), results
    print("OK")


if __name__ == "__main__":
    main()
