"""PyTorch ImageNet ResNet-50 example — analog of the reference's
``examples/pytorch_imagenet_resnet50.py`` on the TPU-native engine,
demonstrating the full production training loop:

- checkpoint-resume with the resume epoch *broadcast* from rank 0 so all
  ranks agree (reference :71-80),
- ``--batches-per-allreduce`` local gradient accumulation via the
  optimizer's ``backward_passes_per_step`` (reference :30-35),
- ``--fp16-allreduce`` gradient compression on the wire,
- ``DistributedSampler``-partitioned data, one shard per rank,
- Goyal et al. LR schedule: warmup from the single-device LR to the
  world-scaled LR over the first epochs, then stepped decay,
- cross-rank metric averaging and rank-0-only checkpointing.

torchvision isn't available in this image, so the ResNet-50 definition is
inline (standard bottleneck residual network) and the dataset is synthetic
ImageNet-shaped noise; every distributed mechanic matches the reference.

Run: python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/pytorch_imagenet_resnet50.py --epochs 1 \
         --image-size 64 --train-batches 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import torch
import torch.nn.functional as F
import torch.utils.data.distributed

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch


class Bottleneck(torch.nn.Module):
    expansion = 4

    def __init__(self, in_ch, width, stride=1):
        super().__init__()
        out_ch = width * self.expansion
        self.conv1 = torch.nn.Conv2d(in_ch, width, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(width)
        self.conv2 = torch.nn.Conv2d(width, width, 3, stride=stride,
                                     padding=1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(width)
        self.conv3 = torch.nn.Conv2d(width, out_ch, 1, bias=False)
        self.bn3 = torch.nn.BatchNorm2d(out_ch)
        self.down = None
        if stride != 1 or in_ch != out_ch:
            self.down = torch.nn.Sequential(
                torch.nn.Conv2d(in_ch, out_ch, 1, stride=stride, bias=False),
                torch.nn.BatchNorm2d(out_ch))

    def forward(self, x):
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        shortcut = x if self.down is None else self.down(x)
        return F.relu(out + shortcut)


class ResNet50(torch.nn.Module):
    """Standard ResNet-50 (He et al.): stages [3, 4, 6, 3] of bottlenecks."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, 64, 7, stride=2, padding=3,
                                     bias=False)
        self.bn1 = torch.nn.BatchNorm2d(64)
        layers = []
        in_ch = 64
        for width, blocks, stride in ((64, 3, 1), (128, 4, 2),
                                      (256, 6, 2), (512, 3, 2)):
            for i in range(blocks):
                layers.append(Bottleneck(in_ch, width,
                                         stride if i == 0 else 1))
                in_ch = width * Bottleneck.expansion
        self.layers = torch.nn.Sequential(*layers)
        self.fc = torch.nn.Linear(in_ch, num_classes)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.bn1(self.conv1(x))), 3, stride=2,
                         padding=1)
        x = self.layers(x)
        x = torch.flatten(F.adaptive_avg_pool2d(x, 1), 1)
        return self.fc(x)


class Metric:
    """Cross-rank running average (reference's Metric helper, :230-246)."""

    def __init__(self, name):
        self.name = name
        self.sum = torch.zeros(1)
        self.n = 0

    def update(self, val):
        self.sum += hvd_torch.allreduce(val.detach(), average=True,
                                        name=self.name)
        self.n += 1

    @property
    def avg(self):
        return self.sum / max(self.n, 1)


def main() -> None:
    parser = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--checkpoint-format",
                        default="/tmp/imagenet-checkpoint-{epoch}.pth.tar")
    parser.add_argument("--fp16-allreduce", action="store_true",
                        help="fp16 gradient compression on the wire")
    parser.add_argument("--batches-per-allreduce", type=int, default=1,
                        help="local accumulation before the allreduce; "
                             "multiplies the effective batch size")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--train-batches", type=int, default=8,
                        help="synthetic batches per rank per epoch")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--base-lr", type=float, default=0.0125,
                        help="single-device learning rate")
    parser.add_argument("--warmup-epochs", type=float, default=5)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=0.00005)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()

    allreduce_batch_size = args.batch_size * args.batches_per_allreduce

    hvd.init()
    torch.manual_seed(args.seed)
    verbose = hvd.rank() == 0

    # Resume from the newest checkpoint rank 0 can see; broadcast the
    # decision so every rank starts the same epoch (reference :71-80).
    resume_from_epoch = 0
    for try_epoch in range(args.epochs, 0, -1):
        if os.path.exists(args.checkpoint_format.format(epoch=try_epoch)):
            resume_from_epoch = try_epoch
            break
    resume_from_epoch = int(hvd_torch.broadcast(
        torch.tensor(resume_from_epoch), root_rank=0,
        name="resume_from_epoch"))

    # Synthetic ImageNet-shaped shard, partitioned by DistributedSampler
    # exactly as the reference partitions the real dataset.
    n = args.train_batches * allreduce_batch_size
    g = torch.Generator().manual_seed(args.seed)
    train_dataset = torch.utils.data.TensorDataset(
        torch.randn(n, 3, args.image_size, args.image_size, generator=g),
        torch.randint(0, args.num_classes, (n,), generator=g))
    train_sampler = torch.utils.data.distributed.DistributedSampler(
        train_dataset, num_replicas=hvd.size(), rank=hvd.rank())
    train_loader = torch.utils.data.DataLoader(
        train_dataset, batch_size=allreduce_batch_size,
        sampler=train_sampler)

    model = ResNet50(num_classes=args.num_classes)
    compression = (hvd_torch.Compression.fp16 if args.fp16_allreduce
                   else hvd_torch.Compression.none)
    optimizer = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(),
                        # LR scaled by total batch multiplier (ref :150).
                        lr=args.base_lr * hvd.size() *
                        args.batches_per_allreduce,
                        momentum=args.momentum, weight_decay=args.wd),
        named_parameters=model.named_parameters(),
        compression=compression,
        backward_passes_per_step=args.batches_per_allreduce)

    if resume_from_epoch > 0 and hvd.rank() == 0:
        ckpt = torch.load(
            args.checkpoint_format.format(epoch=resume_from_epoch))
        model.load_state_dict(ckpt["model"])
        optimizer.load_state_dict(ckpt["optimizer"])

    # Rank-0-consistent start, fresh or restored (reference :158-160).
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd_torch.broadcast_optimizer_state(optimizer, root_rank=0)

    def adjust_learning_rate(epoch, batch_idx, batches_per_epoch):
        """Goyal et al. warmup then 30/60/80-epoch decay (ref :168-184)."""
        if epoch < args.warmup_epochs:
            ep = epoch + float(batch_idx + 1) / batches_per_epoch
            lr_adj = 1.0 / hvd.size() * (
                ep * (hvd.size() - 1) / args.warmup_epochs + 1)
        elif epoch < 30:
            lr_adj = 1.0
        elif epoch < 60:
            lr_adj = 1e-1
        elif epoch < 80:
            lr_adj = 1e-2
        else:
            lr_adj = 1e-3
        for pg in optimizer.param_groups:
            pg["lr"] = (args.base_lr * hvd.size() *
                        args.batches_per_allreduce * lr_adj)

    def accuracy(output, target):
        pred = output.max(1, keepdim=True)[1]
        return pred.eq(target.view_as(pred)).float().mean()

    for epoch in range(resume_from_epoch, args.epochs):
        model.train()
        train_sampler.set_epoch(epoch)
        train_loss, train_acc = Metric("train_loss"), Metric("train_acc")
        for batch_idx, (data, target) in enumerate(train_loader):
            adjust_learning_rate(epoch, batch_idx, len(train_loader))
            optimizer.zero_grad()
            # Split an allreduce batch into sub-batches; grads accumulate
            # locally and the allreduce fires once per full batch
            # (reference :196-208).
            for i in range(0, len(data), args.batch_size):
                data_b = data[i:i + args.batch_size]
                target_b = target[i:i + args.batch_size]
                output = model(data_b)
                train_acc.update(accuracy(output, target_b))
                loss = F.cross_entropy(output, target_b)
                train_loss.update(loss)
                # scale so the accumulated gradient is the batch average
                loss = loss * (len(data_b) / len(data))
                loss.backward()
            optimizer.step()
        if verbose:
            print(f"epoch {epoch}: loss={float(train_loss.avg):.4f} "
                  f"acc={float(train_acc.avg):.4f}")
        # Checkpoint on rank 0 only (reference :249-255).
        if hvd.rank() == 0:
            torch.save({"model": model.state_dict(),
                        "optimizer": optimizer.state_dict()},
                       args.checkpoint_format.format(epoch=epoch + 1))
    print("done")
    hvd.shutdown()


if __name__ == "__main__":
    main()
