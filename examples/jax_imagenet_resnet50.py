"""ResNet-50 ImageNet-style training — analog of the reference's
``examples/keras_imagenet_resnet50.py`` / ``pytorch_imagenet_resnet50.py``:
LR = base * num_devices with gradual warmup (Goyal et al.), staircase decay
at epochs 30/60/80, bf16 compression on the gradient allreduce, checkpoint
on rank 0. Data is synthetic unless a loader is plugged in.

Run: python examples/jax_imagenet_resnet50.py --epochs 1 --steps-per-epoch 5 \
         --batch-size 8 --image-size 64   (smoke settings)
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.callbacks import warmup_schedule
from horovod_tpu.models import ResNet50


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=90)
    parser.add_argument("--steps-per-epoch", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=32,
                        help="per-device batch size")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--base-lr", type=float, default=0.0125,
                        help="per-device LR (reference keras example)")
    parser.add_argument("--warmup-epochs", type=int, default=5)
    parser.add_argument("--checkpoint-dir", default=None)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    n_dev = hvd.local_device_count()

    model = ResNet50(num_classes=1000)
    params_vars = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, args.image_size, args.image_size, 3)))
    params, batch_stats = params_vars["params"], params_vars["batch_stats"]

    # Warmup to base_lr * num_devices over warmup_epochs, then staircase
    # decay (reference LearningRateScheduleCallback stack at 30/60/80).
    def decay(step):
        epoch = step // args.steps_per_epoch + args.warmup_epochs
        scale = jnp.where(epoch >= 80, 1e-3,
                          jnp.where(epoch >= 60, 1e-2,
                                    jnp.where(epoch >= 30, 1e-1, 1.0)))
        return args.base_lr * hvd.num_devices() * scale

    schedule = warmup_schedule(args.base_lr, args.steps_per_epoch,
                               warmup_epochs=args.warmup_epochs, after=decay)
    opt = hvd.DistributedOptimizer(
        optax.sgd(schedule, momentum=0.9, nesterov=True),
        axis_name="data", compression=hvd.Compression.bf16)
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, stats, x, y):
        logits, updated = model.apply(
            {"params": p, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()
        return loss, updated["batch_stats"]

    def train_step(p, s, stats, x, y):
        (loss, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, stats, x, y)
        updates, s = opt.update(grads, s, p)
        stats = jax.tree_util.tree_map(
            lambda v: jax.lax.pmean(v, "data"), stats)
        return (optax.apply_updates(p, updates), s, stats,
                jax.lax.pmean(loss, "data"))

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P())))

    global_batch = args.batch_size * n_dev
    rng = np.random.default_rng(hvd.rank())
    for epoch in range(args.epochs):
        for _ in range(args.steps_per_epoch):
            x = jnp.asarray(rng.standard_normal(
                (global_batch, args.image_size, args.image_size, 3),
                dtype=np.float32))
            y = jnp.asarray(rng.integers(0, 1000, size=(global_batch,)))
            params, opt_state, batch_stats, loss = step(
                params, opt_state, batch_stats, x, y)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f}")
            if args.checkpoint_dir:
                hvd.checkpoint.save(f"{args.checkpoint_dir}/epoch{epoch}",
                                    {"params": params,
                                     "batch_stats": batch_stats})
    hvd.shutdown()


if __name__ == "__main__":
    main()
