"""TF2 eager MNIST example — analog of the reference's
``examples/tensorflow_mnist_eager.py`` on the TPU-native engine:
``DistributedGradientTape`` averages gradients through the collective
engine, ``broadcast_variables`` aligns ranks after the first batch (when
variables exist), and checkpoints are written by rank 0 only via
``tf.train.Checkpoint``.

Data is synthetic MNIST-shaped noise (no network egress here); the
distributed mechanics are identical to the reference example.

Run: python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/tensorflow_mnist_eager.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batches", type=int, default=20)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--checkpoint-dir", default="/tmp/tf_mnist_eager_ckpt")
    args = parser.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    # Horovod: initialize (reference tensorflow_mnist_eager.py:23).
    hvd.init()
    tf.random.set_seed(42 + hvd.rank())

    mnist_model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, [3, 3], activation="relu"),
        tf.keras.layers.Conv2D(16, [3, 3], activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(10),
    ])

    # Horovod: LR scaled by world size (reference :38).
    opt = tf.keras.optimizers.RMSprop(args.lr * hvd.size())

    rng = np.random.default_rng(1234 + hvd.rank())
    images = rng.standard_normal(
        (args.batches * args.batch_size, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=(args.batches * args.batch_size,))
    dataset = tf.data.Dataset.from_tensor_slices(
        (images, labels.astype(np.int64)))
    dataset = dataset.shuffle(1000).batch(args.batch_size)

    checkpoint = tf.train.Checkpoint(model=mnist_model)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    for batch, (x, y) in enumerate(dataset.take(args.batches)):
        with tf.GradientTape() as tape:
            logits = mnist_model(x, training=True)
            loss_value = loss_fn(y, logits)

        # Horovod: broadcast initial variable states from rank 0 once the
        # first forward pass has created them (reference :62-66).
        if batch == 0:
            hvd.broadcast_variables(mnist_model.variables, root_rank=0)

        # Horovod: the distributed tape averages gradients on .gradient()
        # (reference :69).
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss_value, mnist_model.variables)
        opt.apply_gradients(zip(grads, mnist_model.variables))

        if batch % 10 == 0 and hvd.local_rank() == 0:
            print(f"Step #{batch}\tLoss: {float(loss_value):.6f}")

    # Horovod: checkpoint on rank 0 only (reference :78-81).
    if hvd.rank() == 0:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        checkpoint.save(os.path.join(args.checkpoint_dir, "ckpt"))
    print("done")
    hvd.shutdown()


if __name__ == "__main__":
    main()
