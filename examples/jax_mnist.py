"""Data-parallel MNIST training in JAX — the ``examples/pytorch_mnist.py``
equivalent for the TPU-native framework.

Follows the reference README's canonical steps: init → scale LR by the
device count → wrap the optimizer → broadcast initial state from rank 0 →
train, checkpointing on rank 0 only. Data is synthetic (no dataset
downloads in the benchmark environment); swap ``synthetic_mnist`` for a real
loader to train for accuracy.

Run single-host:   python examples/jax_mnist.py
Run multi-process: python -m horovod_tpu.runner -np 2 --host-data-plane \
                       python examples/jax_mnist.py
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n: int, seed: int):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(n,), dtype=np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-device batch size")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--checkpoint-dir", default=None)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    n_dev = hvd.local_device_count()

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(42),
                        jnp.zeros((1, 28, 28, 1)))

    # Reference README step 3: scale LR by the number of workers.
    opt = hvd.DistributedOptimizer(
        optax.sgd(args.lr * hvd.num_devices(), momentum=0.9),
        axis_name="data")
    opt_state = opt.init(params)

    # Step 4: rank-0-consistent start.
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    def loss_fn(p, x, y):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def train_step(p, s, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, jax.lax.pmean(loss, "data")

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P())))

    global_batch = args.batch_size * n_dev
    steps_per_epoch = 20
    for epoch in range(args.epochs):
        for i in range(steps_per_epoch):
            x, y = synthetic_mnist(global_batch, seed=epoch * 1000 + i)
            params, opt_state, loss = step(params, opt_state, x, y)
        # metric averaging across ranks (MetricAverageCallback pattern)
        logs = {"loss": float(loss)}
        hvd.callbacks.MetricAverageCallback().on_epoch_end(
            epoch, hvd.callbacks.TrainLoop(), logs)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f}")
            if args.checkpoint_dir:
                # Step 6: checkpoint on rank 0 only.
                hvd.checkpoint.save(
                    f"{args.checkpoint_dir}/epoch{epoch}",
                    {"params": params, "opt_state": opt_state})
    hvd.shutdown()


if __name__ == "__main__":
    main()
