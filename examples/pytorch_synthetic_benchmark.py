"""PyTorch front-end synthetic benchmark — the reference's canonical
measurement protocol (``examples/pytorch_synthetic_benchmark.py:24-110``):
init → wrap optimizer → broadcast state → warmup → timed iterations →
img/sec mean ± 1.96σ. The model is a compact handwritten residual CNN
(torchvision is not part of the TPU image); swap in any ``nn.Module``.

The interesting path being measured here is the framework's torch engine:
per-parameter hooks fire async named allreduces during ``backward()``, the
engine fuses them within each cycle, and ``opt.step()`` synchronizes — on
multi-process runs the bytes ride the negotiated data plane (XLA device
collectives or the host exchange).

Run: python examples/pytorch_synthetic_benchmark.py --num-iters 3
     python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/pytorch_synthetic_benchmark.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch


class ResidualBlock(torch.nn.Module):
    def __init__(self, channels: int) -> None:
        super().__init__()
        self.conv1 = torch.nn.Conv2d(channels, channels, 3, padding=1,
                                     bias=False)
        self.bn1 = torch.nn.BatchNorm2d(channels)
        self.conv2 = torch.nn.Conv2d(channels, channels, 3, padding=1,
                                     bias=False)
        self.bn2 = torch.nn.BatchNorm2d(channels)

    def forward(self, x):
        h = F.relu(self.bn1(self.conv1(x)))
        return F.relu(x + self.bn2(self.conv2(h)))


class SmallResNet(torch.nn.Module):
    """Stem + residual stages + classifier; ~ResNet-18-shaped but sized for
    CPU benchmarking (the reference benches torchvision resnet50 on GPUs)."""

    def __init__(self, num_classes: int = 1000, width: int = 32,
                 blocks_per_stage: int = 2) -> None:
        super().__init__()
        self.stem = torch.nn.Conv2d(3, width, 7, stride=2, padding=3,
                                    bias=False)
        stages = []
        channels = width
        for stage in range(3):
            if stage:
                stages.append(torch.nn.Conv2d(channels, channels * 2, 1,
                                              stride=2, bias=False))
                channels *= 2
            stages.extend(ResidualBlock(channels)
                          for _ in range(blocks_per_stage))
        self.stages = torch.nn.Sequential(*stages)
        self.head = torch.nn.Linear(channels, num_classes)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.stem(x)), 3, stride=2, padding=1)
        x = self.stages(x)
        x = x.mean(dim=(2, 3))
        return self.head(x)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=64,
                        help="reference uses 224; smaller default keeps the "
                             "CPU demo quick")
    parser.add_argument("--num-warmup-batches", type=int, default=2)
    parser.add_argument("--num-batches-per-iter", type=int, default=2)
    parser.add_argument("--num-iters", type=int, default=3)
    parser.add_argument("--json", action="store_true",
                        help="emit one self-describing JSON result line "
                             "(the bench.py capture protocol) so the chip "
                             "watcher can record this run with provenance")
    args = parser.parse_args()

    hvd.init()

    torch.manual_seed(42)
    model = SmallResNet()
    optimizer = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size()),
        named_parameters=model.named_parameters())

    # Reference steps 5-6: consistent start on every rank.
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd_torch.broadcast_optimizer_state(optimizer, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step() -> None:
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    def log(*a):
        if hvd.rank() == 0:
            print(*a, flush=True)

    log(f"Model: SmallResNet, batch size {args.batch_size}, "
        f"ranks: {hvd.size()}")
    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        rate = args.batch_size * args.num_batches_per_iter / dt
        img_secs.append(rate)
        log(f"Iter #{i}: {rate:.1f} img/sec per rank")

    mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    log(f"Img/sec per rank: {mean:.1f} +- {conf:.1f}")
    log(f"Total img/sec on {hvd.size()} rank(s): "
        f"{mean * hvd.size():.1f} +- {conf * hvd.size():.1f}")
    if args.json and hvd.rank() == 0:
        # Same self-describing capture line as bench.py: the watcher files
        # this under torch_synthetic.json; model compute is torch-CPU (torch
        # has no TPU backend in this image) — what the entry measures is the
        # eager hook→engine→data-plane path, so the plane is stamped in.
        import json

        from horovod_tpu.core.provenance import git_head_sha

        sha = git_head_sha(os.path.dirname(os.path.abspath(__file__)))
        print(json.dumps({
            "metric": "torch_synthetic_train_images_per_sec_per_rank",
            "value": round(float(mean), 2),
            "unit": "img/s",
            "vs_baseline": None,
            "live": True,
            "front_end": "torch",
            "data_plane": os.environ.get("HOROVOD_DATA_PLANE", "auto"),
            "batch_size": args.batch_size,
            "image_size": args.image_size,
            "n_ranks": hvd.size(),
            "captured_at": round(time.time(), 1),
            "git_sha": sha,
        }), flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
