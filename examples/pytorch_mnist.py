"""PyTorch front-end MNIST example — direct analog of the reference's
``examples/pytorch_mnist.py`` on the TPU-native engine: per-parameter
gradient hooks fire async allreduces, ``opt.step()`` waits and applies the
world-averaged gradients, state broadcast keeps ranks consistent.

Run: python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/pytorch_mnist.py
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import torch
import torch.nn.functional as F

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch


class Net(torch.nn.Module):
    """The reference example's model (``examples/pytorch_mnist.py:40-55``)."""

    def __init__(self) -> None:
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(42)

    model = Net()
    # LR scaled by world size (reference README step 3).
    optimizer = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size(),
                        momentum=0.5),
        named_parameters=model.named_parameters())

    # Rank-0-consistent start (steps 4-5).
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd_torch.broadcast_optimizer_state(optimizer, root_rank=0)

    for epoch in range(args.epochs):
        model.train()
        for batch in range(10):
            # synthetic, rank-sharded data
            g = torch.Generator().manual_seed(
                epoch * 10000 + batch * 100 + hvd.rank())
            data = torch.randn(args.batch_size, 1, 28, 28, generator=g)
            target = torch.randint(0, 10, (args.batch_size,), generator=g)
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
        # average the epoch loss across ranks for reporting
        avg = hvd_torch.allreduce(loss.detach(), average=True,
                                  name=f"loss.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(avg):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
