"""Skip-gram word2vec with sparse gradient allreduce — analog of the
reference's ``examples/tensorflow_word2vec.py``, and the showcase for the
sparse (IndexedSlices / allgather-based) gradient path
(``tensorflow/__init__.py:72-83`` in the reference).

Embedding gradients touch only the rows seen in the batch; shipping them as
(indices, values) via allgather moves O(batch) data instead of O(vocab).

Run: python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/jax_word2vec.py
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--vocab-size", type=int, default=2000)
    parser.add_argument("--embedding-dim", type=int, default=64)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--lr", type=float, default=0.5)
    args = parser.parse_args()

    hvd.init()
    rng = np.random.default_rng(1234 + hvd.rank())
    key = jax.random.PRNGKey(0)  # identical init on all ranks
    emb = jax.random.normal(key, (args.vocab_size, args.embedding_dim)) * 0.1
    out_w = jax.random.normal(jax.random.PRNGKey(1),
                              (args.vocab_size, args.embedding_dim)) * 0.1
    emb = hvd.broadcast_parameters(emb, root_rank=0)

    def loss_fn(emb_rows, out_rows, neg_rows):
        # skip-gram with one positive and k sampled negatives per center
        pos = jax.nn.log_sigmoid(
            jnp.sum(emb_rows * out_rows, axis=-1))
        neg = jax.nn.log_sigmoid(
            -jnp.einsum("bd,bkd->bk", emb_rows, neg_rows))
        return -(pos.mean() + neg.sum(axis=-1).mean())

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))

    for step in range(args.steps):
        centers = rng.integers(0, args.vocab_size, args.batch_size)
        contexts = rng.integers(0, args.vocab_size, args.batch_size)
        negatives = rng.integers(0, args.vocab_size, (args.batch_size, 5))
        loss, (g_emb_rows, g_out_rows) = grad_fn(
            emb[centers], out_w[contexts], out_w[negatives])

        # SPARSE path: only touched rows cross the wire
        g_emb = hvd.allreduce_sparse(
            hvd.IndexedSlices(centers, np.asarray(g_emb_rows),
                              emb.shape), name=f"w2v.emb.{step}")
        g_out = hvd.allreduce_sparse(
            hvd.IndexedSlices(contexts, np.asarray(g_out_rows),
                              out_w.shape), name=f"w2v.out.{step}")
        emb = emb - args.lr * g_emb.to_dense()
        out_w = out_w - args.lr * g_out.to_dense()

        if step % 10 == 0:
            avg = hvd.allreduce(np.float64(loss), average=True,
                                name=f"w2v.loss.{step}")
            if hvd.rank() == 0:
                print(f"step {step}: loss={float(avg):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
