"""tf.keras MNIST example — analog of the reference's
``examples/keras_mnist.py`` (and the tf.keras shim it demonstrates,
``horovod/tensorflow/keras``) on the TPU-native engine: wrapped optimizer,
broadcast + metric-average callbacks, LR scaled by world size, rank-0-only
checkpointing.

Data is synthetic MNIST-shaped noise (this environment has no network
egress); the training mechanics are identical.

Run: python -m horovod_tpu.runner -np 2 --host-data-plane \
         python examples/tensorflow_mnist.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--samples", type=int, default=512)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--checkpoint-dir", default="/tmp/tf_mnist_ckpt")
    args = parser.parse_args()

    import keras

    import horovod_tpu.tensorflow.keras as hvd

    # Horovod: initialize (reference keras_mnist.py step 1).
    hvd.init()
    keras.utils.set_random_seed(42 + hvd.rank())

    # synthetic MNIST: each rank sees its own shard, as the reference
    # shards by rank
    x = np.random.randn(args.samples, 28, 28, 1).astype(np.float32)
    y = np.random.randint(0, 10, size=(args.samples,))

    model = keras.Sequential([
        keras.layers.Conv2D(32, kernel_size=(3, 3), activation="relu",
                            input_shape=(28, 28, 1)),
        keras.layers.Conv2D(64, (3, 3), activation="relu"),
        keras.layers.MaxPooling2D(pool_size=(2, 2)),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # Horovod: scale LR by world size and wrap the optimizer (steps 2-3).
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=args.lr * hvd.size(),
                             momentum=0.9))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    callbacks = [
        # Horovod: broadcast rank 0's initial state (step 4).
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # Horovod: world-averaged metrics in the logs.
        hvd.callbacks.MetricAverageCallback(),
    ]
    # Horovod: checkpoint on rank 0 only (step 6).
    if hvd.rank() == 0:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(args.checkpoint_dir, "checkpoint.keras")))

    hist = model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
                     callbacks=callbacks, verbose=0)
    for epoch, loss in enumerate(hist.history["loss"]):
        print(f"epoch {epoch}: loss={loss:.4f} "
              f"acc={hist.history['accuracy'][epoch]:.4f}")
    print("done")


if __name__ == "__main__":
    main()
