"""dm-haiku front-end example: the same DistributedOptimizer wraps any
optax-based framework — flax (``jax_mnist.py``), haiku (here), or raw JAX.
Mirrors the reference's pattern of one optimizer wrapper serving many
front-ends (SURVEY §2.2-2.5).

Run: python examples/haiku_mnist.py
"""

import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import haiku as hk
import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def net_fn(x):
    return hk.Sequential([
        hk.Conv2D(32, 3), jax.nn.relu,
        hk.MaxPool(2, 2, "VALID"),
        hk.Flatten(),
        hk.Linear(128), jax.nn.relu,
        hk.Linear(10),
    ])(x)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-size", type=int, default=32)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    n_dev = hvd.local_device_count()

    net = hk.without_apply_rng(hk.transform(net_fn))
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    # adaptive optimizers don't linear-scale with world size (the Goyal
    # rule is for SGD); keep the base LR
    opt = hvd.DistributedOptimizer(optax.adam(1e-3), axis_name="data")
    opt_state = opt.init(params)
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, x, y):
        logits = net.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def train_step(p, s, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, jax.lax.pmean(loss, "data")

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P())))

    rng = np.random.default_rng(0)
    global_batch = args.batch_size * n_dev
    # small fixed synthetic dataset so the loss visibly decreases
    dataset = [
        (jnp.asarray(rng.standard_normal(
            (global_batch, 28, 28, 1)).astype(np.float32)),
         jnp.asarray(rng.integers(0, 10, size=(global_batch,))))
        for _ in range(4)
    ]
    for i in range(args.steps):
        x, y = dataset[i % len(dataset)]
        params, opt_state, loss = step(params, opt_state, x, y)
    if hvd.rank() == 0:
        print(f"final loss: {float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
