"""Eager-API MNIST — the ``tensorflow_mnist_eager.py`` analog: no jit-SPMD
step; gradients are computed per process and averaged through the *eager*
named-tensor allreduce (the ``DistributedGradientTape`` pattern,
``tensorflow/__init__.py:252-326``). Each named gradient is submitted
async, the engine fuses whatever lands in the same cycle
(HOROVOD_CYCLE_TIME) into one buffer, and ``synchronize`` hands back the
world-averaged result — the reference's enqueue→negotiate→fuse→execute
pipeline end to end.

This is the parity path, not the performance path: for throughput use the
jit/shard_map route (``examples/jax_mnist.py``) where XLA owns the
collectives.

Run single-process: python examples/jax_mnist_eager.py
Run multi-process:  python -m horovod_tpu.runner -np 2 --host-data-plane \
                        python examples/jax_mnist_eager.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n: int, seed: int):
    # one labeling function shared by EVERY seed (class prototypes from a
    # fixed generator): ranks see different samples of the SAME task, so
    # the world-averaged gradient actually converges — a per-seed
    # labeling would hand each rank a conflicting task
    proto = np.random.default_rng(0).standard_normal(
        (10, 28, 28, 1)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    noise = rng.standard_normal((n, 28, 28, 1)).astype(np.float32)
    x = 0.1 * (proto[y] + noise)
    return jnp.asarray(x), jnp.asarray(y)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--compression", type=str, default="none",
                        help="gradient wire codec: none/fp16/bf16/int8/"
                             "fp8/topk (docs/compression.md; topk is the "
                             "sparse wire — HOROVOD_SPARSE_TOPK picks k, "
                             "HOROVOD_SPARSE_ERROR_FEEDBACK=0 ablates "
                             "the residual)")
    args = parser.parse_args()
    compression = hvd.Compression.lookup(args.compression)

    hvd.init()

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    opt = optax.sgd(args.lr * hvd.size(), momentum=0.9)
    opt_state = opt.init(params)

    # consistent start (reference step 6)
    params = hvd.broadcast_parameters(params, root_rank=0)

    @jax.jit
    def local_grads(params, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # each rank sees different data — the allreduce is what keeps replicas
    # identical
    x_all, y_all = synthetic_mnist(args.batch_size * args.steps,
                                   seed=1000 + hvd.rank())

    leaves, treedef = jax.tree_util.tree_flatten(params)
    names = [f"grad.{i}" for i in range(len(leaves))]

    for step in range(args.steps):
        lo = step * args.batch_size
        x, y = x_all[lo:lo + args.batch_size], y_all[lo:lo + args.batch_size]
        loss, grads = local_grads(params, x, y)

        # DistributedGradientTape: submit every named gradient async, let
        # the cycle fuse them, then synchronize in order. The sparse wire
        # needs step-stable names: its error-feedback residual is keyed by
        # tensor name, and a per-step suffix would orphan the carried mass
        # (safe here — every handle is synchronized before resubmission).
        sparse = getattr(compression, "sparse", False)
        grad_leaves = jax.tree_util.tree_leaves(grads)
        handles = [
            hvd.allreduce_async(np.asarray(g), average=True,
                                name=name if sparse else f"{name}.s{step}",
                                compression=compression)
            for name, g in zip(names, grad_leaves)
        ]
        averaged = [jnp.asarray(hvd.synchronize(h)) for h in handles]
        grads = jax.tree_util.tree_unflatten(treedef, averaged)

        params, opt_state = apply(params, opt_state, grads)
        if hvd.rank() == 0 and step % 10 == 0:
            print(f"step {step}: loss={float(loss):.4f}", flush=True)

    # deterministic final eval on this rank's training prefix (each seed
    # carries its OWN labeling function, so a fresh seed would measure an
    # unlearnable task): the machine-readable line the convergence-parity
    # certification (__graft_entry__.dryrun_sparse) compares across codecs
    final_loss, _ = local_grads(params, x_all[:256], y_all[:256])
    if hvd.rank() == 0:
        print(f"final_loss={float(final_loss):.6f}", flush=True)
        print("done", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
