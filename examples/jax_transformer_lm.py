"""Transformer LM training: data-parallel and sequence-parallel modes.

Beyond-parity example (the reference predates attention entirely, SURVEY
§5.7): one model (``horovod_tpu.models.TransformerLM``), three launch modes
on the same device mesh —

* ``--mode dp``      data-parallel batch sharding (the reference's product)
* ``--mode ring``    ring-attention sequence parallelism: the *sequence*
                     dimension is sharded; K/V blocks rotate over the axis
* ``--mode ulysses`` all_to_all head re-sharding sequence parallelism

Run:  python examples/jax_transformer_lm.py --mode ring --seq-len 512
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import TransformerLM, lm_loss

VOCAB = 128


def synthetic_text(n_seq: int, seq_len: int, seed: int):
    """Repeating n-gram structure so the LM has something to learn."""
    rng = np.random.default_rng(seed)
    base = np.tile(np.arange(16), (n_seq, seq_len // 16 + 1))[:, :seq_len]
    noise = rng.integers(0, 4, (n_seq, seq_len))
    return jnp.asarray(((base * 7 + noise) % VOCAB).astype(np.int32))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mode", default="dp",
                        choices=["dp", "ring", "ulysses"])
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-size", type=int, default=8,
                        help="global batch (dp shards it; sp replicates it)")
    parser.add_argument("--seq-len", type=int, default=256)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--remat", action="store_true",
                        help="jax.checkpoint each block: recompute "
                             "activations in backward (memory for FLOPs — "
                             "the lever for longer sequences per chip)")
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    axis = hvd.parallel.DATA_AXIS
    n_dev = hvd.num_devices()  # mesh spans ALL devices in the world
    seq_parallel = args.mode != "dp"
    if seq_parallel and args.seq_len % n_dev:
        raise SystemExit(f"--seq-len must divide by {n_dev} devices")
    if not seq_parallel and args.batch_size % n_dev:
        raise SystemExit(f"--batch-size must divide by {n_dev} devices")

    model = TransformerLM(
        vocab_size=VOCAB, num_layers=2, num_heads=8, d_model=128, d_ff=512,
        max_seq_len=args.seq_len, dtype=jnp.float32,
        attention={"dp": "dense", "ring": "ring",
                   "ulysses": "ulysses"}[args.mode],
        seq_axis=axis if seq_parallel else None, remat=args.remat)
    # dense twin for init: same structure/params, no axis requirement
    init_model = model.clone(attention="dense", seq_axis=None)
    tokens = synthetic_text(args.batch_size, args.seq_len,
                            seed=1000 + (0 if seq_parallel else hvd.rank()))
    variables = init_model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    variables = hvd.broadcast_parameters(variables, root_rank=0)

    opt = hvd.DistributedOptimizer(optax.adam(args.lr), axis_name=axis)
    opt_state = opt.init(variables)
    positions = jnp.broadcast_to(jnp.arange(args.seq_len), tokens.shape)

    def train_step(variables, opt_state, tokens, positions):
        # loss_fn stays LOCAL in both modes: dp shards the batch, sp shards
        # the sequence (each shard scores its next-token slice; the target
        # of a shard's last position lives on the next shard and is skipped
        # — a 1/seq_local margin). The DistributedOptimizer averages the
        # pre-summed replicated-param gradients over the axis, which IS the
        # gradient of the pmean'd global loss — adding a pmean inside
        # loss_fn would divide the gradients by the axis size twice.
        def loss_fn(v):
            return lm_loss(model.apply(v, tokens, positions), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        updates, opt_state = opt.update(grads, opt_state, variables)
        new_vars = optax.apply_updates(variables, updates)
        return new_vars, opt_state, jax.lax.pmean(loss, axis)

    data_spec = P(None, axis) if seq_parallel else P(axis)
    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P())))

    for i in range(args.steps):
        variables, opt_state, loss = step(variables, opt_state, tokens,
                                          positions)
        if hvd.rank() == 0 and (i % 10 == 0 or i == args.steps - 1):
            print(f"step {i}: loss={float(loss):.4f} mode={args.mode}")
    if hvd.rank() == 0:
        print("done")
    hvd.shutdown()


if __name__ == "__main__":
    main()
