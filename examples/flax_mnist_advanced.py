"""Callbacks-driven MNIST training — the ``keras_mnist_advanced.py`` analog
(reference ``examples/keras_mnist_advanced.py``): broadcast-at-start,
gradual LR warmup (Goyal et al.), per-epoch metric averaging across ranks,
and rank-0-only checkpointing, all expressed through the callback surface
(``hvd.callbacks``) that mirrors the reference's Keras callbacks.

The LR-mutating callbacks need the optimizer built with
``optax.inject_hyperparams`` so ``learning_rate`` is a mutable leaf of the
optimizer state — the analog of Keras's mutable ``optimizer.lr``.

Run single-host:   python examples/flax_mnist_advanced.py
Run multi-process: python -m horovod_tpu.runner -np 2 --host-data-plane \
                       python examples/flax_mnist_advanced.py
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
import horovod_tpu.core.jax_compat  # noqa: F401 - jax.shard_map shim on older JAX
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n: int, seed: int):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 28, 28, 1)).astype(np.float32) * 0.1
    w = rng.standard_normal((28 * 28, 10)).astype(np.float32)
    # learnable structure so accuracy visibly improves
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--base-lr", type=float, default=0.01)
    parser.add_argument("--warmup-epochs", type=int, default=2)
    parser.add_argument("--checkpoint-dir", default=None)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.parallel.data_parallel_mesh()
    n_dev = hvd.local_device_count()
    global_batch = args.batch_size * n_dev

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))

    # inject_hyperparams makes learning_rate a state leaf the LR callbacks
    # can poke between batches (keras_mnist_advanced sets optimizer.lr).
    opt = hvd.DistributedOptimizer(
        optax.inject_hyperparams(optax.sgd)(
            learning_rate=args.base_lr, momentum=0.9),
        axis_name="data")
    opt_state = opt.init(params)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, jnp.argmax(logits, -1)

        (loss, pred), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        acc = jnp.mean((pred == y).astype(jnp.float32))
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, "data"), jax.lax.pmean(acc, "data"))

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P())))

    x_all, y_all = synthetic_mnist(global_batch * 12, seed=1000 + hvd.rank())
    steps_per_epoch = x_all.shape[0] // global_batch

    state = hvd.callbacks.TrainLoop(params=params, opt_state=opt_state,
                                    learning_rate=args.base_lr)
    callbacks = hvd.callbacks.CallbackList([
        # keras_mnist_advanced callback stack, one-for-one:
        hvd.callbacks.BroadcastGlobalVariablesCallback(root_rank=0),
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=args.base_lr, warmup_epochs=args.warmup_epochs,
            steps_per_epoch=steps_per_epoch),
        hvd.callbacks.MetricAverageCallback(),
    ])

    callbacks.on_train_begin(state)
    for epoch in range(args.epochs):
        callbacks.on_epoch_begin(epoch, state)
        losses, accs = [], []
        for b in range(steps_per_epoch):
            callbacks.on_batch_begin(b, state)
            lo = b * global_batch
            x, y = x_all[lo:lo + global_batch], y_all[lo:lo + global_batch]
            state.params, state.opt_state, loss, acc = step(
                state.params, state.opt_state, x, y)
            losses.append(float(loss))
            accs.append(float(acc))
        logs = {"loss": float(np.mean(losses)),
                "accuracy": float(np.mean(accs))}
        callbacks.on_epoch_end(epoch, state, logs)  # world-averaged in place
        if hvd.rank() == 0:
            print(f"epoch {epoch}: lr={state.learning_rate:.4f} "
                  f"loss={logs['loss']:.4f} acc={logs['accuracy']:.3f}",
                  flush=True)
            if args.checkpoint_dir:
                # rank-0-only checkpointing (README Usage step 6)
                hvd.checkpoint.save(
                    os.path.join(args.checkpoint_dir, f"epoch{epoch}"),
                    {"params": state.params, "opt_state": state.opt_state})
    hvd.shutdown()


if __name__ == "__main__":
    main()
