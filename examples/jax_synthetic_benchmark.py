"""Synthetic throughput benchmark — the reference's
``examples/pytorch_synthetic_benchmark.py`` / ``tensorflow_synthetic_benchmark.py``
protocol. The canonical implementation lives at the repo root as
``bench.py`` (the driver-facing entry point); this example forwards to it so
the examples directory mirrors the reference layout.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import main  # noqa: E402

if __name__ == "__main__":
    main()
