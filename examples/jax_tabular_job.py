"""End-to-end tabular data job — the ``keras_spark_rossmann.py`` analog
(reference ``examples/keras_spark_rossmann.py``) without Spark: the
driver does the feature engineering (vocabulary building, continuous
normalization, log-target, train/val split — the roles of
``prepare_df``/``build_vocabulary``/``cast_columns`` there), ships a
train fn to N worker processes via ``hvd.runner.run`` (the
``horovod.spark.run`` contract), and each worker shards the prepared
rows by rank (``cur_shard=hvd.rank(), shard_count=hvd.size()`` — the
petastorm sharding of reference ``:451``), trains an embeddings+MLP
regressor with eagerly averaged gradients, LR warmup, per-epoch metric
averaging, and rank-0 checkpointing. The driver then restores the
checkpoint and writes a submission CSV from its predictions — the full
driver → distributed-train → driver round trip of the reference job.

The dataset is a synthetic store-sales table (store / day-of-week /
promo categoricals, distance / day-index continuous, multiplicative
sales structure) so the example is hermetic; the metric is RMSPE on
expm1'd predictions, the reference's ``exp_rmspe``.

Run: python examples/jax_tabular_job.py [--np 2] [--epochs 3]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CATEGORICALS = ("store", "dow", "promo")
CONTINUOUS = ("distance", "day_idx")


def make_sales_table(n_rows: int, seed: int = 0) -> dict:
    """Synthetic raw table with learnable multiplicative structure."""
    rng = np.random.default_rng(seed)
    store = rng.integers(0, 40, n_rows)
    dow = rng.integers(0, 7, n_rows)
    promo = rng.integers(0, 2, n_rows)
    distance = rng.lognormal(1.0, 0.5, n_rows).astype(np.float32)
    day_idx = rng.integers(0, 365, n_rows).astype(np.float32)
    store_eff = rng.lognormal(0.0, 0.3, 40)
    dow_eff = np.array([1.0, 0.9, 0.85, 0.9, 1.0, 1.3, 0.2])
    sales = (1000.0 * store_eff[store] * dow_eff[dow] *
             (1.0 + 0.25 * promo) / np.sqrt(1.0 + distance) *
             rng.lognormal(0.0, 0.1, n_rows)).astype(np.float32)
    return {"store": store, "dow": dow, "promo": promo,
            "distance": distance, "day_idx": day_idx, "sales": sales}


def prepare_features(table: dict) -> tuple:
    """Driver-side feature engineering: vocabularies for categoricals
    (``build_vocabulary``), standardization for continuous columns, and
    the log1p target transform (the reference trains on log sales)."""
    vocabs = {c: {v: i for i, v in enumerate(sorted(set(table[c])))}
              for c in CATEGORICALS}
    cats = np.stack([np.vectorize(vocabs[c].get)(table[c])
                     for c in CATEGORICALS], axis=1).astype(np.int32)
    cont_stats = {c: (float(table[c].mean()), float(table[c].std() + 1e-6))
                  for c in CONTINUOUS}
    conts = np.stack([(table[c] - cont_stats[c][0]) / cont_stats[c][1]
                      for c in CONTINUOUS], axis=1).astype(np.float32)
    target = np.log1p(table["sales"]).astype(np.float32)
    vocab_sizes = tuple(len(vocabs[c]) for c in CATEGORICALS)
    return cats, conts, target, vocab_sizes


def rmspe(pred_sales: np.ndarray, true_sales: np.ndarray) -> float:
    """Root mean squared percentage error on real (expm1'd) sales —
    the reference's ``exp_rmspe`` metric."""
    return float(np.sqrt(np.mean(
        ((true_sales - pred_sales) / true_sales) ** 2)))


def build_model(vocab_sizes: tuple):
    """Embeddings-per-categorical + MLP regressor (the reference's
    entity-embedding network shape). Defined at module scope so the
    worker (cloudpickled by value with the train fn) and the driver's
    prediction step share ONE definition."""
    import flax.linen as nn
    import jax.numpy as jnp

    class TabularNet(nn.Module):
        vocab_sizes: tuple

        @nn.compact
        def __call__(self, cats, conts):
            embeds = [nn.Embed(v, 8)(cats[:, i])
                      for i, v in enumerate(self.vocab_sizes)]
            x = jnp.concatenate(embeds + [conts], axis=1)
            x = nn.relu(nn.Dense(64)(x))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(1)(x)[:, 0]

    return TabularNet(vocab_sizes)


def train_fn(cats, conts, target, vocab_sizes, ckpt_dir, epochs,
             batch_size, base_lr):
    """Runs on every rank under ``hvd.runner.run`` (cloudpickled by
    value, like reference user fns under ``horovod.spark.run``)."""
    import os

    platform = os.environ.get("EXAMPLE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # Shard rows by rank — the petastorm cur_shard/shard_count contract.
    cats, conts, target = (a[rank::size] for a in (cats, conts, target))

    model = build_model(vocab_sizes)
    params = model.init(jax.random.PRNGKey(0), cats[:2], conts[:2])
    # LR scaled by world size with warmup from base_lr, the reference's
    # LearningRateWarmupCallback schedule expressed as an optax schedule.
    steps_per_epoch = max(1, len(target) // batch_size)
    schedule = hvd.callbacks.warmup_schedule(
        base_lr, steps_per_epoch, warmup_epochs=1, target_scale=float(size))
    opt = optax.adam(schedule)
    opt_state = opt.init(params)
    # rank-0-consistent start (BroadcastGlobalVariablesCallback)
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    @jax.jit
    def local_grads(params, bc, bx, by):
        def loss_fn(p):
            pred = model.apply(p, bc, bx)
            return jnp.mean((pred - by) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    @jax.jit
    def apply(params, opt_state, grads):
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    for epoch in range(epochs):
        perm = np.random.default_rng(epoch).permutation(len(target))
        losses = []
        for b in range(steps_per_epoch):
            idx = perm[b * batch_size:(b + 1) * batch_size]
            loss, grads = local_grads(params, cats[idx], conts[idx],
                                      target[idx])
            # eager world-averaged gradients (DistributedGradientTape)
            grads = hvd.allreduce_gradients(grads)
            params, opt_state = apply(params, opt_state, grads)
            losses.append(float(loss))
        # per-epoch metric averaging (MetricAverageCallback)
        mean_loss = float(np.asarray(hvd.allreduce(
            np.float32(np.mean(losses)), average=True,
            name=f"job.loss.{epoch}")))
        if rank == 0:
            print(f"epoch {epoch}: world loss {mean_loss:.4f}", flush=True)

    if rank == 0:  # rank-0 checkpoint convention
        hvd.checkpoint.save(os.path.join(ckpt_dir, "model"), params)
    pred = np.asarray(model.apply(params, cats, conts))
    shard_rmspe = rmspe(np.expm1(pred), np.expm1(np.asarray(target)))
    hvd.shutdown()
    return {"rank": rank, "rmspe": shard_rmspe, "loss": mean_loss}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--np", type=int, default=2)
    parser.add_argument("--rows", type=int, default=4096)
    parser.add_argument(
        "--epochs", type=lambda s: max(1, int(s)), default=3)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--base-lr", type=float, default=1e-3)
    parser.add_argument("--output", default=None,
                        help="output DIRECTORY for the checkpoint and "
                             "submission.csv (default: a fresh temp dir)")
    args = parser.parse_args()

    # 1. driver: raw data + feature engineering
    table = make_sales_table(args.rows)
    cats, conts, target, vocab_sizes = prepare_features(table)
    out_dir = args.output or tempfile.mkdtemp(prefix="tabular_job_")
    os.makedirs(out_dir, exist_ok=True)
    ckpt_dir = os.path.join(out_dir, "ckpt")

    # 2. distributed training (one process per rank, real TCP world)
    import horovod_tpu.runner as runner

    results = runner.run(
        train_fn,
        args=(cats, conts, target, vocab_sizes, ckpt_dir, args.epochs,
              args.batch_size, args.base_lr),
        np=args.np, timeout_s=600.0)
    print("per-rank results:", results)

    # 3. driver: restore the rank-0 checkpoint and write the submission
    platform = os.environ.get("EXAMPLE_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import horovod_tpu as hvd

    hvd.init()  # driver-side size-1 world: restore broadcasts post-load
    params = hvd.checkpoint.restore(os.path.join(ckpt_dir, "model"))
    # driver-side prediction with the restored params (deserialize_model
    # + df.withColumn(predict) in the reference)
    pred_sales = np.expm1(np.asarray(
        build_model(vocab_sizes).apply(params, cats, conts)))
    score = rmspe(pred_sales, table["sales"])
    csv_path = os.path.join(out_dir, "submission.csv")
    with open(csv_path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["Id", "Sales"])
        writer.writerows((i, f"{s:.2f}") for i, s in enumerate(pred_sales))
    print(f"submission written: {csv_path} (RMSPE {score:.3f})")
    # the model must have learned the multiplicative structure: a naive
    # predict-the-mean baseline scores ~1.0+ on this table
    baseline = rmspe(np.full_like(table["sales"], table["sales"].mean()),
                     table["sales"])
    assert score < baseline, (score, baseline)
    hvd.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
