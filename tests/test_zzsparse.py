"""Sparse top-k gradient wire tests (docs/compression.md §sparse).

Named past the 870 s tier-1 truncation point (ROADMAP note); the
``sparse`` marker runs just this battery. Covers: the wire-format
helpers (deterministic selection, pack/unpack, clipped scatter), the
error-feedback residual lifecycle on the live engine (persists across
steps, drains once the gradient stops, resets on an elastic epoch
bump, and demonstrably differs with feedback disabled), the multi-axis
``allreduce_sparse`` average fix, the evidence gate's per-codec
coverage floor, the in-jit SPMD twin, the 2-proc decode/dense-fallback
acceptance, and the chaos matrix's sparse flipbits cell (consensus
digesting the decoded DENSE result names the injected rank).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu.ops import sparse_wire
from horovod_tpu.ops.compression import Compression, TopKCompressor

pytestmark = pytest.mark.sparse


@pytest.fixture(autouse=True)
def _default_fraction():
    saved = TopKCompressor.FRACTION_KEY
    TopKCompressor.FRACTION_KEY = "1"
    yield
    TopKCompressor.FRACTION_KEY = saved


# -- wire-format helpers -------------------------------------------------------


def test_topk_select_deterministic_tie_break():
    # four-way magnitude tie: ascending-index order wins, every time
    x = np.array([2.0, -2.0, 2.0, -2.0, 1.0], np.float32)
    idx, vals = sparse_wire.topk_select(x, 3)
    assert idx.tolist() == [0, 1, 2]
    assert vals.tolist() == [2.0, -2.0, 2.0]
    assert idx.dtype == np.int32 and vals.dtype == np.float32


def test_pack_unpack_roundtrip_rank_major():
    i0, v0 = np.array([3, 1], np.int32), np.array([1.5, -2.0], np.float32)
    i1, v1 = np.array([0, 3], np.int32), np.array([4.0, 8.0], np.float32)
    combined = sparse_wire.pack_pairs(i0, v0) + sparse_wire.pack_pairs(i1, v1)
    g_idx, g_vals = sparse_wire.unpack_wire(combined, 2)
    assert g_idx.tolist() == [3, 1, 0, 3]
    assert g_vals.tolist() == [1.5, -2.0, 4.0, 8.0]


def test_unpack_wire_rejects_malformed_payload():
    with pytest.raises(ValueError):
        sparse_wire.unpack_wire(b"\x00" * 12, 1)  # not a whole pair set
    with pytest.raises(ValueError):
        sparse_wire.unpack_wire(b"\x00" * 16, 3)  # not divisible by ranks


def test_scatter_sum_clips_corrupt_index_instead_of_raising():
    idx = np.array([0, 99], np.int32)  # 99 is out of range for n=4
    vals = np.array([1.0, 2.0], np.float32)
    out = sparse_wire.scatter_sum(idx, vals, 4)
    # the corrupt index lands on the clipped edge row — a DIVERGENT
    # decode (consensus's job), never an asymmetric raise
    assert out.tolist() == [1.0, 0.0, 0.0, 2.0]


def test_decode_sum_duplicate_indices_accumulate():
    i0, v0 = np.array([1], np.int32), np.array([2.0], np.float32)
    i1, v1 = np.array([1], np.int32), np.array([3.0], np.float32)
    combined = sparse_wire.pack_pairs(i0, v0) + sparse_wire.pack_pairs(i1, v1)
    out = sparse_wire.decode_sum(combined, 3, 2)
    assert out.tolist() == [0.0, 5.0, 0.0]


def test_select_with_feedback_residual_contract():
    x = np.array([5.0, 1.0, -3.0, 0.5], np.float32)
    res = np.array([0.0, 4.0, 0.0, 0.0], np.float32)
    idx, vals, new_res = sparse_wire.select_with_feedback(x, res, 2)
    # corrected = [5, 5, -3, .5]: top-2 by |.| is the 5s (tie -> low idx)
    assert idx.tolist() == [0, 1]
    assert vals.tolist() == [5.0, 5.0]
    assert new_res.tolist() == [0.0, 0.0, -3.0, 0.5]
    idx2, vals2, none_res = sparse_wire.select_with_feedback(
        x, res, 2, error_feedback=False)
    assert none_res is None
    # feedback off ignores the carried residual: raw top-2 of x
    assert idx2.tolist() == [0, 2]
    assert vals2.tolist() == [5.0, -3.0]


# -- codec math ----------------------------------------------------------------


def test_k_of_fractions_exact_and_never_zero():
    assert TopKCompressor.k_of(1000, "0.1") == 1
    assert TopKCompressor.k_of(1000, "1") == 10
    assert TopKCompressor.k_of(1000, "10") == 100
    assert TopKCompressor.k_of(3, "0.1") == 1  # never 0
    assert TopKCompressor.k_of(0, "1") == 0


def test_set_fraction_key_rejects_unknown_loudly():
    with pytest.raises(ValueError, match="HOROVOD_SPARSE_TOPK"):
        TopKCompressor.set_fraction_key("2.5")


def test_wire_cost_reduction_at_least_8x_at_one_percent():
    TopKCompressor.set_fraction_key("1")
    n = 1 << 20
    pre, post = TopKCompressor.wire_cost(n, 4)
    assert pre == n * 4
    assert post == TopKCompressor.k_of(n) * 8
    assert pre / post >= 8.0  # the acceptance floor (actual: 50x)


def test_roundtrip_error_is_dropped_energy():
    rng = np.random.RandomState(0)
    x = rng.randn(500).astype(np.float32)
    sig, err = TopKCompressor.roundtrip_error(x, 4)
    k = TopKCompressor.k_of(500)
    order = np.sort(np.abs(x).astype(np.float64) ** 2)[::-1]
    assert sig == pytest.approx(float(order.sum()), rel=1e-6)
    assert err == pytest.approx(float(order[k:].sum()), rel=1e-6)


def test_coverage_floor_db_mapping():
    from horovod_tpu.obs import tensorwatch as tw

    # 95% coverage = -10*log10(0.05) ~= 13.01 dB selection SNR
    assert tw.coverage_floor_db(0.95) == pytest.approx(13.0103, abs=1e-3)
    assert tw.coverage_floor_db(0.99) > tw.coverage_floor_db(0.9)
    assert tw.coverage_floor_db(1.0) == tw.snr_db(1.0, 0.0)  # lossless cap


def test_evidence_gate_per_codec_floor():
    from horovod_tpu.obs import tensorwatch as tw

    gate = tw.EvidenceGate(floor_db=20.0, window=2)
    gate.set_floor("topk", tw.coverage_floor_db(0.95))
    assert gate.floor_for("topk") == pytest.approx(13.0103, abs=1e-3)
    assert gate.floor_for("int8") == 20.0
    # 15 dB certifies topk (above ITS floor) but not int8
    for _ in range(2):
        gate.observe("topk", 15.0)
        gate.observe("int8", 15.0)
    assert gate.allows("topk") and not gate.allows("int8")
    assert gate.evidence_record("topk")["floor_db"] == \
        pytest.approx(13.0103, abs=1e-3)
    # in-flight collapse below the coverage floor latches the revert
    gate.observe("topk", 5.0)
    assert not gate.allows("topk") and gate.take_collapse("topk")


def test_tune_codec_ids_include_topk():
    from horovod_tpu.tune.policy import CODEC_IDS

    assert CODEC_IDS["topk"] == 3


# -- multi-axis allreduce_sparse average (satellite fix) -----------------------


def test_sparse_allreduce_spmd_multi_axis_average(hvd):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dcn", "ici"))
    values = jnp.ones((8, 1, 2), dtype=jnp.float32)
    indices = jnp.ones((8, 1), dtype=jnp.int32)

    def step(v, i):
        s = hvd.allreduce_sparse(
            hvd.IndexedSlices(i[0], v[0], (4, 2)), average=True,
            axis_name=("dcn", "ici"))
        return s.to_dense()[None]

    out = jax.jit(shard_map(step, mesh=mesh,
                            in_specs=(P(("dcn", "ici")), P(("dcn", "ici"))),
                            out_specs=P(("dcn", "ici"))))(values, indices)
    for shard in np.asarray(out):
        # 8 contributions averaged over BOTH axes (2*4): exactly 1.0 —
        # the single-axis divide bug yielded 4.0 here
        np.testing.assert_array_equal(shard[1], 1.0)
        np.testing.assert_array_equal(shard[0], 0.0)


# -- in-jit SPMD twin ----------------------------------------------------------


def test_spmd_sparse_allreduce_mesh(hvd):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.ops import spmd

    n_dev, n = 8, 400
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    rng = np.random.RandomState(1)
    # concentrated rows: top-1% holds nearly all energy per rank
    xs = 1e-3 * rng.randn(n_dev, n).astype(np.float32)
    hot = rng.randint(0, n, size=(n_dev, 4))
    for d in range(n_dev):
        xs[d, hot[d]] = 10.0 + np.arange(4, dtype=np.float32)

    def step(v):
        return spmd.sparse_allreduce(v, "data", average=True,
                                     codec=TopKCompressor)

    out = np.asarray(jax.jit(shard_map(
        step, mesh=mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False))(jnp.asarray(xs.reshape(-1))))
    # reference: per-rank top-k kept exactly, mean over ranks
    k = TopKCompressor.k_of(n)
    want = np.zeros(n, np.float64)
    for d in range(n_dev):
        keep = np.argsort(-np.abs(xs[d]), kind="stable")[:k]
        want[keep] += xs[d][keep].astype(np.float64)
    want /= n_dev
    np.testing.assert_allclose(out, want.astype(np.float32), atol=1e-6)


def test_spmd_sparse_allreduce_threads_residual(hvd):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.ops import spmd

    n = 160
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    x = jnp.asarray(np.ones(8 * n, np.float32))
    res0 = jnp.asarray(np.zeros(8 * n, np.float32))

    def step(v, r):
        out, new_r = spmd.sparse_allreduce(v, "data", average=False,
                                           codec=TopKCompressor,
                                           residual=r)
        return out[None], new_r

    out, new_r = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False))(x, res0)
    k = TopKCompressor.k_of(n)
    new_r = np.asarray(new_r).reshape(8, n)
    # every rank banked exactly n-k dropped ones in its residual shard
    for d in range(8):
        assert int((new_r[d] == 1.0).sum()) == n - k
        assert int((new_r[d] == 0.0).sum()) == k


# -- engine residual lifecycle (host path, world of one) -----------------------


def test_engine_residual_persists_and_drains_after_gradient_stops(hvd):
    from horovod_tpu.ops.engine import get_engine

    n = 32  # k_of(32) at 1% = 1: one entry ships per step
    g0 = np.arange(1, n + 1, dtype=np.float32)
    out0 = np.asarray(hvd.allreduce(g0, average=False, name="sp.drain",
                                    compression=Compression.topk))
    assert np.count_nonzero(out0) == 1 and out0[n - 1] == float(n)
    eng = get_engine()
    res = eng._sparse_residuals["sp.drain"]
    assert float(np.linalg.norm(res)) > 0  # persisted across the call
    # gradient stops: every subsequent step drains the largest banked
    # entry until the residual is exactly zero
    delivered = [out0.copy()]
    for _ in range(n - 1):
        delivered.append(np.asarray(hvd.allreduce(
            np.zeros(n, np.float32), average=False, name="sp.drain",
            compression=Compression.topk)))
    total = np.sum(delivered, axis=0)
    np.testing.assert_array_equal(total, g0)  # nothing lost, ever
    assert float(np.linalg.norm(
        eng._sparse_residuals["sp.drain"])) == 0.0


def test_engine_error_feedback_disabled_loses_dropped_mass():
    import horovod_tpu as hvd_mod
    from horovod_tpu.core.config import HOROVOD_SPARSE_ERROR_FEEDBACK
    from horovod_tpu.ops.engine import get_engine

    saved = os.environ.get(HOROVOD_SPARSE_ERROR_FEEDBACK)
    os.environ[HOROVOD_SPARSE_ERROR_FEEDBACK] = "0"
    try:
        hvd_mod.init()
        g = np.arange(1, 33, dtype=np.float32)
        outs = [np.asarray(hvd_mod.allreduce(
            g, average=False, name="sp.noef",
            compression=Compression.topk)) for _ in range(3)]
        # no residual: the SAME top-1 entry ships every step, the rest
        # of the mass is dropped on the floor each time
        for out in outs:
            assert np.count_nonzero(out) == 1 and out[31] == 32.0
        assert get_engine()._sparse_residuals == {}
        hvd_mod.shutdown()
    finally:
        if saved is None:
            os.environ.pop(HOROVOD_SPARSE_ERROR_FEEDBACK, None)
        else:
            os.environ[HOROVOD_SPARSE_ERROR_FEEDBACK] = saved


def test_engine_residual_resets_on_elastic_epoch_bump(hvd):
    from horovod_tpu.core.config import HOROVOD_ELASTIC_EPOCH
    from horovod_tpu.ops.engine import get_engine

    g = np.arange(1, 33, dtype=np.float32)
    hvd.allreduce(g, average=False, name="sp.epoch",
                  compression=Compression.topk)
    eng = get_engine()
    assert "sp.epoch" in eng._sparse_residuals
    saved = os.environ.get(HOROVOD_ELASTIC_EPOCH)
    os.environ[HOROVOD_ELASTIC_EPOCH] = "7"
    try:
        # the relaunched world restarted from committed state: replaying
        # pre-relaunch residuals would double-count their mass
        out = np.asarray(hvd.allreduce(g, average=False, name="sp.epoch",
                                       compression=Compression.topk))
        assert out[31] == 32.0  # fresh selection, no carried residual
        assert set(eng._sparse_residuals) == {"sp.epoch"}
        assert eng._sparse_epoch == 7
    finally:
        if saved is None:
            os.environ.pop(HOROVOD_ELASTIC_EPOCH, None)
        else:
            os.environ[HOROVOD_ELASTIC_EPOCH] = saved


def test_engine_non_f32_batch_degrades_to_dense(hvd):
    # the sparse wire's value block is f32 by layout: an int32 batch
    # reduces dense at full precision (warned once), bit-exactly
    x = np.arange(16, dtype=np.int32)
    out = np.asarray(hvd.allreduce(x, average=False, name="sp.int",
                                   compression=Compression.topk))
    np.testing.assert_array_equal(out, x)
    from horovod_tpu.ops.engine import get_engine

    assert get_engine()._sparse_residuals == {}


def test_sparse_metric_families_and_summary_section(hvd, tmp_path):
    from horovod_tpu.obs.registry import registry

    hvd.allreduce(np.arange(64, dtype=np.float32), average=False,
                  name="sp.metrics", compression=Compression.topk)
    snap = registry().snapshot()
    for fam in ("horovod_sparse_selected_total",
                "horovod_sparse_dropped_total",
                "horovod_sparse_residual_norm",
                "horovod_sparse_wire_bytes_total"):
        assert fam in snap, sorted(snap)
    total = sum(s["value"] for s in
                snap["horovod_sparse_wire_bytes_total"]["samples"])
    assert total > 0
    # the summary tool renders the families as their own section
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(snap))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                      "metrics_summary.py"), str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "sparse wire" in proc.stdout
    assert "horovod_sparse_residual_norm" in proc.stdout


# -- 2-proc acceptance ---------------------------------------------------------


def _sparse_world_fn(steps):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    n = 64
    sparse_outs, dense_outs = [], []
    for step in range(steps):
        g = np.zeros(n, np.float32)
        g[(rank + step) % n] = float(rank + step + 1)  # concentrated
        sparse_outs.append(np.asarray(hvd.allreduce(
            g, average=False, name="sp.mp",
            compression=hvd.Compression.topk)).tolist())
        # codec off in the same world: the dense wire must stay bit-exact
        dense_outs.append(np.asarray(hvd.allreduce(
            np.full((n,), float(rank + step + 1), np.float32),
            average=False, name="sp.mp.dense")).tolist())
    res = get_engine()._sparse_residuals
    res_norm = float(sum(np.linalg.norm(r) for r in res.values()))
    hvd.shutdown()
    return {"rank": rank, "size": size, "sparse": sparse_outs,
            "dense": dense_outs, "residual_norm": res_norm}


def test_mp_2proc_sparse_decodes_to_dense_sum_and_dense_fallback():
    from horovod_tpu.runner import run

    steps = 4
    pins = {"HOROVOD_PLATFORM": "cpu", "HOROVOD_CYCLE_TIME": "2",
            "HOROVOD_NATIVE_CONTROLLER": "0", "HOROVOD_SPARSE_TOPK": "1"}
    saved = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        results = run(_sparse_world_fn, args=(steps,), np=2,
                      timeout_s=180.0, start_timeout_s=120.0)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    by_rank = {r["rank"]: r for r in results}
    # every rank decoded the identical dense result (the consensus
    # invariant), and single-spike contributions are fully covered by
    # k=1: the decode IS the exact dense sum here
    assert by_rank[0]["sparse"] == by_rank[1]["sparse"]
    for step in range(steps):
        want = np.zeros(64, np.float32)
        for rank in range(2):
            want[(rank + step) % 64] += float(rank + step + 1)
        np.testing.assert_array_equal(
            np.asarray(by_rank[0]["sparse"][step], np.float32), want)
        # full coverage -> zero dropped mass -> zero residual
    assert by_rank[0]["residual_norm"] == 0.0
    # codec-off traffic in the same world stayed bit-exact dense
    for step in range(steps):
        clean = float(sum(r + step + 1 for r in range(2)))
        for rank in range(2):
            np.testing.assert_array_equal(
                np.asarray(by_rank[rank]["dense"][step], np.float32),
                clean)


@pytest.mark.slow
def test_convergence_parity_error_feedback_is_load_bearing(tmp_path):
    """examples/jax_mnist_eager.py at k=1%: sparse+EF lands within 1% of
    the dense final loss; the EF-ablated arm demonstrably does not —
    the residual is what makes the sparse wire a training-grade codec,
    not just a bandwidth trick."""

    def arm(codec, error_feedback):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   HOROVOD_PLATFORM="cpu", HOROVOD_CYCLE_TIME="2",
                   HOROVOD_SPARSE_TOPK="1",
                   HOROVOD_SPARSE_ERROR_FEEDBACK=error_feedback)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), os.pardir,
                          "examples", "jax_mnist_eager.py"),
             "--steps", "140", "--compression", codec],
            capture_output=True, text=True, timeout=420, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("final_loss=")]
        assert line, proc.stdout
        return float(line[0].split("=")[1])

    dense = arm("none", "1")
    with_ef = arm("topk", "1")
    without_ef = arm("topk", "0")
    # within 1% of the dense final loss (measured: EF lands BELOW dense)
    assert with_ef <= dense * 1.01 + 1e-6, (dense, with_ef)
    # the ablation is demonstrably outside it (measured: ~30x dense)
    assert without_ef > dense * 1.01 + 1e-6, (dense, without_ef)
    assert without_ef > with_ef * 5, (with_ef, without_ef)


def test_mp_sparse_flipbits_consensus_names_injected_rank():
    from horovod_tpu.chaos.matrix import DATA_GRID, run_data_cell

    spec, policy, consensus, expect, codec = DATA_GRID[5]
    assert codec == "topk", DATA_GRID[5]
    cell = run_data_cell(spec, policy, consensus, expect, codec=codec)
    assert cell["outcome"] == "escalated", cell
    named = [r for r in cell.get("results", [])
             if r.get("error_type") == "ConsensusError"]
    assert named, cell
    # consensus digests the decoded DENSE result, so the flipped index
    # stream is attributable: rank 1 is named on every surviving rank
    assert all(r["consensus_ranks"] == [1] for r in named), cell
