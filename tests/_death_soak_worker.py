"""Failure-injection churn: a victim dies at a RANDOMIZED point in a
randomized collective stream; survivors must surface SHUT_DOWN_ERROR
within a bound, every time.

The single-shot peer_death scenario (tests/_mp_worker.py) pins one
timing; this worker is run many times by test_soak.py with different
HOROVOD_TEST_KILL_CYCLE values so the death lands during negotiation,
payload exchange, or idle — wherever the seed puts it. Victim exits 7;
survivors exit 0 after ASSERTING the error semantics (so the harness
distinguishes 'survived correctly' from 'hung/crashed')."""
import os
import sys
import time

os.environ.pop("JAX_PLATFORMS", None)
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import horovod_tpu as hvd

rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
kill_cycle = int(os.environ["HOROVOD_TEST_KILL_CYCLE"])
seed = int(os.environ.get("HOROVOD_TEST_SEED", "7"))
victim = size - 1

hvd.init()
rng = np.random.default_rng(seed)
# formed-world barrier: a death during init is a different failure class
hvd.allreduce(np.ones((2,), np.float32), average=False, name="ds.barrier")

t0 = time.monotonic()
try:
    for cyc in range(10_000):
        if rank == victim and cyc == kill_cycle:
            # die with tensors possibly in flight - a real crash: no
            # shutdown message, no atexit
            os._exit(7)
        handles = []
        for i in range(int(rng.integers(1, 6))):
            shape = (int(rng.integers(1, 100)),)
            handles.append(hvd.allreduce_async(
                np.full(shape, float(rank), np.float32), average=False,
                name=f"ds.{cyc}.{i}"))
        for h in handles:
            hvd.synchronize(h)
except RuntimeError as exc:
    # HorovodInternalError via synchronize, OR the engine's plain
    # RuntimeError(SHUT_DOWN_ERROR) when the randomized kill point lands
    # an enqueue after the background loop already stopped - both are the
    # correct reference semantics (HorovodInternalError is a RuntimeError)
    assert "shut down" in str(exc), exc
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"unblocked only after {elapsed:.1f}s"
    print(f"DSOAK-OK rank {rank} (peer death surfaced cleanly)",
          flush=True)
    os._exit(0)
raise AssertionError("victim never died or survivors never noticed")
