"""Soak worker: randomized eager collectives, correctness-checked.

Driven by test_soak.py; duration via SOAK_S (seconds)."""
import os, sys, time
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd

DURATION_S = float(os.environ.get("SOAK_S", "900"))
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
rng = np.random.default_rng(1234)  # same stream on every rank
t_end = time.time() + DURATION_S
round_no = 0
ops_done = 0
while True:
    hvd.init()
    # agreed stop: rank 0's clock decides, broadcast through the product
    # itself - per-rank clock checks would let a fast rank exit for good
    # while a slow rank re-inits into a world that can never form
    cont = np.asarray(hvd.broadcast(
        np.array([time.time() < t_end], np.int32), root_rank=0,
        name=f"soak.cont.{round_no}"))
    if not bool(cont[0]):
        hvd.shutdown()
        break
    # several cycles of mixed traffic per init epoch
    for cyc in range(30):
        n_tensors = int(rng.integers(1, 12))
        handles = []
        checks = []
        for i in range(n_tensors):
            kind = int(rng.integers(0, 3))
            dt = [np.float32, np.float64, np.int32][int(rng.integers(0, 3))]
            shape = tuple(int(s) for s in rng.integers(1, 40, size=int(rng.integers(1, 3))))
            name = f"soak.{round_no}.{cyc}.{i}"
            base = np.arange(np.prod(shape), dtype=dt).reshape(shape)
            if kind == 0:
                arr = base + rank
                h = hvd.allreduce_async(arr, average=False, name=name)
                want = base * size + sum(range(size))
                checks.append(("ar", h, want))
            elif kind == 1 and dt != np.float64:
                rows = rank + 1
                g = np.full((rows,) + shape, float(rank), dtype=np.float32)
                h = hvd.allgather_async(g, name=name)
                want = np.concatenate([np.full((r + 1,) + shape, float(r), np.float32)
                                       for r in range(size)])
                checks.append(("ag", h, want))
            else:
                root = int(rng.integers(0, size))
                b = base + (rank * 7)
                h = hvd.broadcast_async(b, root_rank=root, name=name)
                want = base + root * 7
                checks.append(("bc", h, want))
        for kind, h, want in checks:
            out = hvd.synchronize(h)
            np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6,
                err_msg=f"{kind} mismatch rank {rank} round {round_no}")
            ops_done += 1
    hvd.shutdown()
    round_no += 1
print(f"SOAK-OK rank {rank} rounds={round_no} ops={ops_done}", flush=True)
os._exit(0)
