"""Device-plane soak: one long world, randomized mixed numpy/jax traffic.

Targets the round-3 finalizer/completion machinery: async dispatch,
union waits, launch-order compatibility between host-fed and
device-resident ranks. Same rng stream on every rank => identical
submission sets; per-rank values differ so correctness is checkable."""
import os, sys, time
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
coord = os.environ["HOROVOD_TEST_JAX_COORD"]
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(coord,
                           num_processes=int(os.environ["HOROVOD_SIZE"]),
                           process_id=int(os.environ["HOROVOD_RANK"]))
import numpy as np
import jax.numpy as jnp
import horovod_tpu as hvd

DURATION_S = float(os.environ.get("SOAK_S", "300"))
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
hvd.init()
rng = np.random.default_rng(99)
t_end = time.time() + DURATION_S
ops_done = 0
cyc = 0
while True:
    # agreed stop: rank 0's clock decides, broadcast through the product
    # itself - per-rank `time.time() < t_end` checks would let a fast
    # rank shut down while a slow one submits one more cycle (the
    # documented finished-rank SHUT_DOWN_ERROR, not a soak failure)
    cont = np.asarray(hvd.broadcast(
        np.array([time.time() < t_end], np.int32), root_rank=0,
        name=f"xsoak.cont.{cyc}"))
    if not bool(cont[0]):
        break
    n_tensors = int(rng.integers(1, 10))
    checks = []
    for i in range(n_tensors):
        kind = int(rng.integers(0, 3))
        # device-resident (jax) or host-fed (numpy) submission: ranks may
        # DISAGREE per tensor (launch-order compatibility contract)
        as_jax = bool(rng.integers(0, 2) ^ (rank % 2 and i % 3 == 0))
        shape = tuple(int(s) for s in rng.integers(1, 64, size=int(rng.integers(1, 3))))
        name = f"xsoak.{cyc}.{i}"
        base = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
        if kind == 0:
            arr = base + rank
            sub = jnp.asarray(arr) if as_jax else arr
            h = hvd.allreduce_async(sub, average=False, name=name)
            checks.append((h, base * size + sum(range(size))))
        elif kind == 1:
            rows = rank + 1
            g = np.full((rows,) + shape, float(rank), np.float32)
            sub = jnp.asarray(g) if as_jax else g
            h = hvd.allgather_async(sub, name=name)
            checks.append((h, np.concatenate(
                [np.full((r + 1,) + shape, float(r), np.float32)
                 for r in range(size)])))
        else:
            root = int(rng.integers(0, size))
            b = base + rank * 3
            sub = jnp.asarray(b) if as_jax else b
            h = hvd.broadcast_async(sub, root_rank=root, name=name)
            checks.append((h, base + root * 3))
    for h, want in checks:
        out = hvd.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)
        ops_done += 1
    cyc += 1
hvd.shutdown()
print(f"XSOAK-OK rank {rank} cycles={cyc} ops={ops_done}", flush=True)
jax.distributed.shutdown()
os._exit(0)
