"""Data-plane integrity plane (docs/integrity.md).

Unit tier: sentry policy semantics and loud validation, the verdict-bit
wire helpers, data-plane chaos grammar/determinism, the consensus
accumulator/judge (authority and majority paths, state items), and the
SPMD in-program guard on the virtual 8-device mesh. Multi-process tier:
the collective-verdict contract (identical skip decision on the
identical step ordinal on every rank, bit-exact final state), the
flipbits→ConsensusError escalation naming the outlier, and the clean
world's zero-false-positive claim.

Named to sort PAST test_tune.py — the 870 s tier-1 budget truncates the
suite alphabetically (ROADMAP operational note), so the multi-process
cells here cost tier-1 nothing; run the battery with ``-m integrity``.
"""

import numpy as np
import pytest

from horovod_tpu.chaos import ChaosInjector, ChaosSpecError, parse_chaos_spec
from horovod_tpu.integrity import (
    ConsensusAuthority,
    ConsensusJudge,
    DigestAccumulator,
    GradSentry,
    tree_digest,
)
from horovod_tpu.integrity.sentry import or_bits, pack_bits, unpack_bits

pytestmark = pytest.mark.integrity


# -- sentry units -------------------------------------------------------------

def test_sentry_policy_validation_is_loud():
    with pytest.raises(ValueError, match="HOROVOD_GRAD_SENTRY"):
        GradSentry("skipp")


def test_sentry_skip_zeroes_whole_batch():
    s = GradSentry("skip")
    out = s.screen_batch(
        ["a", "b"], [np.array([1.0, np.nan]), np.array([2.0, 3.0])])
    assert all((np.asarray(r) == 0).all() for r in out)
    assert s.trips == [(1, "skip", "nan")]


def test_sentry_zero_nulls_only_bad_tensors():
    s = GradSentry("zero")
    out = s.screen_batch(
        ["a", "b"], [np.array([np.inf]), np.array([2.0, 3.0])])
    assert (np.asarray(out[0]) == 0).all()
    np.testing.assert_array_equal(out[1], [2.0, 3.0])
    assert s.trips == [(1, "zero", "inf")]


def test_sentry_warn_hands_values_through():
    s = GradSentry("warn")
    bad = np.array([np.nan, 1.0])
    out = s.screen_batch(["a"], [bad])
    assert out[0] is bad
    assert s.trips == [(1, "warn", "nan")]


def test_sentry_abort_raises_structured_error():
    from horovod_tpu.core.status import NonFiniteGradError

    s = GradSentry("abort")
    s.screen_batch(["a"], [np.ones(2)])  # clean batch: no trip
    with pytest.raises(NonFiniteGradError) as exc:
        s.screen_batch(["a"], [np.array([np.nan])])
    assert exc.value.step == 2
    assert exc.value.tensor_names == ["a"]


def test_sentry_clean_batches_trip_nothing():
    s = GradSentry("skip")
    for i in range(5):
        out = s.screen_batch(["g"], [np.full(4, float(i))])
        np.testing.assert_array_equal(out[0], np.full(4, float(i)))
    assert s.trips == [] and s.ordinal == 5


def test_sentry_integer_batches_are_finite_by_construction():
    s = GradSentry("abort")
    out = s.screen_batch(["i"], [np.array([1, 2], np.int32)])
    np.testing.assert_array_equal(out[0], [1, 2])
    assert s.trips == []


def test_sentry_collective_verdict_overrides_clean_local_view():
    """The collectivity contract in miniature: a rank whose LOCAL copy is
    clean must still apply the policy when the exchanged verdict says a
    peer saw the tensor bad — that is exactly the desync the one-element
    exchange exists to prevent."""
    def peer_saw_bad(ordinal, bits):
        return or_bits([bits, pack_bits([True])])

    s = GradSentry("skip", exchange=peer_saw_bad)
    out = s.screen_batch(["g"], [np.ones(4)])
    assert (np.asarray(out[0]) == 0).all()
    assert s.trips == [(1, "skip", "peer")]


def test_verdict_bits_roundtrip_and_or():
    bits = [True, False, True, False, False, False, False, False, True]
    assert unpack_bits(pack_bits(bits), len(bits)) == bits
    combined = or_bits([pack_bits([True, False, False]),
                        pack_bits([False, False, True])])
    assert unpack_bits(combined, 3) == [True, False, True]


# -- data-plane chaos units ---------------------------------------------------

def test_chaos_grammar_accepts_data_kinds():
    plan = parse_chaos_spec("nan@rank1:msg3,flipbits@rank0:every4,seed:9")
    assert [r.describe() for r in plan.rules] == [
        "nan@rank1:msg3", "flipbits@rank0:every4"]
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec("nan@rank1")  # missing trigger
    with pytest.raises(ChaosSpecError):
        parse_chaos_spec("flipbits@relaunch:1")  # not a refuse scope


def test_data_faults_fire_on_batch_ordinals_deterministically():
    def run():
        inj = ChaosInjector(
            parse_chaos_spec("nan@rank0:msg2,flipbits@rank0:msg3"), 0)
        buf = np.arange(4, dtype=np.float32)
        events = []
        for _ in range(4):
            inj.begin_batch()
            b = inj.on_reduce_input(buf)
            o = inj.on_reduce_output(np.array(buf))
            events.append((bool(np.isnan(b).any()),
                           not np.array_equal(o, buf)))
        return events, list(inj.events)

    first, events1 = run()
    second, events2 = run()
    assert first == second  # bit-identical replay
    assert first == [(False, False), (True, False), (False, True),
                     (False, False)]
    assert events1 == events2 == [("nan", 2), ("flipbits", 3)]


def test_flipbits_stays_finite_and_nan_respects_dtype():
    inj = ChaosInjector(parse_chaos_spec("flipbits@rank0:every1"), 0)
    buf = np.arange(1.0, 5.0, dtype=np.float32)
    inj.begin_batch()
    out = inj.on_reduce_output(buf)
    assert not np.array_equal(out, buf)
    assert np.isfinite(out).all()  # the SILENT corruption class
    # nan never fires into an integer wire, and records no phantom event
    inj2 = ChaosInjector(parse_chaos_spec("nan@rank0:every1"), 0)
    inj2.begin_batch()
    ints = inj2.on_reduce_input(np.arange(4, dtype=np.int32))
    np.testing.assert_array_equal(ints, np.arange(4, dtype=np.int32))
    assert inj2.events == []


def test_data_and_wire_ordinal_domains_are_independent():
    inj = ChaosInjector(
        parse_chaos_spec("drop@rank0:msg2,nan@rank0:msg2"), 0)
    assert inj.has_data_rules()
    # two wire requests, one batch: the wire msg2 arms, the data msg2
    # must NOT (its domain saw only ordinal 1)
    inj.begin_request()
    inj.begin_request()
    assert "drop" in inj._armed
    inj.begin_batch()
    assert "nan" not in inj._armed_data


# -- consensus units ----------------------------------------------------------

def test_accumulator_windows_on_interval():
    acc = DigestAccumulator(2)
    acc.observe_batch(["a"], [np.ones(4, np.float32)])
    assert acc.drain() is None  # window incomplete
    acc.observe_batch(["b"], [np.zeros(4, np.float32)])
    windows = acc.drain()
    assert len(windows) == 1
    ordinal, items = windows[0]
    assert ordinal == 1 and [i[0] for i in items] == ["batch", "batch"]
    assert acc.drain() is None  # drained exactly once


def test_judge_authority_names_exact_outlier_in_two_rank_world():
    good = np.ones(8, np.float32)
    bad = good.copy()
    bad[0] = np.float32(1.0000001)
    auth = ConsensusAuthority(1)
    auth.observe_combine(["g"], good.tobytes())
    judge = ConsensusJudge(2, authority=auth)
    a0, a1 = DigestAccumulator(1), DigestAccumulator(1)
    a0.observe_batch(["g"], [good])
    a1.observe_batch(["g"], [bad])
    assert judge.submit(0, a0.drain()) is None
    assert judge.submit(1, a1.drain()) == ([1], ["g"])


def test_judge_ignores_out_of_phase_authority_items():
    """Mixed data-plane worlds: rank accumulators digest EVERY allreduce
    batch but the authority only sees host-payload combines, so the two
    streams can slip out of phase with matching counts. An authority
    item whose batch names differ from the rank item at that position
    must be IGNORED (rank-majority instead) — never compared, or a
    healthy world aborts on digests of the wrong batches."""
    onchip = np.ones(8, np.float32)  # reduced on-device: authority blind
    hosted = np.full(8, 2.0, np.float32)
    auth = ConsensusAuthority(1)
    # the authority's window 1 carries the HOSTED batch; the ranks'
    # window 1 carries the ONCHIP batch (different names)
    auth.observe_combine(["hosted"], hosted.tobytes())
    judge = ConsensusJudge(2, authority=auth)
    verdict = None
    for rank in range(2):
        acc = DigestAccumulator(1)
        acc.observe_batch(["onchip"], [onchip])
        v = judge.submit(rank, acc.drain())
        verdict = v or verdict
    assert verdict is None  # ranks agree; the stale authority never votes


def test_judge_majority_without_authority():
    good = np.ones(8, np.float32)
    bad = np.zeros(8, np.float32)
    judge = ConsensusJudge(3)
    verdict = None
    for rank, arr in enumerate((good, good, bad)):
        acc = DigestAccumulator(1)
        acc.observe_batch(["t"], [arr])
        v = judge.submit(rank, acc.drain())
        verdict = v or verdict
    assert verdict == ([2], ["t"])


def test_judge_clean_world_no_verdict():
    good = np.ones(8, np.float32)
    auth = ConsensusAuthority(1)
    auth.observe_combine(["g"], good.tobytes())
    judge = ConsensusJudge(2, authority=auth)
    for rank in range(2):
        acc = DigestAccumulator(1)
        acc.observe_batch(["g"], [good])
        assert judge.submit(rank, acc.drain()) is None
    assert judge.mismatches == 0


def test_state_commit_items_compare_rank_vs_rank():
    """elastic.State commit digests join the window as 'state' items;
    diverged committed trees are named even though the coordinator's
    authority stream never saw them."""
    t_good = {"w": np.arange(4, dtype=np.float32), "step": 3}
    t_bad = {"w": np.arange(4, dtype=np.float32) + 1e-6, "step": 3}
    judge = ConsensusJudge(2)
    accs = [DigestAccumulator(1), DigestAccumulator(1)]
    for acc, tree in zip(accs, (t_good, t_bad)):
        # the commit lands mid-window; the next batch closes it — the
        # same deterministic stream position on every rank
        acc.observe_state("elastic.state.commit.3", tree_digest(tree))
        acc.observe_batch(["g"], [np.ones(4, np.float32)])
    assert judge.submit(0, accs[0].drain()) is None
    verdict = judge.submit(1, accs[1].drain())
    assert verdict is not None
    ranks, names = verdict
    assert names == ["elastic.state.commit.3"]
    assert ranks == [0, 1]  # a 2-rank tie has no arbiter off-authority


def test_tree_digest_is_order_insensitive_and_value_sensitive():
    t1 = {"a": np.ones(3, np.float32), "b": 7}
    t2 = {"b": 7, "a": np.ones(3, np.float32)}
    assert tree_digest(t1) == tree_digest(t2)
    t2["a"] = t2["a"] + np.float32(1e-7)
    assert tree_digest(t1) != tree_digest(t2)


# -- sentry verdict RPC over the real controller wire -------------------------

def test_sentry_rpc_or_folds_across_ranks_on_the_real_wire():
    """The end-to-end pin NaN propagation cannot fake: over a REAL
    ControllerService + ControllerClient pair, a rank whose local view
    is CLEAN receives the bad bit its peer submitted — the exchange, not
    the local check, is what makes the verdict collective."""
    import threading

    from horovod_tpu.core.config import Config
    from horovod_tpu.ops.controller import (
        ControllerClient,
        ControllerService,
        make_negotiator,
    )

    secret = b"integrity-test-secret-integrity!"
    cfg = Config()
    service = ControllerService(2, make_negotiator(2, cfg), secret=secret,
                                consensus_interval_steps=0)
    clients = [ControllerClient(("127.0.0.1", service.port),
                                secret=secret, rank=r, timeout_s=10.0)
               for r in range(2)]
    try:
        results = {}

        def exchange(rank, bits):
            results[rank] = clients[rank].sentry(rank, 1, bits)

        threads = [threading.Thread(
            target=exchange,
            args=(r, pack_bits([r == 1])))  # only rank 1 sees it bad
            for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert unpack_bits(results[0], 1) == [True], results
        assert unpack_bits(results[1], 1) == [True], results
    finally:
        for c in clients:
            c.close()
        service.shutdown()


def test_sentry_rpc_config_drift_fails_loudly_not_wedged():
    """A rank whose HOROVOD_GRAD_SENTRY drifted to off never joins the
    verdict exchange; the armed rank's rendezvous must surface a loud
    structured diagnosis within its bound — never a wedge (the repo's
    hang-free escalation contract)."""
    from horovod_tpu.ops.controller import _Rendezvous

    # unit-level: the bounded rendezvous itself (fast timeout)
    rv = _Rendezvous(2)
    with pytest.raises(RuntimeError, match="GRAD_SENTRY"):
        rv.submit(("sentry", 1), 0, b"\x00", lambda s: b"\x00",
                  timeout_s=0.2,
                  timeout_hint="HOROVOD_GRAD_SENTRY must resolve "
                               "identically on every rank")

def _spmd_guarded_sum(poison_shard=None):
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import spmd
    from horovod_tpu.parallel import DATA_AXIS, data_parallel_mesh

    N = 8
    x = np.ones((N, 4), np.float32)
    if poison_shard is not None:
        x[poison_shard, 0] = np.nan
    mesh = data_parallel_mesh()

    def per_shard(x):
        return spmd.allreduce(x, DATA_AXIS, average=False)

    out = jax.jit(shard_map(per_shard, mesh=mesh,
                            in_specs=(P(DATA_AXIS),),
                            out_specs=P(DATA_AXIS)))(jnp.asarray(x))
    return np.asarray(out)


def test_spmd_guard_zeroes_poisoned_reduction(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_GRAD_SENTRY", "skip")
    out = _spmd_guarded_sum(poison_shard=3)
    # one shard's NaN poisons the sum; the guard's collective verdict
    # zeroes the tensor identically on every shard
    assert (out == 0).all()


def test_spmd_guard_passes_clean_reduction(hvd, monkeypatch):
    monkeypatch.setenv("HOROVOD_GRAD_SENTRY", "skip")
    out = _spmd_guarded_sum()
    np.testing.assert_array_equal(out, np.full((8, 4), 8.0, np.float32))


# -- multi-process tier (sorts past the tier-1 truncation point) --------------

def test_mp_sentry_verdicts_are_collective_and_bit_exact():
    """THE acceptance pin (ISSUE 8): with ``nan@rank1`` only, rank 0 and
    rank 1 take the IDENTICAL skip decision on the IDENTICAL step
    ordinal (no world desync), and the final accumulator is bit-exact to
    a clean run that excludes the poisoned step."""
    from horovod_tpu.chaos.matrix import (
        DATA_POISON_ORDINAL,
        run_data_cell,
    )

    cell = run_data_cell(f"nan@rank1:msg{DATA_POISON_ORDINAL}", "skip", 0,
                         "healed")
    assert cell["outcome"] == "healed", cell
    trips = [r["sentry"]["trips"] for r in cell["results"]]
    assert trips[0] == trips[1] == [
        (DATA_POISON_ORDINAL, "skip", "nan")], cell
    # only rank 1 carried the injection (a NaN does propagate through
    # the sum, so identical LOCAL views would also agree here — the
    # fail-open regression is pinned by the `collective` flag below plus
    # test_sentry_rpc_* and the clean-local-view unit)
    events = {r["rank"]: r["chaos_events"] for r in cell["results"]}
    assert events[1] and not events[0], cell
    # every rank's verdict actually rode the exchange: an engine that
    # silently failed open to local-only verdicts cannot pass this
    assert all(r["sentry"]["collective"] for r in cell["results"]), cell


def test_mp_flipbits_escalates_as_consensus_error_naming_rank():
    from horovod_tpu.chaos.matrix import (
        DATA_POISON_ORDINAL,
        run_data_cell,
    )

    cell = run_data_cell(f"flipbits@rank1:msg{DATA_POISON_ORDINAL}",
                         "off", 1, "escalated")
    assert cell["outcome"] == "escalated", cell
    named = [r for r in cell.get("results", [])
             if r.get("error_type") == "ConsensusError"]
    assert named, cell
    assert all(r["consensus_ranks"] == [1] for r in named), cell


def test_mp_clean_world_zero_false_positives():
    from horovod_tpu.chaos.matrix import run_data_cell

    cell = run_data_cell("seed:1", "skip", 1, "healed")
    assert cell["outcome"] == "healed", cell
    for r in cell["results"]:
        assert r["sentry"]["trips"] == [], r
        assert r["sentry"]["checks"] > 0, r


@pytest.mark.slow
@pytest.mark.parametrize("cell_idx", [1, 2, 3])
def test_mp_data_grid_slow(cell_idx):
    """The remaining fault-kind x policy grid cells (zero / warn /
    abort); the skip and consensus cells run in tier-1 above."""
    from horovod_tpu.chaos.matrix import DATA_GRID, run_data_cell

    spec, policy, consensus, expect, codec = DATA_GRID[cell_idx]
    cell = run_data_cell(spec, policy, consensus, expect, codec=codec)
    assert cell["outcome"] == expect, cell
