"""Subset-world churn soak: alternating memberships across lifecycles.

Exercises ``hvd.init(ranks=[...])`` under the same shared-port
succession pressure as the plain re-init soak: subset service creation
(launcher world-rank 0 hosts it even as a NON-member), non-member
self-worlds, rank remapping, and member/non-member teardown ordering.
Count-based: every launcher rank runs the same epoch schedule, so no
cross-world stop agreement is needed (a non-member cannot join a
member-world continue broadcast)."""
import os
import sys

os.environ.pop("JAX_PLATFORMS", None)
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

import horovod_tpu as hvd

ROUNDS = int(os.environ.get("SOAK_ROUNDS", "40"))
world_rank = int(os.environ["HOROVOD_RANK"])
world_size = int(os.environ["HOROVOD_SIZE"])
assert world_size == 4, "schedule below assumes 4 launcher ranks"
SCHEDULE = [
    [0, 1, 2, 3],   # full world
    [0, 1, 2],      # member coordinator host
    [1, 2, 3],      # NON-member coordinator host
    [0, 3],         # sparse pair
    [2, 1],         # reordered pair: list order defines rank mapping
]

for round_no in range(ROUNDS):
    subset = SCHEDULE[round_no % len(SCHEDULE)]
    hvd.init(ranks=subset)
    if world_rank in subset:
        my = subset.index(world_rank)
        assert hvd.rank() == my, (hvd.rank(), my)
        assert hvd.size() == len(subset)
        out = hvd.allreduce(
            np.full((8,), float(world_rank), np.float32),
            average=False, name=f"ssoak.{round_no}")
        np.testing.assert_array_equal(np.asarray(out), float(sum(subset)))
        root = round_no % len(subset)
        b = hvd.broadcast(np.full((4,), float(world_rank), np.float32),
                          root_rank=root, name=f"ssoak.b.{round_no}")
        np.testing.assert_array_equal(np.asarray(b), float(subset[root]))
    else:
        assert hvd.rank() == 0 and hvd.size() == 1
        out = hvd.allreduce(np.full((2,), 5.0, np.float32),
                            average=False, name=f"ssoak.self.{round_no}")
        np.testing.assert_array_equal(np.asarray(out), 5.0)
    hvd.shutdown()

print(f"SSOAK-OK rank {world_rank} rounds={ROUNDS}", flush=True)
os._exit(0)
